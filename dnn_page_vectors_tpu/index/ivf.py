"""IVF inverted-file ANN index over the vector store (docs/ANN.md).

Every retrieval path used to pay O(corpus) per query through
`ops/topk.py:topk_over_store`. This index makes retrieval sublinear the
canonical way (Jegou et al. 2011; Johnson et al. 2017 / faiss): a coarse
k-means quantizer (index/kmeans.py, trained on the MXU over streamed store
shards) partitions the store's rows into `nlist` inverted lists; a query
scores the tiny [nlist, D] centroid matrix on device, gathers only the
rows of its top-`nprobe` lists from the store's memory-mapped shards (int8
codes at stored width — dequant fuses into the re-rank matmul), and
exact-reranks that candidate block with `ops.topk.rerank_candidates`.
Recall-vs-exact is a measured contract (`evals.recall.recall_vs_exact`,
bench `ann_recall_at_10`), not a hope.

Layout (next to the store, same manifest machinery as VectorStore):

  <store>/ivf/manifest.json     nlist, dim, model_step stamp, seed, per-file
                                byte sizes + CRC32s, per-shard posting table,
                                optional "pq" section (m, ksub, opq config)
  <store>/ivf/centroids.npy     [nlist, D] float32 unit-norm centroids
  <store>/ivf/posting_NNNNN.ord.npy   [count] int32 shard-row order, grouped
                                      by centroid (CSR values)
  <store>/ivf/posting_NNNNN.off.npy   [nlist+1] int64 CSR offsets
  <store>/ivf/pq_rotation.npy   [D, D] f32 OPQ rotation       (PQ builds)
  <store>/ivf/pq_codebooks.npy  [m, ksub, dsub] f32 codebooks (PQ builds)
  <store>/ivf/posting_NNNNN.pqc.npy   [count, m] uint8 PQ codes, SHARD ROW
                                      order (gathered through .ord like the
                                      store rows themselves)

Compressed payloads (index/pq.py, docs/ANN.md): a PQ build additionally
trains an OPQ rotation + per-subspace codebooks on the same streamed,
seeded k-means machinery and stores m-byte codes per row. `search` then
runs ADC — per-query lookup tables computed on device, candidates scored
from m-byte codes instead of stored-width rows, a running on-device top-r
per query — and keeps the EXACT re-rank from the store for the final
top-k (only the ~r surviving rows per query are gathered at stored
width), so the recall contract is measured on true scores while the
candidate gather moves ~m bytes/row. `stage_hot` pins the largest lists'
codes (plus their list/id metadata) in device memory so resident lists
skip the per-request host gather entirely; the non-resident tail still
reads the mmap (infer/serve.py wires the budget).

Validity contract (docs/ROBUSTNESS.md semantics): `open()` re-checks the
recorded model step against the store's stamp, the recorded shard table
(index, count) against the store's live one, and every file's bytes+CRC32.
A stale index (ensure_model_step re-stamp, re-embed, shard quarantine)
raises `IndexUnavailable`; a corrupt file is quarantined (renamed aside,
counted in the fault counters) and the index reports unavailable — callers
(SearchService, eval, mine) fall back to the exact brute-force path
per request, visibly, and `cli index` rebuilds.

Live updates (docs/UPDATES.md): a store APPEND (new generation of shards)
makes the recorded table a strict subset of the live one — `update()`
extends the index in O(new shards) by assigning only the unrecorded shards
to the existing centroids and appending their posting files, until the
drift (corpus fraction appended since the last full k-means,
`updates.rebuild_drift`) forces a fresh build. Tombstoned rows stay in
their posting lists; the store's read-time id masking turns them into
dead (-1) candidates the re-rank already drops.
"""
from __future__ import annotations

import json
import math
import os
import time
from typing import Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from dnn_page_vectors_tpu.index.kmeans import assign_store, train_kmeans
from dnn_page_vectors_tpu.index.pq import PQCodec, adc_topr, train_pq
from dnn_page_vectors_tpu.infer.vector_store import crc_file
from dnn_page_vectors_tpu.ops.topk import (
    chunked_topk, rerank_candidates, rerank_positions)
from dnn_page_vectors_tpu.utils import faults, telemetry

DIRNAME = "ivf"
MANIFEST = "manifest.json"


class IndexUnavailable(RuntimeError):
    """The IVF index cannot serve (missing / stale / quarantined). Callers
    catch this and fall back to exact search — it is a routing signal, not
    a crash."""


def index_dir(store) -> str:
    """The LIVE index directory: the store manifest's `index_dir` pointer
    ("ivf" by default). A background rebuild (docs/MAINTENANCE.md) builds
    the next index generation into a sibling dir and flips the pointer
    atomically — readers never observe a half-written index."""
    return os.path.join(store.directory,
                        getattr(store, "index_dirname", DIRNAME))


def auto_nlist(num_vectors: int) -> int:
    """Default list count: ~sqrt(N) (the standard IVF operating point),
    clamped so tiny toy stores still get a few multi-row lists and huge
    stores don't pay a megarow centroid scan."""
    return max(4, min(int(math.isqrt(max(num_vectors, 1))), 65_536,
                      max(num_vectors, 1)))


def _bucket(n: int, lo: int) -> int:
    """Next power of two >= max(n, lo): one compiled shape per octave, so
    varying candidate/query counts don't retrace every call."""
    return 1 << max(int(math.ceil(math.log2(max(n, 1)))), int(lo - 1).bit_length())


def _write_npy(path: str, arr: np.ndarray) -> Tuple[int, int]:
    """Durable seeded-fault-aware array write (the write_shard pattern):
    bytes land + fsync, size+CRC recorded from the written bytes, and the
    post-fsync corruption hook fires AFTER the record — so injected rot is
    caught by the verify gate, not hidden by the writer."""
    plan = faults.active()

    def _w():
        plan.check("index_write")
        np.save(path, arr)
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)

    faults.retry(_w, op="index_write")
    rec = (os.path.getsize(path), crc_file(path))
    plan.corrupt("index_file", path)
    return rec


def _atomic_dump(obj, path: str) -> None:
    plan = faults.active()

    def _dump():
        plan.check("index_write")
        tmp = path + f".tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(obj, f, indent=1, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    faults.retry(_dump, op="index_write")


class IVFIndex:
    def __init__(self, store, manifest: Dict, centroids: np.ndarray,
                 postings: Dict[int, Tuple[np.ndarray, np.ndarray]],
                 pq: Optional[PQCodec] = None):
        self.store = store
        self.manifest = manifest
        self.centroids = centroids                 # [nlist, D] f32
        self._postings = postings                  # {shard: (order, offsets)}
        self._entries = {s["index"]: s for s in store.shards()}
        self._meta = {s["index"]: s for s in manifest["shards"]}
        self._raw: Dict[int, tuple] = {}           # lazy mmap cache
        self._codes: Dict[int, np.ndarray] = {}    # lazy PQ code mmaps
        self._attrs: Dict[int, np.ndarray] = {}    # lazy attr-word arrays
        self._dev_centroids = None
        self.pq = pq                               # OPQ+PQ codec or None
        self._hot = None                           # stage_hot device state
        # total rows per list across shards: candidate accounting without
        # touching the postings at search time
        sizes = np.zeros((self.nlist,), np.int64)
        for _, offsets in postings.values():
            sizes += np.diff(offsets)
        self.list_sizes = sizes
        self.stats = {"searches": 0, "lists_scanned": 0,
                      "candidates_reranked": 0, "gather_bytes": 0,
                      "reranked_rows": 0, "hot_rows_scored": 0,
                      "filter_escalations": 0}
        # windowed per-list popularity table (docs/ANN.md "Popularity
        # tiering"): every search adds its probed-list histogram here,
        # and stage_hot ranks by it — then HALVES it, so the resident
        # hot set tracks the current Zipf head instead of raw list size.
        # Approximate like `stats`: racing increments may drop a count,
        # never corrupt the ranking.
        # graftcheck: off=locks -- approximate telemetry, single array
        # rebind on decay; a lost increment only nudges the ranking
        self.scan_counts = np.zeros((self.nlist,), np.int64)

    # -- identity ----------------------------------------------------------
    @property
    def nlist(self) -> int:
        return int(self.manifest["nlist"])

    @property
    def model_step(self) -> Optional[int]:
        return self.manifest.get("model_step")

    @property
    def imbalance(self) -> float:
        return float(self.manifest.get("imbalance", 0.0))

    @property
    def index_generation(self) -> int:
        """Incremental updates applied since the last full k-means build
        (0 = freshly built; docs/UPDATES.md)."""
        return int(self.manifest.get("index_generation", 0))

    @property
    def pq_m(self) -> int:
        """PQ subspace count — bytes per posting code row (0 =
        uncompressed stored-width postings)."""
        return int((self.manifest.get("pq") or {}).get("m", 0))

    @property
    def hot_rows(self) -> int:
        """Rows resident in the staged hot posting set (0 = not staged)."""
        return 0 if self._hot is None else int(self._hot["rows"])

    # -- build -------------------------------------------------------------
    @staticmethod
    def _balance_assignments(tops: np.ndarray, nlist: int, cap: int
                             ) -> np.ndarray:
        """Deterministic capacity-capped assignment over the FULL row set
        (docs/ANN.md, the balanced-init ROADMAP item): every row starts on
        its best centroid; a list holding more than `cap` rows keeps its
        first `cap` (stable global row order) and spills the rest to each
        row's next-ranked choice, for choices-1 rounds. Rows that exhaust
        their choices stay where they are (soft cap) — recall never
        depends on the cap, only which list a row waits in. `tops` is
        [N, C] ranked centroid choices; returns the final [N] assignment."""
        n, n_choices = tops.shape
        cur = tops[:, 0].copy()
        level = np.zeros((n,), np.int64)
        for _ in range(max(1, n_choices - 1)):
            order = np.argsort(cur, kind="stable")      # group rows by list
            grouped = cur[order]
            starts = np.searchsorted(grouped, np.arange(nlist))
            rank = np.arange(n) - starts[grouped]
            overflow = order[rank >= cap]
            movable = overflow[level[overflow] < n_choices - 1]
            if movable.size == 0:
                break
            level[movable] += 1
            cur[movable] = tops[movable, level[movable]]
        return cur

    @classmethod
    def _assign_postings(cls, d: str, store, mesh, centroids: np.ndarray,
                         entries, chunk: int, balance_cap: int = 0,
                         choices: int = 4):
        """Assign `entries`' rows to `centroids` and persist their CSR
        posting files. Returns (shards_meta, postings, sizes [nlist],
        sizes_raw [nlist]) for exactly those entries — build runs it over
        the whole store, update() over only the new generation's shards.
        With `balance_cap` > 0 the sweep takes each row's top-`choices`
        centroids, rebalances globally (memory O(N * choices) host — the
        opt-in price of the cap), and sizes_raw reports the pre-balance
        first-choice counts so the imbalance delta is measurable."""
        nlist = centroids.shape[0]
        shards_meta = []
        postings: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
        sizes = np.zeros((nlist,), np.int64)
        sizes_raw = np.zeros((nlist,), np.int64)
        nonzero = [e for e in entries if e["count"] > 0]
        per_shard = assign_store(
            store, mesh, centroids, chunk=chunk, entries=nonzero,
            choices=choices if balance_cap > 0 else 1)
        if balance_cap > 0:
            collected = list(per_shard)
            tops = (np.concatenate([a for _, a in collected])
                    if collected else np.zeros((0, choices), np.int32))
            sizes_raw += np.bincount(tops[:, 0], minlength=nlist) \
                if tops.size else 0
            flat = cls._balance_assignments(tops, nlist, balance_cap)
            out, lo = [], 0
            for entry, a in collected:
                out.append((entry, flat[lo: lo + a.shape[0]]))
                lo += a.shape[0]
            per_shard = out
        for entry, assign in per_shard:
            order = np.argsort(assign, kind="stable").astype(np.int32)
            counts = np.bincount(assign, minlength=nlist)
            offsets = np.zeros((nlist + 1,), np.int64)
            offsets[1:] = np.cumsum(counts)
            sizes += counts
            if balance_cap <= 0:
                sizes_raw += counts
            stem = f"posting_{entry['index']:05d}"
            ob, oc = _write_npy(os.path.join(d, stem + ".ord.npy"), order)
            fb, fc = _write_npy(os.path.join(d, stem + ".off.npy"), offsets)
            shards_meta.append({
                "index": entry["index"], "count": int(entry["count"]),
                "ord": stem + ".ord.npy", "off": stem + ".off.npy",
                "bytes": {"ord": ob, "off": fb},
                "crc": {"ord": oc, "off": fc}})
            postings[entry["index"]] = (order, offsets)
        # zero-count shards carry no postings but must stay in the recorded
        # table, or open() would read an honest store change into them
        for entry in entries:
            if entry["count"] == 0:
                shards_meta.append({"index": entry["index"], "count": 0})
        return shards_meta, postings, sizes, sizes_raw

    @staticmethod
    def _encode_codes(d: str, store, codec: PQCodec, shards_meta) -> None:
        """Encode each recorded shard's rows into its PQ code file
        (posting_NNNNN.pqc.npy, shard ROW order — gathered through the
        same .ord indices as the store rows) and extend the shard meta
        in place with the pqc byte/CRC record. Streams one shard at a
        time; update() calls this with only the new shards' meta."""
        entries = {s["index"]: s for s in store.shards()}
        for meta in shards_meta:
            if meta["count"] == 0 or "ord" not in meta:
                continue
            _, vecs = store._load_entry(entries[meta["index"]])
            codes = codec.encode(np.asarray(vecs, np.float32))
            name = f"posting_{meta['index']:05d}.pqc.npy"
            cb, cc = _write_npy(os.path.join(d, name), codes)
            meta["pqc"] = name
            meta["bytes"]["pqc"] = cb
            meta["crc"]["pqc"] = cc

    @classmethod
    def build(cls, store, mesh, nlist: int = 0, iters: int = 8,
              seed: int = 0, chunk: int = 8192,
              sample_per_shard: Optional[int] = None,
              init: str = "kmeans++", balance: float = 0.0,
              pq_m: int = 0, pq_iters: int = 8,
              opq_iters: int = 3,
              dirname: Optional[str] = None) -> "IVFIndex":
        """Train the quantizer, assign every store row, and persist the
        inverted file next to the store (atomic manifest last, so a crash
        mid-build leaves the previous index or none — never a torn one
        that passes verification). `balance` > 0 caps lists at
        ceil(balance * N / nlist) rows during the assignment sweep
        (overflow spills to the row's next-best centroid — docs/ANN.md).
        `pq_m` > 0 additionally trains the OPQ+PQ codec (index/pq.py) and
        persists m-byte codes per row for the ADC search path.

        `dirname` builds into an explicit sibling directory instead of
        the live pointer target — the background rebuilder's
        build-beside-then-flip protocol (docs/MAINTENANCE.md); the
        returned object should be re-opened after the pointer flip."""
        t0 = time.perf_counter()
        N = store.num_vectors
        if N == 0:
            raise ValueError("cannot build an IVF index over an empty store")
        nlist = int(nlist) if nlist and nlist > 0 else auto_nlist(N)
        nlist = min(nlist, N)
        centroids, kstats = train_kmeans(
            store, mesh, nlist, iters=iters, seed=seed, chunk=chunk,
            sample_per_shard=sample_per_shard, init=init)
        cap = (int(math.ceil(float(balance) * N / nlist))
               if balance and balance > 0 else 0)
        codec = None
        pq_stats: Optional[Dict] = None
        if pq_m:
            codec, pq_stats = train_pq(store, int(pq_m), iters=pq_iters,
                                       opq_iters=opq_iters, seed=seed)
        d = (os.path.join(store.directory, dirname) if dirname
             else index_dir(store))
        os.makedirs(d, exist_ok=True)
        cb, cc = _write_npy(os.path.join(d, "centroids.npy"), centroids)
        shards_meta, postings, sizes, sizes_raw = cls._assign_postings(
            d, store, mesh, centroids, store.shards(), chunk,
            balance_cap=cap)
        pq_section = None
        if codec is not None:
            rb, rc = _write_npy(os.path.join(d, "pq_rotation.npy"),
                                codec.rotation)
            kb, kc = _write_npy(os.path.join(d, "pq_codebooks.npy"),
                                codec.codebooks)
            cls._encode_codes(d, store, codec, shards_meta)
            pq_section = {
                **pq_stats,
                "rotation": {"file": "pq_rotation.npy",
                             "bytes": rb, "crc": rc},
                "codebooks": {"file": "pq_codebooks.npy",
                              "bytes": kb, "crc": kc},
            }
        shards_meta.sort(key=lambda s: s["index"])
        imbalance = float(nlist * np.square(sizes, dtype=np.float64).sum()
                          / max(N, 1) ** 2)
        imbalance_raw = float(
            nlist * np.square(sizes_raw, dtype=np.float64).sum()
            / max(N, 1) ** 2)
        manifest = {
            "version": 1, "nlist": nlist, "dim": store.dim,
            "dtype": store.manifest["dtype"],
            "model_step": store.model_step, "seed": int(seed),
            "iters": kstats["iters"], "reseeded": kstats["reseeded"],
            "init": kstats["init"],
            "init_imbalance": kstats["init_imbalance"],
            "num_vectors": int(N), "imbalance": round(imbalance, 4),
            # balanced-assignment record (docs/ANN.md): the cap applied in
            # the final sweep and the first-choice imbalance it improved
            # on (balance_cap 0 = pure argmax; imbalance_raw == imbalance)
            "balance": float(balance), "balance_cap": cap,
            "imbalance_raw": round(imbalance_raw, 4),
            # live-update bookkeeping (docs/UPDATES.md): rows covered by
            # the last full k-means vs rows appended incrementally since —
            # their ratio is the drift that triggers the next full rebuild
            "built_num_vectors": int(N),
            "appended_since_build": 0,
            "index_generation": 0,
            "build_seconds": round(time.perf_counter() - t0, 3),
            "centroids": {"file": "centroids.npy", "bytes": cb, "crc": cc},
            "shards": shards_meta,
        }
        if pq_section is not None:
            manifest["pq"] = pq_section
        _atomic_dump(manifest, os.path.join(d, MANIFEST))
        return cls(store, manifest, centroids, postings, pq=codec)

    # -- incremental update (docs/UPDATES.md) ------------------------------
    @classmethod
    def update(cls, store, mesh, rebuild_drift: float = 0.25,
               nlist: int = 0, iters: int = 8, seed: Optional[int] = None,
               chunk: int = 8192, init: str = "kmeans++",
               defer_rebuild: bool = False
               ) -> Tuple["IVFIndex", Dict]:
        """Bring the persisted index up to date with the store after an
        append: assign ONLY the shards the recorded table doesn't know to
        the EXISTING centroids and append their posting files — O(new
        shards), not O(corpus) — then atomically re-dump the manifest.

        Falls back to a FULL rebuild (fresh k-means) when the existing
        index can't be extended: missing/torn/corrupt files, a model-step
        re-stamp, a recorded shard that changed or vanished (quarantine /
        re-embed), or accumulated drift — the fraction of the corpus
        appended since the last full k-means — crossing `rebuild_drift`
        (stale centroids mis-assign enough new rows to erode recall).

        Returns (index, info) where info["action"] is "noop" |
        "incremental" | "rebuild" plus the decision inputs, so callers
        (SearchService.refresh, cli refresh, bench) can count
        incremental_updates vs full_rebuilds. Raises (IOError etc.) only
        when the write path itself fails — the manifest is untouched then,
        so readers keep the previous index generation.

        PQ config is INHERITED: an index built with compressed payloads
        keeps them — incremental updates encode the new shards' codes
        with the existing rotation/codebooks (O(new shards), same as the
        posting append), and a drift rebuild retrains the codec with the
        recorded m/iters/opq settings. The balance factor is inherited
        the same way, though incremental appends assign new rows by
        plain argmax — the cap re-applies at the next full rebuild.

        `defer_rebuild` moves full rebuilds OFF this caller
        (docs/MAINTENANCE.md): a pure-drift overrun still runs the O(new
        shards) incremental append — new docs stay servable — and flags
        `info["rebuild_pending"]` for the background builder; a
        structural reason (missing/torn/stale index, changed shard table)
        raises IndexUnavailable instead of rebuilding inline, so the
        caller degrades to exact search, visibly, until the background
        rebuild hot-swaps a fresh index generation in."""
        t0 = time.perf_counter()
        d = index_dir(store)
        mpath = os.path.join(d, MANIFEST)

        def _rebuild(reason: str, man: Optional[Dict] = None
                     ) -> Tuple["IVFIndex", Dict]:
            if defer_rebuild:
                raise IndexUnavailable(
                    f"rebuild deferred to the background worker ({reason})")
            pq_cfg = (man or {}).get("pq") or {}
            idx = cls.build(store, mesh, nlist=nlist, iters=iters,
                            seed=0 if seed is None else seed, chunk=chunk,
                            init=init,
                            balance=(man or {}).get("balance", 0.0),
                            pq_m=pq_cfg.get("m", 0),
                            pq_iters=pq_cfg.get("iters", 8),
                            opq_iters=pq_cfg.get("opq_iters", 3))
            faults.count("index_full_rebuilds")
            # lifecycle event (docs/OBSERVABILITY.md): a full rebuild is
            # the expensive transition operators watch for
            telemetry.default_registry().event(
                "ivf_rebuild", {"reason": reason[:200],
                                "nlist": idx.nlist})
            return idx, {"action": "rebuild", "reason": reason,
                         "seconds": round(time.perf_counter() - t0, 3)}

        if not os.path.exists(mpath):
            return _rebuild("no index on disk")
        try:
            with open(mpath) as f:
                man = json.load(f)
        except (json.JSONDecodeError, ValueError):
            return _rebuild("torn index manifest")
        if (man.get("model_step") != store.model_step
                or man.get("dim") != store.dim):
            return _rebuild("model step / dim changed", man)
        live = store.shards()
        live_by_idx = {s["index"]: s["count"] for s in live}
        recorded = {s["index"]: s["count"] for s in man.get("shards", [])}
        if any(recorded.get(i) != c for i, c in live_by_idx.items()
               if i in recorded) or any(i not in live_by_idx
                                        for i in recorded):
            return _rebuild("recorded shards changed (quarantine/re-embed)",
                            man)
        new_entries = [e for e in live if e["index"] not in recorded]
        if not new_entries:
            return (cls.open(store),
                    {"action": "noop",
                     "seconds": round(time.perf_counter() - t0, 3)})
        try:
            cls._verify_files(d, man)      # don't extend corrupt postings
        except IndexUnavailable as e:
            return _rebuild(f"existing index unhealthy ({e})", man)
        total = store.num_vectors
        appended = (int(man.get("appended_since_build", 0))
                    + sum(e["count"] for e in new_entries))
        drift = appended / max(total, 1)
        rebuild_pending = False
        if drift > rebuild_drift:
            if not defer_rebuild:
                return _rebuild(
                    f"drift {drift:.3f} > rebuild_drift {rebuild_drift}",
                    man)
            # deferred: extend anyway (new docs must serve NOW; the stale
            # centroids cost bounded recall until the background rebuild)
            rebuild_pending = True
        centroids = np.asarray(
            np.load(os.path.join(d, man["centroids"]["file"])), np.float32)
        new_meta, _, new_sizes, _ = cls._assign_postings(
            d, store, mesh, centroids, new_entries, chunk)
        if man.get("pq"):
            # incremental CODE append: new shards encode with the existing
            # rotation/codebooks — O(new shards), like the posting append
            codec = PQCodec(
                np.load(os.path.join(d, man["pq"]["rotation"]["file"])),
                np.load(os.path.join(d, man["pq"]["codebooks"]["file"])))
            cls._encode_codes(d, store, codec, new_meta)
        man["shards"] = sorted(man["shards"] + new_meta,
                               key=lambda s: s["index"])
        man["num_vectors"] = int(total)
        man["appended_since_build"] = appended
        man["index_generation"] = int(man.get("index_generation", 0)) + 1
        # imbalance over the FULL posting set: old sizes from the small
        # [nlist+1] offset files, new from the assignment just done
        sizes = new_sizes.astype(np.float64)
        for s in man["shards"]:
            if s["count"] == 0 or s["index"] in {m["index"]
                                                 for m in new_meta}:
                continue
            off = np.load(os.path.join(d, s["off"]))
            sizes += np.diff(off)
        man["imbalance"] = round(
            float(man["nlist"] * np.square(sizes).sum()
                  / max(total, 1) ** 2), 4)
        _atomic_dump(man, mpath)
        faults.count("index_incremental_updates")
        return (cls.open(store, verify=False),
                {"action": "incremental", "new_shards": len(new_entries),
                 "appended_rows": sum(e["count"] for e in new_entries),
                 "drift": round(drift, 4),
                 "rebuild_pending": rebuild_pending,
                 "index_generation": man["index_generation"],
                 "seconds": round(time.perf_counter() - t0, 3)})

    # -- open / verify -----------------------------------------------------
    @classmethod
    def open(cls, store, verify: bool = True) -> "IVFIndex":
        """Load the persisted index, re-checking stamp, shard table, and
        bytes+CRC32. Raises IndexUnavailable (with the reason) on any
        mismatch — corrupt files are quarantined first."""
        d = index_dir(store)
        mpath = os.path.join(d, MANIFEST)
        if not os.path.exists(mpath):
            raise IndexUnavailable(
                f"no IVF index at {d} (run the 'index' command to build)")
        try:
            with open(mpath) as f:
                man = json.load(f)
        except (json.JSONDecodeError, ValueError):
            q = mpath + ".quarantined"
            os.replace(mpath, q)
            faults.count("quarantined_index_manifests")
            faults.warn(f"IVF manifest {mpath} is torn (invalid JSON); "
                        f"moved aside to {q}")
            raise IndexUnavailable(f"torn IVF manifest (quarantined to {q})")
        if man.get("model_step") != store.model_step:
            raise IndexUnavailable(
                f"stale IVF index: built at model step "
                f"{man.get('model_step')}, store is stamped "
                f"{store.model_step} (rebuild after re-embedding)")
        if man.get("dim") != store.dim:
            raise IndexUnavailable(
                f"stale IVF index: built for {man.get('dim')}-d vectors, "
                f"store holds {store.dim}-d")
        live = {s["index"]: s["count"] for s in store.shards()}
        recorded = {s["index"]: s["count"] for s in man.get("shards", [])}
        if live != recorded:
            raise IndexUnavailable(
                "stale IVF index: store shard table changed since the "
                f"build ({len(recorded)} recorded vs {len(live)} live "
                "shards or row counts differ); rebuild")
        if verify:
            cls._verify_files(d, man)
        plan = faults.active()
        centroids = np.load(os.path.join(d, man["centroids"]["file"]))
        postings: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
        for s in man["shards"]:
            if s["count"] == 0:
                continue
            plan.check("index_read")
            postings[s["index"]] = (
                np.load(os.path.join(d, s["ord"])),
                np.load(os.path.join(d, s["off"])))
        codec = None
        if man.get("pq"):
            codec = PQCodec(
                np.load(os.path.join(d, man["pq"]["rotation"]["file"])),
                np.load(os.path.join(d, man["pq"]["codebooks"]["file"])))
        return cls(store, man, np.asarray(centroids, np.float32), postings,
                   pq=codec)

    @staticmethod
    def _verify_files(d: str, man: Dict) -> None:
        files = [(man["centroids"]["file"], man["centroids"]["bytes"],
                  man["centroids"]["crc"])]
        for key in ("rotation", "codebooks"):
            rec = man.get("pq", {}).get(key)
            if rec is not None:
                files.append((rec["file"], rec["bytes"], rec["crc"]))
        for s in man["shards"]:
            if s["count"] == 0:
                continue
            for key in ("ord", "off") + (("pqc",) if "pqc" in s else ()):
                files.append((s[key], s["bytes"][key], s["crc"][key]))
        for name, want_bytes, want_crc in files:
            path = os.path.join(d, name)
            err = None
            if not os.path.exists(path):
                err = "missing"
            elif os.path.getsize(path) != want_bytes:
                err = (f"{os.path.getsize(path)} bytes, manifest records "
                       f"{want_bytes} (truncated?)")
            elif crc_file(path) != want_crc:
                err = "CRC mismatch (corrupt)"
            if err is None:
                continue
            if err != "missing":
                os.replace(path, path + ".quarantined")
                faults.count("quarantined_index_files")
                faults.warn(f"quarantined IVF index file {path} ({err}); "
                            "exact search serves until a rebuild")
            raise IndexUnavailable(
                f"IVF index file {name} {err}; rebuild the index")

    # -- partitioned serving (infer/partition.py, docs/SCALING.md) ---------
    def partition_view(self, shard_indices) -> "IVFIndex":
        """A serving view of this index restricted to one partition's
        shard range: the same manifest, centroids, and PQ codec, but ONLY
        the listed shards' posting files — so `search` gathers candidates
        from exactly the partition's slice of the inverted file and
        `stage_hot` pins only its rows. The centroid scan stays global
        (the [nlist, D] matrix is tiny and identical everywhere); the
        per-list candidate accounting (`list_sizes`, `stats`) is fresh
        and partition-local. Mmap caches are lazy per view, so a
        partition never touches a sibling's shard files."""
        keep = {int(s) for s in shard_indices}
        return IVFIndex(self.store, self.manifest, self.centroids,
                        {s: p for s, p in self._postings.items()
                         if s in keep}, pq=self.pq)

    # -- search ------------------------------------------------------------
    def _shard_raw(self, sidx: int):
        raw = self._raw.get(sidx)
        if raw is None:
            raw = self._raw[sidx] = self.store._load_entry(
                self._entries[sidx], raw=True)
        return raw

    def _codes_raw(self, sidx: int) -> np.ndarray:
        arr = self._codes.get(sidx)
        if arr is None:
            arr = self._codes[sidx] = np.load(
                os.path.join(index_dir(self.store),
                             self._meta[sidx]["pqc"]), mmap_mode="r")
        return arr

    def _shard_attrs(self, sidx: int) -> np.ndarray:
        """One shard's packed attribute words (uint32 [count]; zeros for
        shards written before the store's attribute table existed) —
        the filtered-retrieval prefilter's input (index/attrs.py)."""
        arr = self._attrs.get(sidx)
        if arr is None:
            arr = self._attrs[sidx] = self.store.load_attrs(
                self._entries[sidx])
        return arr

    def _gather_codes(self, cents: np.ndarray, predicate=None):
        """Candidate block for one probed-list union at CODE width: m
        bytes per row off the mmap'd pqc files instead of the stored row
        width. Returns (codes [C, m] u8, page_ids [C] i64, cand_cent [C]
        i32, src_shard [C] i32, src_row [C] i32) — the source coordinates
        let the exact re-rank fetch only the ADC survivors' rows later.
        Tombstoned rows get centroid -2 (matches no probed list), the
        same dead-slot convention as _gather. A `predicate`
        (index/attrs.py) prefilters each shard's posting rows against its
        attribute words BEFORE the code gather, so a filtered query moves
        selectivity-proportional bytes instead of post-filtering top-k."""
        c_parts, i_parts, n_parts, sh_parts, rw_parts = [], [], [], [], []
        for sidx in sorted(self._postings):
            order, offsets = self._postings[sidx]
            rows = [order[offsets[c]: offsets[c + 1]] for c in cents]
            lens = np.array([r.shape[0] for r in rows], np.int64)
            if lens.sum() == 0:
                continue
            take = np.concatenate(rows)
            cent = np.repeat(np.asarray(cents, np.int32), lens)
            if predicate is not None:
                keep = predicate.matches(self._shard_attrs(sidx)[take])
                if not keep.any():
                    continue
                take, cent = take[keep], cent[keep]
            ids, _, _ = self._shard_raw(sidx)
            taken_ids = np.asarray(ids[take], np.int64)
            c_parts.append(np.asarray(self._codes_raw(sidx)[take]))
            i_parts.append(taken_ids)
            n_parts.append(np.where(taken_ids >= 0, cent, np.int32(-2)))
            sh_parts.append(np.full((take.shape[0],), sidx, np.int32))
            rw_parts.append(take.astype(np.int32))
        if not c_parts:
            return (np.zeros((0, self.pq.m), np.uint8),
                    np.zeros((0,), np.int64), np.zeros((0,), np.int32),
                    np.zeros((0,), np.int32), np.zeros((0,), np.int32))
        return tuple(np.concatenate(p) for p in
                     (c_parts, i_parts, n_parts, sh_parts, rw_parts))

    def _fetch_rows(self, src_shard: np.ndarray, src_row: np.ndarray):
        """Stored-width rows (+ int8 scales) for an explicit (shard, row)
        set — the exact re-rank's gather: only the per-query ADC
        survivors pay row-width bytes off the store mmaps."""
        U = src_shard.shape[0]
        rows = None
        scales = None
        for sidx in np.unique(src_shard):
            _, vecs, scl = self._shard_raw(int(sidx))
            mask = src_shard == sidx
            part = np.asarray(vecs[src_row[mask]])
            if rows is None:
                rows = np.zeros((U, part.shape[1]), part.dtype)
            rows[mask] = part
            if scl is not None:
                if scales is None:
                    scales = np.zeros((U,), np.float16)
                scales[mask] = np.asarray(scl[src_row[mask]])
        return rows, scales

    # -- HBM-resident hot posting set (docs/ANN.md, infer/serve.py) --------
    def stage_hot(self, budget_bytes: float) -> Dict:
        """Pin the largest posting lists' PQ codes — plus the per-row list
        ids the ADC mask needs and the page-id / source tables the re-rank
        needs — in device memory, biggest lists first until `budget_bytes`
        runs out. Resident lists then score against the staged codes with
        ZERO per-request host gather; non-resident lists keep the mmap
        path, and results are identical either way (test-pinned,
        tests/test_pq.py). Tombstones are masked at staging time (dead
        rows get centroid -2), so restaging follows the same refresh
        cadence as the serving HBM shards."""
        if self.pq is None:
            raise ValueError("stage_hot needs a PQ index (build with pq_m)")
        per_row = self.pq.m + 4                 # code bytes + centroid id
        resident = np.zeros((self.nlist,), bool)
        used = 0
        # popularity-driven ranking (docs/ANN.md "Popularity tiering"):
        # with measured probe counts, pin the HOTTEST lists (size breaks
        # ties, deterministically); a cold table — fresh build, restart —
        # degrades to the original biggest-first order. The table is
        # halved after ranking, so each restage sees a decayed window of
        # recent traffic, not all-time totals.
        counts = np.asarray(self.scan_counts)
        by_popularity = bool(counts.sum() > 0)
        if by_popularity:
            order = np.lexsort((-self.list_sizes, -counts))
        else:
            order = np.argsort(-self.list_sizes, kind="stable")
        self.scan_counts = counts >> 1
        for c in order:
            need = int(self.list_sizes[c]) * per_row
            if self.list_sizes[c] == 0 or used + need > budget_bytes:
                continue                        # smaller lists may still fit
            resident[int(c)] = True
            used += need
        cents = np.nonzero(resident)[0]
        codes, ids, cent, sh, rw = self._gather_codes(cents)
        n = codes.shape[0]
        if n == 0:
            self._hot = None
            return {"hot_lists": 0, "hot_rows": 0, "hot_bytes": 0,
                    "hot_by_popularity": by_popularity}
        # per-row attribute words ride along so a filtered query can mask
        # resident rows ON DEVICE (index/attrs.py matches_device) instead
        # of forcing hot lists back onto the host gather path
        words = np.zeros((n,), np.uint32)
        for sidx in np.unique(sh):
            m_ = sh == sidx
            words[m_] = self._shard_attrs(int(sidx))[rw[m_]]
        pad = _bucket(n, lo=512)
        if pad > n:
            codes = np.concatenate(
                [codes, np.zeros((pad - n, self.pq.m), np.uint8)])
            cent = np.concatenate([cent, np.full((pad - n,), -1, np.int32)])
            words = np.concatenate([words, np.zeros((pad - n,), np.uint32)])
        self._hot = {
            "lists": resident, "rows": n, "bytes": used,
            "codes": jnp.asarray(codes), "cent": jnp.asarray(cent),
            "attrs": jnp.asarray(words),
            "chunk": min(2048, pad), "ids": ids, "shard": sh, "row": rw}
        return {"hot_lists": int(resident.sum()), "hot_rows": n,
                "hot_bytes": used, "hot_by_popularity": by_popularity}

    def _gather(self, cents: np.ndarray, predicate=None):
        """Candidate block for one probed-list union: rows of every listed
        centroid across every shard, at STORED width (int8 codes / fp16
        rows straight off the mmap — the rerank matmul widens on device).
        Returns (vecs [C, D], scales [C]|None, page_ids [C] i64,
        cand_cent [C] i32). Tombstoned rows (id -1 after the store's
        read-time masking, docs/UPDATES.md) get centroid -2 — matching no
        probed list — so a dead vector can never OCCUPY a top-k slot, not
        merely be filtered after winning one. A `predicate`
        (index/attrs.py) drops non-matching rows against the shard's
        attribute words BEFORE the row gather — the filtered path's
        scan-byte reduction happens exactly here."""
        v_parts, s_parts, i_parts, c_parts = [], [], [], []
        for sidx in sorted(self._postings):
            order, offsets = self._postings[sidx]
            rows = [order[offsets[c]: offsets[c + 1]] for c in cents]
            lens = np.array([r.shape[0] for r in rows], np.int64)
            if lens.sum() == 0:
                continue
            take = np.concatenate(rows)
            cent = np.repeat(cents.astype(np.int32), lens)
            if predicate is not None:
                keep = predicate.matches(self._shard_attrs(sidx)[take])
                if not keep.any():
                    continue
                take, cent = take[keep], cent[keep]
            ids, vecs, scl = self._shard_raw(sidx)
            taken_ids = np.asarray(ids[take], np.int64)
            v_parts.append(np.asarray(vecs[take]))
            i_parts.append(taken_ids)
            if scl is not None:
                s_parts.append(np.asarray(scl[take]))
            c_parts.append(np.where(taken_ids >= 0, cent, np.int32(-2)))
        if not v_parts:
            return (np.zeros((0, self.store.dim), np.float16), None,
                    np.zeros((0,), np.int64), np.zeros((0,), np.int32))
        return (np.concatenate(v_parts),
                np.concatenate(s_parts) if s_parts else None,
                np.concatenate(i_parts), np.concatenate(c_parts))

    def search(self, qvecs: np.ndarray, k: int, nprobe: Optional[int] = None,
               block: int = 256, rerank: Optional[int] = None,
               predicate=None, escalate: float = 4.0
               ) -> Tuple[np.ndarray, np.ndarray, Dict[str, int]]:
        """ANN top-k: (scores [Nq, k] f32, page_ids [Nq, k] i64 -1-padded,
        stats) — see _search_once for the scoring machinery. `predicate`
        (index/attrs.py Predicate) restricts results to matching rows,
        intersected with the posting gathers BEFORE any candidate bytes
        move. A filtered probe set can under-fill k (the matching rows
        may live in un-probed lists): `escalate` > 1 re-searches the
        under-filled queries with nprobe multiplied per round until they
        fill or the probe set reaches nlist — the drain-more-lists
        escalation, counted in stats["filter_escalations"]."""
        out_s, out_i, stats = self._search_once(
            qvecs, k, nprobe=nprobe, block=block, rerank=rerank,
            predicate=predicate)
        if predicate is None or not escalate or escalate <= 1:
            return out_s, out_i, stats
        np_eff = int(min(max(1, nprobe or 1), self.nlist))
        k = int(min(k, max(out_i.shape[1], 1)))
        while np_eff < self.nlist:
            need = (out_i >= 0).sum(axis=1) < k
            if not need.any():
                break
            np_eff = int(min(self.nlist,
                             max(np_eff + 1, math.ceil(np_eff * escalate))))
            s2, i2, st2 = self._search_once(
                np.asarray(qvecs, np.float32)[need], k, nprobe=np_eff,
                block=block, rerank=rerank, predicate=predicate)
            out_s[need], out_i[need] = s2, i2
            n_esc = int(need.sum())
            stats["filter_escalations"] = (
                stats.get("filter_escalations", 0) + n_esc)
            self.stats["filter_escalations"] = (
                self.stats.get("filter_escalations", 0) + n_esc)
            telemetry.default_registry().counter(
                "ivf.filter_escalations").inc(n_esc)
            for key in ("lists_scanned", "candidates_reranked",
                        "gather_bytes", "reranked_rows", "hot_rows_scored"):
                if key in st2:
                    stats[key] = stats.get(key, 0) + st2[key]
        return out_s, out_i, stats

    def _search_once(self, qvecs: np.ndarray, k: int,
                     nprobe: Optional[int] = None, block: int = 256,
                     rerank: Optional[int] = None, predicate=None
                     ) -> Tuple[np.ndarray, np.ndarray, Dict[str, int]]:
        """One ANN pass: (scores [Nq, k] f32, page_ids [Nq, k] i64
        -1-padded, stats). Centroid scoring runs on device through
        `chunked_topk` (queries padded to a power-of-two bucket, one
        compiled program per octave); queries are then processed in
        `block`-sized sub-blocks — per sub-block ONE gathered candidate
        matmul via `rerank_candidates`, dispatched async so sub-block
        i+1's host gather overlaps sub-block i's device re-rank.

        On a PQ index (manifest "pq" section) the sub-blocks route
        through the ADC path instead (_search_adc): candidates score from
        m-byte codes, and only each query's top-`rerank` ADC survivors
        (default max(8k, 64)) are gathered at stored width for the exact
        final top-k. stats["gather_bytes"] reports the store payload
        bytes either path actually moved — with a `predicate`, the
        posting rows it rejects are dropped before the gather, so this
        number falls in proportion to selectivity."""
        qvecs = np.asarray(qvecs, np.float32)
        nq = qvecs.shape[0]
        k = int(k)
        out_s = np.full((nq, k), -np.inf, np.float32)
        out_i = np.full((nq, k), -1, np.int64)
        if nq == 0:
            return out_s, out_i, {}
        nprobe = int(min(max(1, nprobe or 1), self.nlist))
        if self._dev_centroids is None:
            self._dev_centroids = jnp.asarray(self.centroids)
        qb = _bucket(nq, lo=8)
        qpad = np.concatenate(
            [qvecs, np.zeros((qb - nq, qvecs.shape[1]), np.float32)]) \
            if qb > nq else qvecs
        _, sel = chunked_topk(jnp.asarray(qpad), self._dev_centroids,
                              k=nprobe, chunk=8192)
        sel = np.asarray(sel, np.int32)[:nq]
        # feed the popularity table: one count per (query, probed list).
        # bincount over the flat selection is one vectorized pass — the
        # per-search cost of popularity tiering is this line.
        self.scan_counts += np.bincount(sel.ravel(), minlength=self.nlist)
        stats = {"searches": nq, "lists_scanned": nq * nprobe,
                 "candidates_reranked":
                     int(self.list_sizes[sel].sum()),
                 "gather_bytes": 0}
        # index-level instruments (docs/OBSERVABILITY.md): windowed search
        # rate + probe volume regardless of which service routed here
        reg = telemetry.default_registry()
        reg.counter("ivf.searches",
                    window_s=telemetry.DEFAULT_WINDOW_S).inc(nq)
        reg.counter("ivf.lists_scanned").inc(nq * nprobe)
        if self.pq is not None:
            return self._search_adc(qvecs, sel, k, block, rerank,
                                    out_s, out_i, stats,
                                    predicate=predicate)
        pending = []
        for s in range(0, nq, block):
            e = min(s + block, nq)
            sel_b = sel[s:e]
            cents = np.unique(sel_b)
            cand, scl, cids, ccent = self._gather(cents,
                                                  predicate=predicate)
            C = cand.shape[0]
            stats["gather_bytes"] += C * self.store.row_bytes
            if C == 0:
                pending.append((s, e, None, None))
                continue
            cp = _bucket(C, lo=max(512, k))
            if cp > C:
                cand = np.concatenate(
                    [cand, np.zeros((cp - C, cand.shape[1]), cand.dtype)])
                ccent = np.concatenate(
                    [ccent, np.full((cp - C,), -1, np.int32)])
                if scl is not None:
                    scl = np.concatenate(
                        [scl, np.zeros((cp - C,), scl.dtype)])
            # pow-2 query bucket: a lone serve bucket of 8 must not pad to
            # the full mining block width (32x wasted matmul rows)
            bq = min(_bucket(e - s, lo=8), _bucket(block, lo=8))
            qblk = qvecs[s:e]
            if bq > e - s:
                qblk = np.concatenate(
                    [qblk, np.zeros((bq - (e - s), qvecs.shape[1]),
                                    np.float32)])
                sel_b = np.concatenate(
                    [sel_b, np.full((bq - (e - s), nprobe), -1, np.int32)])
            packed = rerank_candidates(
                jnp.asarray(qblk), jnp.asarray(cand),
                None if scl is None else jnp.asarray(scl),
                jnp.asarray(ccent), jnp.asarray(sel_b), k)
            pending.append((s, e, packed, cids))
        for s, e, packed, cids in pending:
            if packed is None:
                continue
            top_s, pos = (np.asarray(packed[0]), np.asarray(packed[1]))
            top_s, pos = top_s[: e - s], pos[: e - s]
            kk = pos.shape[1]
            out_i[s:e, :kk] = np.where(
                pos >= 0, cids[np.clip(pos, 0, None)], -1)
            out_s[s:e, :kk] = np.where(pos >= 0, top_s, -np.inf)
        for key, val in stats.items():
            self.stats[key] = self.stats.get(key, 0) + val
        return out_s, out_i, stats

    def _search_adc(self, qvecs: np.ndarray, sel: np.ndarray, k: int,
                    block: int, rerank: Optional[int],
                    out_s: np.ndarray, out_i: np.ndarray, stats: Dict,
                    predicate=None
                    ) -> Tuple[np.ndarray, np.ndarray, Dict[str, int]]:
        """The compressed-payload block loop (docs/ANN.md): per sub-block,
        gather the probed lists' m-byte CODES (mmap — resident lists skip
        the gather entirely and score against the staged device codes),
        compute per-query ADC lookup tables on device (`pq.lut`), run the
        running top-r over code scores (`adc_topr`, masked per query to
        its probed lists), then fetch ONLY the union of per-query
        survivors' rows at stored width and exact re-rank them
        (`rerank_positions`) for the final top-k. ADC ties and the
        survivor cut are deterministic (stable sorts, lax.top_k)."""
        nq = qvecs.shape[0]
        nprobe = sel.shape[1]
        r = max(int(rerank) if rerank else max(8 * k, 64), k)
        hot = self._hot
        m = self.pq.m
        for s in range(0, nq, block):
            e = min(s + block, nq)
            sel_b = sel[s:e]
            cents = np.unique(sel_b)
            cold_cents = (cents[~hot["lists"][cents]] if hot is not None
                          else cents)
            codes, cids, ccent, csh, crw = self._gather_codes(
                cold_cents, predicate=predicate)
            C = codes.shape[0]
            stats["gather_bytes"] += C * m
            # pow-2 query bucket (same rule as the uncompressed path)
            bq = min(_bucket(e - s, lo=8), _bucket(block, lo=8))
            qblk = qvecs[s:e]
            sel_pad = sel_b
            if bq > e - s:
                qblk = np.concatenate(
                    [qblk, np.zeros((bq - (e - s), qvecs.shape[1]),
                                    np.float32)])
                sel_pad = np.concatenate(
                    [sel_b, np.full((bq - (e - s), nprobe), -1, np.int32)])
            q_dev = jnp.asarray(qblk)
            lut = self.pq.lut(q_dev)
            sel_dev = jnp.asarray(sel_pad)
            parts = []            # (scores, page ids, src shard, src row)
            if C:
                cp = _bucket(C, lo=512)
                if cp > C:
                    codes = np.concatenate(
                        [codes, np.zeros((cp - C, m), np.uint8)])
                    ccent = np.concatenate(
                        [ccent, np.full((cp - C,), -1, np.int32)])
                cs, cpos = adc_topr(lut, jnp.asarray(codes),
                                    jnp.asarray(ccent), sel_dev, r=r,
                                    chunk=min(2048, cp))
                cs, cpos = np.asarray(cs), np.asarray(cpos)
                # a PADDING query (probed set all -1) "matches" padding
                # candidates (cent -1): clip + mask so its garbage rows
                # never reach the union gather
                ok = (cpos >= 0) & (cpos < C)
                idx = np.clip(cpos, 0, C - 1)
                parts.append((np.where(ok, cs, -np.inf),
                              np.where(ok, cids[idx], -1),
                              np.where(ok, csh[idx], -1),
                              np.where(ok, crw[idx], -1)))
            if hot is not None and hot["rows"]:
                # filtered queries mask resident rows ON DEVICE: attribute
                # words staged next to the codes, one and+compare per
                # predicate alternative, non-matching rows -> centroid -2
                # (matches no probed list) before the ADC scan
                hcent = hot["cent"]
                if predicate is not None:
                    hcent = jnp.where(
                        predicate.matches_device(hot["attrs"]),
                        hcent, jnp.int32(-2))
                hs, hpos = adc_topr(lut, hot["codes"], hcent,
                                    sel_dev, r=r, chunk=hot["chunk"])
                hs, hpos = np.asarray(hs), np.asarray(hpos)
                ok = (hpos >= 0) & (hpos < hot["rows"])
                idx = np.clip(hpos, 0, hot["rows"] - 1)
                parts.append((np.where(ok, hs, -np.inf),
                              np.where(ok, hot["ids"][idx], -1),
                              np.where(ok, hot["shard"][idx], -1),
                              np.where(ok, hot["row"][idx], -1)))
                res = hot["lists"][sel_b]
                stats["hot_rows_scored"] = stats.get(
                    "hot_rows_scored", 0) + int(
                        self.list_sizes[sel_b][res].sum())
            if not parts:
                continue                        # out stays -inf / -1
            scores = np.concatenate([p[0] for p in parts], axis=1)
            pids = np.concatenate([p[1] for p in parts], axis=1)
            shm = np.concatenate([p[2] for p in parts], axis=1)
            rwm = np.concatenate([p[3] for p in parts], axis=1)
            if scores.shape[1] > r:             # merge hot + cold survivors
                ordx = np.argsort(-scores, axis=1, kind="stable")[:, :r]
                take = lambda a: np.take_along_axis(a, ordx, axis=1)  # noqa: E731
                scores, pids = take(scores), take(pids)
                shm, rwm = take(shm), take(rwm)
            ok = np.isfinite(scores) & (pids >= 0)
            ok[e - s:] = False                  # padding queries: no gather
            key = np.where(
                ok, shm.astype(np.int64) * (1 << 32) + rwm.astype(np.int64),
                np.int64(-1))
            uniq = np.unique(key[ok])
            if uniq.size == 0:
                continue
            rows, scl = self._fetch_rows(
                (uniq >> 32).astype(np.int32),
                (uniq & 0xFFFFFFFF).astype(np.int32))
            stats["gather_bytes"] += int(uniq.size) * self.store.row_bytes
            stats["reranked_rows"] = stats.get(
                "reranked_rows", 0) + int(ok[: e - s].sum())
            U = uniq.size
            up = _bucket(U, lo=max(64, k))
            if up > U:
                rows = np.concatenate(
                    [rows, np.zeros((up - U, rows.shape[1]), rows.dtype)])
                if scl is not None:
                    scl = np.concatenate(
                        [scl, np.zeros((up - U,), scl.dtype)])
            pos = np.where(ok, np.searchsorted(uniq, key), -1).astype(
                np.int32)
            uids = np.full((up,), -1, np.int64)
            uids[pos[ok]] = pids[ok]            # union row -> page id
            top_s, top_pos = rerank_positions(
                q_dev, jnp.asarray(rows),
                None if scl is None else jnp.asarray(scl),
                jnp.asarray(pos), k)
            top_s = np.asarray(top_s)[: e - s]
            top_pos = np.asarray(top_pos)[: e - s]
            kk = top_pos.shape[1]
            out_i[s:e, :kk] = np.where(
                top_pos >= 0, uids[np.clip(top_pos, 0, None)], -1)
            out_s[s:e, :kk] = np.where(top_pos >= 0, top_s, -np.inf)
        for key_, val in stats.items():
            self.stats[key_] = self.stats.get(key_, 0) + val
        return out_s, out_i, stats
