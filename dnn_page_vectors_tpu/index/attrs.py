"""Per-row attribute words and the filtered-retrieval predicate language.

Production page-vector traffic is segmented — by language, site, and
recency — and post-filtering top-k silently breaks the recall contract at
low selectivity (docs/ANN.md "Filtered retrieval"). This module is the
substrate the whole filtered path shares:

  * one packed little-endian ``uint32`` attribute word per corpus row,
    bit-field layout **versioned in the store manifest** (``ATTRS_VERSION``)
    and written through the same CRC-recording shard writers as vectors
    (infer/vector_store.py), so appends, compaction, and migration all
    carry attributes for free;
  * a tiny predicate grammar — ``lang==X``, ``site in {...}``,
    ``recency>=B``, and ``&`` conjunctions — that compiles to
    (mask, value) word tests evaluated with ONE bitwise-and + compare per
    alternative, identically on host (numpy, posting-gather prefilter) and
    on device (jnp, the staged hot-set ADC mask);
  * a canonical normal form (sorted terms, sorted set members, buckets
    resolved) whose rendered text doubles as the wire encoding
    (infer/transport.py ``FLAG_FILTERS``) and the result-cache key
    component — two spellings of the same filter hash identically.

Everything here is pure and deterministic: site strings map to buckets via
CRC32, no clocks, no RNG, no I/O.
"""
from __future__ import annotations

import re
import zlib
from typing import Iterable, Optional, Sequence, Tuple, Union

import numpy as np

# ---------------------------------------------------------------------------
# Attribute word layout (version 1)
#
#   bits  0..7   language id        (0..255)
#   bits  8..23  site-hash bucket   (0..65535)
#   bits 24..27  recency band       (0..15, higher = fresher)
#   bits 28..31  reserved, must be zero
# ---------------------------------------------------------------------------

ATTRS_VERSION = 1
ATTR_DTYPE = np.dtype("<u4")          # one little-endian word per row

LANG_SHIFT, LANG_BITS = 0, 8
SITE_SHIFT, SITE_BITS = 8, 16
REC_SHIFT, REC_BITS = 24, 4

LANG_MAX = (1 << LANG_BITS) - 1
SITE_MAX = (1 << SITE_BITS) - 1
REC_MAX = (1 << REC_BITS) - 1

_LANG_MASK = LANG_MAX << LANG_SHIFT
_SITE_MASK = SITE_MAX << SITE_SHIFT
_REC_MASK = REC_MAX << REC_SHIFT


class FilterError(ValueError):
    """A predicate string failed to parse or a field value is out of range."""


def site_bucket(site: Union[str, int]) -> int:
    """Map a site name to its hash bucket (CRC32 mod 2^16, deterministic).

    Integers pass through as explicit bucket ids so tests and tools can
    address buckets directly."""
    if isinstance(site, (int, np.integer)):
        b = int(site)
        if not 0 <= b <= SITE_MAX:
            raise FilterError(f"site bucket {b} out of range 0..{SITE_MAX}")
        return b
    return zlib.crc32(str(site).encode("utf-8")) & SITE_MAX


def pack_word(lang: int = 0, site: Union[str, int] = 0,
              recency: int = 0) -> int:
    """Pack one attribute word. `site` may be a name (hashed) or bucket."""
    lang = int(lang)
    recency = int(recency)
    if not 0 <= lang <= LANG_MAX:
        raise FilterError(f"lang {lang} out of range 0..{LANG_MAX}")
    if not 0 <= recency <= REC_MAX:
        raise FilterError(f"recency {recency} out of range 0..{REC_MAX}")
    return ((lang << LANG_SHIFT) | (site_bucket(site) << SITE_SHIFT)
            | (recency << REC_SHIFT))


def pack_words(lang, site, recency) -> np.ndarray:
    """Vectorized pack: arrays (or scalars, broadcast) -> uint32 words."""
    lang = np.asarray(lang, np.uint32)
    site = np.asarray(site, np.uint32)
    recency = np.asarray(recency, np.uint32)
    if lang.size and int(lang.max(initial=0)) > LANG_MAX:
        raise FilterError(f"lang out of range 0..{LANG_MAX}")
    if site.size and int(site.max(initial=0)) > SITE_MAX:
        raise FilterError(f"site bucket out of range 0..{SITE_MAX}")
    if recency.size and int(recency.max(initial=0)) > REC_MAX:
        raise FilterError(f"recency out of range 0..{REC_MAX}")
    out = ((lang << LANG_SHIFT) | (site << SITE_SHIFT)
           | (recency << REC_SHIFT))
    return np.ascontiguousarray(out, ATTR_DTYPE)


def unpack_word(word: int) -> Tuple[int, int, int]:
    """Inverse of pack_word -> (lang, site_bucket, recency)."""
    w = int(word)
    return ((w & _LANG_MASK) >> LANG_SHIFT,
            (w & _SITE_MASK) >> SITE_SHIFT,
            (w & _REC_MASK) >> REC_SHIFT)


# ---------------------------------------------------------------------------
# Predicate language
#
# Grammar (whitespace-tolerant):
#   predicate := term ('&' term)*
#   term      := 'lang' '==' INT
#              | 'site' 'in' '{' member (',' member)* '}'
#              | 'recency' '>=' INT
#   member    := INT | NAME          (names hash through site_bucket)
#
# A term compiles to a disjunction of (mask, value) word tests; the
# predicate matches a row when EVERY term has at least one alternative
# with (word & mask) == value. `recency>=B` unrolls to one alternative
# per band B..15 so the evaluator needs no ordered comparison.
# ---------------------------------------------------------------------------

MAX_PREDICATE_BYTES = 512         # wire-decode hard cap (reject fuzz)
_MAX_TERMS = 16
_MAX_SET_MEMBERS = 64

_LANG_RE = re.compile(r"^lang\s*==\s*(\d+)$")
_REC_RE = re.compile(r"^recency\s*>=\s*(\d+)$")
_SITE_RE = re.compile(r"^site\s+in\s+\{([^{}]*)\}$")
_NAME_RE = re.compile(r"^[A-Za-z0-9_.:\-]+$")

Alts = Tuple[Tuple[int, int], ...]          # ((mask, value), ...)


class Predicate:
    """A compiled, canonicalized filter predicate.

    Immutable; equality/hash follow the canonical `text`, so two spellings
    of the same filter are one cache-key and one wire encoding."""

    __slots__ = ("text", "conjuncts", "_masks", "_values")

    def __init__(self, text: str, conjuncts: Tuple[Alts, ...]):
        self.text = text
        self.conjuncts = conjuncts
        # flattened per-conjunct arrays for the vectorized evaluators
        self._masks = tuple(
            np.asarray([m for m, _ in alts], ATTR_DTYPE)
            for alts in conjuncts)
        self._values = tuple(
            np.asarray([v for _, v in alts], ATTR_DTYPE)
            for alts in conjuncts)

    # -- construction -------------------------------------------------------

    @classmethod
    def parse(cls, text: str) -> "Predicate":
        """Parse + canonicalize. Raises FilterError on anything malformed."""
        if not isinstance(text, str):
            raise FilterError("predicate must be a string")
        if len(text.encode("utf-8")) > MAX_PREDICATE_BYTES:
            raise FilterError(
                f"predicate longer than {MAX_PREDICATE_BYTES} bytes")
        terms = [t.strip() for t in text.split("&")]
        if not terms or any(not t for t in terms):
            raise FilterError(f"empty term in predicate {text!r}")
        if len(terms) > _MAX_TERMS:
            raise FilterError(f"more than {_MAX_TERMS} terms")
        parsed = []                      # (sort_key, canonical_term, alts)
        for term in terms:
            m = _LANG_RE.match(term)
            if m:
                lang = int(m.group(1))
                if lang > LANG_MAX:
                    raise FilterError(
                        f"lang {lang} out of range 0..{LANG_MAX}")
                parsed.append(((0, lang, ()), f"lang=={lang}",
                               ((_LANG_MASK, lang << LANG_SHIFT),)))
                continue
            m = _REC_RE.match(term)
            if m:
                band = int(m.group(1))
                if band > REC_MAX:
                    raise FilterError(
                        f"recency band {band} out of range 0..{REC_MAX}")
                alts = tuple((_REC_MASK, b << REC_SHIFT)
                             for b in range(band, REC_MAX + 1))
                parsed.append(((1, band, ()), f"recency>={band}", alts))
                continue
            m = _SITE_RE.match(term)
            if m:
                raw = [s.strip() for s in m.group(1).split(",")]
                if not raw or any(not s for s in raw):
                    raise FilterError(f"empty member in {term!r}")
                if len(raw) > _MAX_SET_MEMBERS:
                    raise FilterError(
                        f"more than {_MAX_SET_MEMBERS} site members")
                buckets = set()
                for s in raw:
                    if s.isdigit():
                        buckets.add(site_bucket(int(s)))
                    elif _NAME_RE.match(s):
                        buckets.add(site_bucket(s))
                    else:
                        raise FilterError(f"bad site member {s!r}")
                ordered = tuple(sorted(buckets))
                canon = "site in {%s}" % ",".join(str(b) for b in ordered)
                alts = tuple((_SITE_MASK, b << SITE_SHIFT) for b in ordered)
                parsed.append(((2, 0, ordered), canon, alts))
                continue
            raise FilterError(f"cannot parse predicate term {term!r}")
        # canonical: sorted unique terms; conjunction semantics unchanged
        parsed.sort(key=lambda p: p[0])
        seen = set()
        canon_terms, conjuncts = [], []
        for _, canon, alts in parsed:
            if canon in seen:
                continue
            seen.add(canon)
            canon_terms.append(canon)
            conjuncts.append(alts)
        return cls("&".join(canon_terms), tuple(conjuncts))

    # -- evaluation ---------------------------------------------------------

    def matches(self, words: np.ndarray) -> np.ndarray:
        """Host evaluation: uint32 words [N] -> bool [N]."""
        words = np.asarray(words, ATTR_DTYPE)
        ok = np.ones(words.shape, bool)
        for masks, values in zip(self._masks, self._values):
            ok &= ((words[..., None] & masks) == values).any(axis=-1)
        return ok

    def matches_device(self, words):
        """Device evaluation: jnp uint32 words -> jnp bool, same tests as
        `matches` (one and+compare per alternative) so host prefilter and
        on-device hot-set mask agree bit for bit."""
        import jax.numpy as jnp
        ok = jnp.ones(words.shape, bool)
        for masks, values in zip(self._masks, self._values):
            hit = (words & int(masks[0])) == int(values[0])
            for mask, val in zip(masks[1:], values[1:]):
                hit = hit | ((words & int(mask)) == int(val))
            ok = ok & hit
        return ok

    # -- wire / identity ----------------------------------------------------

    def encode(self) -> bytes:
        """Wire bytes: the canonical utf-8 text (decode re-parses it)."""
        return self.text.encode("utf-8")

    def __eq__(self, other):
        return isinstance(other, Predicate) and other.text == self.text

    def __hash__(self):
        return hash(self.text)

    def __repr__(self):
        return f"Predicate({self.text!r})"


def decode_predicate(data: bytes) -> Predicate:
    """Inverse of Predicate.encode; FilterError on malformed bytes."""
    if len(data) > MAX_PREDICATE_BYTES:
        raise FilterError("predicate field too long")
    try:
        text = bytes(data).decode("utf-8")
    except UnicodeDecodeError as e:
        raise FilterError(f"predicate not utf-8: {e}") from None
    return Predicate.parse(text)


def compile_filters(spec: Union[None, str, Predicate]) -> Optional[Predicate]:
    """Normalize a user-facing `filters` argument: None/"" pass through as
    None (unfiltered), strings parse, Predicates return as-is."""
    if spec is None:
        return None
    if isinstance(spec, Predicate):
        return spec
    if isinstance(spec, str):
        if not spec.strip():
            return None
        return Predicate.parse(spec)
    raise FilterError(f"filters must be a string or Predicate, "
                      f"got {type(spec).__name__}")


def parse_attr_assignments(pairs: Iterable[str]) -> int:
    """`lang=3 site=wiki.org recency=2` (cli append --attrs) -> packed word.

    Unknown keys and out-of-range values raise FilterError with the
    offending token in the message."""
    lang, site, recency = 0, 0, 0
    for tok in pairs:
        if "=" not in tok:
            raise FilterError(f"bad --attrs token {tok!r} (want key=value)")
        key, _, val = tok.partition("=")
        key, val = key.strip(), val.strip()
        if not val:
            raise FilterError(f"empty value in --attrs token {tok!r}")
        if key == "lang":
            if not val.isdigit():
                raise FilterError(f"lang must be an integer, got {val!r}")
            lang = int(val)
        elif key == "site":
            site = int(val) if val.isdigit() else val
        elif key == "recency":
            if not val.isdigit():
                raise FilterError(f"recency must be an integer, got {val!r}")
            recency = int(val)
        else:
            raise FilterError(f"unknown --attrs key {key!r} "
                              "(want lang/site/recency)")
    return pack_word(lang=lang, site=site, recency=recency)


def attrs_manifest_section() -> dict:
    """The manifest stanza recorded when a store's attribute table is
    initialized; readers reject unknown layout versions."""
    return {"version": ATTRS_VERSION, "dtype": str(ATTR_DTYPE.name),
            "fields": {"lang": [LANG_SHIFT, LANG_BITS],
                       "site": [SITE_SHIFT, SITE_BITS],
                       "recency": [REC_SHIFT, REC_BITS]}}


def check_attrs_section(section: dict) -> None:
    """Validate a manifest attrs stanza; raises FilterError on drift."""
    ver = int(section.get("version", -1))
    if ver != ATTRS_VERSION:
        raise FilterError(
            f"unsupported attrs layout version {ver} "
            f"(this build speaks {ATTRS_VERSION})")
