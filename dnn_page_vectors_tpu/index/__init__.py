"""IVF ANN index subsystem (docs/ANN.md).

`kmeans.py` trains the coarse quantizer (nlist centroids) on the MXU by
streaming vector-store shards through the mesh; `ivf.py` persists the
inverted file next to the store and serves sublinear `search(q, k, nprobe)`
with an exact on-device re-rank. Every retrieval caller (serve, eval, mine)
falls back to the exact brute-force path (`ops/topk.py`) when the index is
missing, stale, or quarantined.
"""
from dnn_page_vectors_tpu.index.ivf import IndexUnavailable, IVFIndex  # noqa: F401
from dnn_page_vectors_tpu.index.kmeans import train_kmeans  # noqa: F401
