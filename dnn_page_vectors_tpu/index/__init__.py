"""IVF ANN index subsystem (docs/ANN.md).

`kmeans.py` trains the coarse quantizer (nlist centroids) on the MXU by
streaming vector-store shards through the mesh — and the grouped
per-subspace Euclidean variant that trains PQ codebooks; `pq.py` is the
OPQ+PQ codec (rotation + codebooks, device encode/LUT/ADC kernels);
`ivf.py` persists the inverted file next to the store and serves
sublinear `search(q, k, nprobe)` — stored-width gather + exact re-rank,
or, on PQ builds, m-byte code gather + on-device ADC with the exact
re-rank kept for the final top-k. Every retrieval caller (serve, eval,
mine) falls back to the exact brute-force path (`ops/topk.py`) when the
index is missing, stale, or quarantined.
"""
from dnn_page_vectors_tpu.index.ivf import IndexUnavailable, IVFIndex  # noqa: F401
from dnn_page_vectors_tpu.index.kmeans import train_kmeans  # noqa: F401
from dnn_page_vectors_tpu.index.pq import PQCodec, auto_pq_m, train_pq  # noqa: F401
