"""Model layer: encoder zoo + two-tower wrapper + contrastive losses.

Every encoder maps token ids -> a [B, out_dim] page/query vector and is a
pure flax module: `init` / `apply` only, static shapes, compute dtype
bfloat16 so matmuls and convs land on the MXU (SURVEY.md §2 layer 2).
"""
from dnn_page_vectors_tpu.models.factory import build_two_tower
from dnn_page_vectors_tpu.models.two_tower import TwoTower
from dnn_page_vectors_tpu.models.losses import cosine_contrastive_loss

__all__ = ["build_two_tower", "TwoTower", "cosine_contrastive_loss"]
