"""Encoder-zoo factory: ModelConfig -> TwoTower module (SURVEY.md §3 #5-9)."""
from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

from dnn_page_vectors_tpu.config import Config
from dnn_page_vectors_tpu.models.cdssm import CdssmEncoder
from dnn_page_vectors_tpu.models.kim_cnn import KimCnnEncoder
from dnn_page_vectors_tpu.models.lstm import LstmEncoder
from dnn_page_vectors_tpu.models.transformer import TransformerEncoder
from dnn_page_vectors_tpu.models.two_tower import TwoTower

_DTYPES = {"bfloat16": jnp.bfloat16, "float32": jnp.float32}


def _build_encoder(cfg: Config, vocab_size: int, name: str,
                   mesh: Optional[Any] = None) -> nn.Module:
    m = cfg.model
    dtype = _DTYPES[m.dtype]
    if m.encoder == "cdssm":
        return CdssmEncoder(vocab_size=vocab_size, embed_dim=m.embed_dim,
                            conv_width=m.conv_widths[0],
                            conv_channels=m.conv_channels, out_dim=m.out_dim,
                            dtype=dtype, name=name)
    if m.encoder == "kim_cnn":
        return KimCnnEncoder(vocab_size=vocab_size, embed_dim=m.embed_dim,
                             conv_widths=m.conv_widths,
                             conv_channels=m.conv_channels, out_dim=m.out_dim,
                             dropout=m.dropout, dtype=dtype, name=name)
    if m.encoder == "lstm":
        return LstmEncoder(vocab_size=vocab_size, embed_dim=m.embed_dim,
                           hidden_dim=m.model_dim, num_layers=m.num_layers,
                           out_dim=m.out_dim, dropout=m.dropout,
                           dtype=dtype, name=name)
    if m.encoder in ("bert", "t5"):
        if m.attention not in ("dense", "flash", "ring"):
            raise ValueError(f"unknown attention kind {m.attention!r} "
                             "(want dense | flash | ring)")
        max_len = max(cfg.data.query_len, cfg.data.page_len)
        return TransformerEncoder(vocab_size=vocab_size,
                                  num_layers=m.num_layers,
                                  num_heads=m.num_heads,
                                  model_dim=m.model_dim, mlp_dim=m.mlp_dim,
                                  out_dim=m.out_dim, max_len=max_len,
                                  dropout=m.dropout, variant=m.encoder,
                                  attention_kind=m.attention,
                                  mesh=mesh if m.attention == "ring" else None,
                                  dtype=dtype, name=name)
    raise ValueError(f"unknown encoder {cfg.model.encoder!r}")


def build_two_tower(cfg: Config, vocab_size: int,
                    mesh: Optional[Any] = None) -> TwoTower:
    """Both towers share one tokenizer vocab (query/page differ only in
    length), so one vocab_size parameterises both. `mesh` is only needed for
    model.attention == 'ring' (sequence parallelism)."""
    query_tower = _build_encoder(cfg, vocab_size, "query_tower", mesh)
    page_tower = _build_encoder(cfg, vocab_size, "page_tower", mesh)
    return TwoTower(query_tower=query_tower, page_tower=page_tower,
                    shared=cfg.model.shared_towers,
                    temperature_init=cfg.train.temperature_init)
