"""CDSSM char-trigram Conv1D encoder (SURVEY.md §3 #5; BASELINE.json:5,7).

The classic CDSSM feeds a ~30k-dim letter-trigram count vector per word into
a Conv1D. On TPU that sparse one-hot layout is hostile to the MXU, so the
trigram hash ids [B, L, K] are embedded and summed per word (embedding-bag —
a dense gather+reduce XLA handles well), then a word-window Conv1D + tanh +
masked global max-pool + projection produce the page/query vector, which is
the same function the reference computes.
"""
from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class CdssmEncoder(nn.Module):
    vocab_size: int            # trigram hash buckets + 1 (0 = pad)
    embed_dim: int = 128
    conv_width: int = 3
    conv_channels: int = 256
    out_dim: int = 128
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, ids: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        # ids: [B, L, K] hashed trigram ids, 0 = pad.
        tg_mask = (ids > 0).astype(self.dtype)[..., None]          # [B, L, K, 1]
        emb = nn.Embed(self.vocab_size, self.embed_dim, dtype=self.dtype,
                       name="trigram_embed")(ids)                  # [B, L, K, E]
        word = (emb * tg_mask).sum(axis=2)                         # [B, L, E]
        word_mask = (ids > 0).any(axis=-1)                         # [B, L]

        h = nn.Conv(self.conv_channels, kernel_size=(self.conv_width,),
                    padding="SAME", dtype=self.dtype, name="conv")(word)
        h = jnp.tanh(h)                                            # [B, L, C]
        neg_inf = jnp.asarray(-1e9, self.dtype)
        h = jnp.where(word_mask[..., None], h, neg_inf)
        pooled = h.max(axis=1)                                     # [B, C]
        # all-pad rows (empty text) pool to -1e9; zero them out
        any_word = word_mask.any(axis=1, keepdims=True)
        pooled = jnp.where(any_word, pooled, jnp.zeros_like(pooled))
        out = nn.Dense(self.out_dim, dtype=self.dtype, name="proj")(pooled)
        return jnp.tanh(out).astype(jnp.float32)                   # [B, D]
