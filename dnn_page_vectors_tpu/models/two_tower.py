"""Two-tower wrapper: query tower + page tower + learnable logit scale
(SURVEY.md §3 #9; BASELINE.json:5,9).

Towers are any encoder from the zoo. `shared=True` ties the weights (one
tower applied to both sides); otherwise towers are independent, matching the
reference's separate query/page encoders. The logit scale is a learnable
log-inverse-temperature for the cosine-contrastive loss, clamped at apply
time for stability.
"""
from __future__ import annotations

from typing import Any, Callable

import flax.linen as nn
import jax.numpy as jnp
import numpy as np


class TwoTower(nn.Module):
    query_tower: nn.Module
    page_tower: nn.Module         # ignored (aliased) when shared=True
    shared: bool = False
    temperature_init: float = 20.0

    def setup(self) -> None:
        self.log_scale = self.param(
            "log_scale",
            lambda rng: jnp.asarray(np.log(self.temperature_init), jnp.float32))

    def _page_enc(self) -> nn.Module:
        return self.query_tower if self.shared else self.page_tower

    def encode_query(self, ids: jnp.ndarray,
                     deterministic: bool = True) -> jnp.ndarray:
        return self.query_tower(ids, deterministic=deterministic)

    def encode_page(self, ids: jnp.ndarray,
                    deterministic: bool = True,
                    seg: jnp.ndarray | None = None,
                    pos: jnp.ndarray | None = None,
                    nseg: int = 0) -> jnp.ndarray:
        """[R, L] ids -> [R, D], or — with a packed row's segment mask
        `seg` [R, L] (+ per-segment local positions `pos`, see
        data/loader.py pack_segments) — [R, nseg, D]: one vector per
        packed page, attention and pooling never crossing segments."""
        if seg is None:
            return self._page_enc()(ids, deterministic=deterministic)
        return self._page_enc()(ids, deterministic=deterministic,
                                seg=seg, pos=pos, nseg=nseg)

    def scale(self) -> jnp.ndarray:
        return jnp.minimum(jnp.exp(self.log_scale), 100.0)

    def __call__(self, query_ids: jnp.ndarray, page_ids: jnp.ndarray,
                 neg_page_ids: jnp.ndarray | None = None,
                 deterministic: bool = True,
                 page_seg: jnp.ndarray | None = None,
                 page_pos: jnp.ndarray | None = None):
        """Returns (q_vec [B,D], p_vec [B,D], neg_vec [B,H,D] | None, scale).

        With `page_seg` (sequence packing, train.pack_pages): `page_ids`
        is [R, L] packed rows carrying B = query_ids.shape[0] pages total
        (pack = B / R consecutive pages per row); the page tower returns
        [R, pack, D] per-segment vectors, flattened back to [B, D] in the
        same page order the unpacked batch would have produced."""
        q = self.encode_query(query_ids, deterministic)
        if page_seg is not None:
            B = query_ids.shape[0]
            R = page_ids.shape[0]
            assert B % R == 0, (B, R)
            p = self.encode_page(page_ids, deterministic, seg=page_seg,
                                 pos=page_pos, nseg=B // R)
            p = p.reshape(B, p.shape[-1])
        else:
            p = self.encode_page(page_ids, deterministic)
        neg = None
        if neg_page_ids is not None:
            B, H = neg_page_ids.shape[:2]
            flat = neg_page_ids.reshape((B * H,) + neg_page_ids.shape[2:])
            neg = self._page_enc()(flat, deterministic=deterministic)
            neg = neg.reshape(B, H, -1)
        return q, p, neg, self.scale()
