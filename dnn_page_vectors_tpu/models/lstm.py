"""Bidirectional LSTM word encoder (SURVEY.md §1 [PRIOR]: the public
dnn_page_vectors lineage ships LSTM page encoders alongside its CNNs; no
reference file is citable — empty mount, SURVEY.md §0 — so this follows the
standard masked-BiLSTM text-encoder shape behind the same TwoTower interface
as the rest of the zoo).

TPU-first layout: an LSTM's only true serial dependency is the recurrent
h @ U matmul, so the input projection for ALL timesteps is hoisted out of
the recurrence into one [B, L, E] @ [E, 4H] matmul that tiles onto the MXU,
and the `lax.scan` over time carries just the [B, H] @ [H, 4H] step. Gate
math runs in float32 regardless of the module dtype: the carry crosses
hundreds of sequential steps, where bfloat16 rounding compounds (unlike one
matmul accumulation, which the MXU already does in f32). Padding (id 0)
carries (h, c) through unchanged, so the forward scan ends at the state of
the last real token and the reversed scan at the first — page content past
the mask can never leak into the vector (tests/test_models.py padding
invariance).
"""
from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp


def _lstm_pass(x_proj: jnp.ndarray, mask: jnp.ndarray, u: jnp.ndarray,
               reverse: bool) -> tuple[jnp.ndarray, jnp.ndarray]:
    """One direction over time. x_proj: [B, L, 4H] (input projection + bias,
    float32), mask: [B, L] bool, u: [H, 4H] recurrent weights. Returns
    (final hidden state [B, H], per-step hidden states [B, L, H])."""
    B = x_proj.shape[0]
    H = u.shape[0]
    h0 = jnp.zeros((B, H), jnp.float32)
    c0 = jnp.zeros((B, H), jnp.float32)

    def step(carry, inp):
        h, c = carry
        xp, m = inp                                   # [B, 4H], [B]
        gates = xp + jnp.dot(h, u, preferred_element_type=jnp.float32)
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        # +1 forget-gate bias: the standard init that keeps early gradients
        # flowing through long pages.
        c_new = jax.nn.sigmoid(f + 1.0) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
        h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
        m = m[:, None]
        return (jnp.where(m, h_new, h), jnp.where(m, c_new, c)), \
            jnp.where(m, h_new, h)

    xs = (jnp.moveaxis(x_proj, 1, 0), jnp.moveaxis(mask, 1, 0))
    (h, _c), hs = jax.lax.scan(step, (h0, c0), xs, reverse=reverse)
    return h, jnp.moveaxis(hs, 0, 1)


class LstmEncoder(nn.Module):
    """Stacked BiLSTM over word embeddings; encoding = concat of both
    directions' final states -> Dense projection. hidden size = model_dim,
    depth = num_layers (shared knobs with the transformer family)."""
    vocab_size: int
    embed_dim: int = 256
    hidden_dim: int = 256
    num_layers: int = 1
    out_dim: int = 256
    dropout: float = 0.1
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, ids: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        # ids: [B, L] word ids, 0 = pad.
        mask = ids > 0                                            # [B, L]
        x = nn.Embed(self.vocab_size, self.embed_dim, dtype=self.dtype,
                     name="word_embed")(ids)                      # [B, L, E]
        H = self.hidden_dim
        finals = []
        for layer in range(self.num_layers):
            last = layer == self.num_layers - 1
            outs = []
            for tag, rev in (("fwd", False), ("bwd", True)):
                # The bulk matmul ([B*L, E_in] @ [E_in, 4H]) runs in module
                # dtype on the MXU; the serial gate math stays f32 (above).
                xp = nn.Dense(4 * H, dtype=self.dtype,
                              name=f"in_proj{layer}_{tag}")(x)
                u = self.param(f"rec{layer}_{tag}",
                               nn.initializers.orthogonal(), (H, 4 * H),
                               jnp.float32)
                h_final, hs = _lstm_pass(xp.astype(jnp.float32), mask, u, rev)
                outs.append(hs)
                if last:
                    finals.append(h_final)
            if last:
                break  # only the final states feed the encoding
            x = jnp.concatenate(outs, axis=-1).astype(self.dtype)  # [B, L, 2H]
            x = nn.Dropout(self.dropout)(x, deterministic=deterministic)
        h = jnp.concatenate(finals, axis=-1)                       # [B, 2H]
        any_word = mask.any(axis=1, keepdims=True)
        h = jnp.where(any_word, h, jnp.zeros_like(h))
        h = nn.Dropout(self.dropout)(h, deterministic=deterministic)
        out = nn.Dense(self.out_dim, dtype=self.dtype, name="proj")(
            h.astype(self.dtype))
        return out.astype(jnp.float32)                             # [B, D]
