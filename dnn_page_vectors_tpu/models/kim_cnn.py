"""Kim-CNN word-level encoder (SURVEY.md §3 #6; BASELINE.json:8).

Multi-width Conv1D banks over word embeddings with masked global max-pool
and concatenation — the Kim (2014) text-CNN shape. All conv widths run as
separate `nn.Conv`s over the same [B, L, E] activations; XLA fuses the
elementwise tails and keeps the convs on the MXU.
"""
from __future__ import annotations

from typing import Tuple

import flax.linen as nn
import jax.numpy as jnp


class KimCnnEncoder(nn.Module):
    vocab_size: int
    embed_dim: int = 256
    conv_widths: Tuple[int, ...] = (3, 4, 5)
    conv_channels: int = 256
    out_dim: int = 256
    dropout: float = 0.1
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, ids: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        # ids: [B, L] word ids, 0 = pad.
        mask = ids > 0                                             # [B, L]
        x = nn.Embed(self.vocab_size, self.embed_dim, dtype=self.dtype,
                     name="word_embed")(ids)                       # [B, L, E]
        neg_inf = jnp.asarray(-1e9, self.dtype)
        pools = []
        for w in self.conv_widths:
            h = nn.Conv(self.conv_channels, kernel_size=(w,), padding="SAME",
                        dtype=self.dtype, name=f"conv{w}")(x)
            h = nn.relu(h)
            h = jnp.where(mask[..., None], h, neg_inf)
            pools.append(h.max(axis=1))                            # [B, C]
        h = jnp.concatenate(pools, axis=-1)                        # [B, C * n]
        any_word = mask.any(axis=1, keepdims=True)
        h = jnp.where(any_word, h, jnp.zeros_like(h))
        h = nn.Dropout(self.dropout)(h, deterministic=deterministic)
        out = nn.Dense(self.out_dim, dtype=self.dtype, name="proj")(h)
        return out.astype(jnp.float32)                             # [B, D]
