"""Cosine-contrastive loss with global in-batch + ANN-mined hard negatives
(SURVEY.md §3 #10; BASELINE.json:5,9,10).

TPU-first note on distribution: this loss is written as *global-batch* math.
Under jit with the batch sharded over the mesh 'data' axis, the q @ p.T
similarity needs every page vector on every shard, so GSPMD inserts the
all-gather (and the corresponding reduce-scatter in the backward pass) over
ICI automatically — the gradient-correct global in-batch negatives that
torch-DDP's NCCL hooks provided the reference (SURVEY.md §7 "hard parts")
fall out of the partitioner with no user collective code.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import optax


def l2_normalize(x: jnp.ndarray, eps: float = 1e-6) -> jnp.ndarray:
    x = x.astype(jnp.float32)
    return x * jax.lax.rsqrt((x * x).sum(-1, keepdims=True) + eps)


def cosine_contrastive_loss(
    q: jnp.ndarray,                       # [B, D] query vectors
    p: jnp.ndarray,                       # [B, D] gold page vectors
    scale: jnp.ndarray,                   # scalar inverse temperature
    neg: Optional[jnp.ndarray] = None,    # [B, H, D] mined hard negatives
    symmetric: bool = True,
) -> Tuple[jnp.ndarray, dict]:
    """Softmax contrastive loss over cosine similarities.

    Row i's positives are the diagonal; its negatives are every other
    in-batch page (global batch under GSPMD) plus, if given, all B*H mined
    hard negatives. `symmetric=True` adds the page->query direction (only
    over the in-batch block — mined negatives have no query side).
    """
    qn = l2_normalize(q)
    pn = l2_normalize(p)
    logits = scale * (qn @ pn.T)                                   # [B, B]
    if neg is not None:
        B = q.shape[0]
        nn_ = l2_normalize(neg.reshape(-1, neg.shape[-1]))         # [B*H, D]
        extra = scale * (qn @ nn_.T)                               # [B, B*H]
        logits_qp = jnp.concatenate([logits, extra], axis=1)       # [B, B+BH]
    else:
        logits_qp = logits
    labels = jnp.arange(q.shape[0])
    loss_qp = optax.softmax_cross_entropy_with_integer_labels(
        logits_qp, labels).mean()
    if symmetric:
        loss_pq = optax.softmax_cross_entropy_with_integer_labels(
            logits.T, labels).mean()
        loss = 0.5 * (loss_qp + loss_pq)
    else:
        loss = loss_qp
    in_batch_acc = (logits_qp.argmax(axis=1) == labels).mean()
    return loss, {"loss": loss, "in_batch_acc": in_batch_acc,
                  "scale": scale}
