"""Cosine-contrastive loss with global in-batch + ANN-mined hard negatives
(SURVEY.md §3 #10; BASELINE.json:5,9,10).

TPU-first note on distribution: this loss is written as *global-batch* math.
Under jit with the batch sharded over the mesh 'data' axis, the q @ p.T
similarity needs every page vector on every shard, so GSPMD inserts the
all-gather (and the corresponding reduce-scatter in the backward pass) over
ICI automatically — the gradient-correct global in-batch negatives that
torch-DDP's NCCL hooks provided the reference (SURVEY.md §7 "hard parts")
fall out of the partitioner with no user collective code.

Two implementations of the same math (parity pinned by
tests/test_losses_fused.py):
  * dense (default) — materializes the [B, B(1+H)] logits; simple, fine
    while the logits fit HBM next to the activations.
  * chunked/fused (`chunk` > 0, train.loss_chunk) — streams query chunks
    against the (GSPMD-gathered) global page pool, computing logits +
    log-sum-exp + the gradient contribution one [chunk, B(1+H)] tile at a
    time under jax.checkpoint, so live logits memory is O(chunk * pool)
    instead of O(B * pool) in forward AND backward. This is what lets the
    effective in-batch negative pool scale with the global batch instead
    of with the largest square matrix HBM can hold.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import optax


def l2_normalize(x: jnp.ndarray, eps: float = 1e-6) -> jnp.ndarray:
    x = x.astype(jnp.float32)
    return x * jax.lax.rsqrt((x * x).sum(-1, keepdims=True) + eps)


def _chunk_stats(rows: jnp.ndarray, pool: jnp.ndarray, labels: jnp.ndarray,
                 scale: jnp.ndarray, chunk: int):
    """Per-row softmax-CE statistics of `rows` scored against `pool`,
    `chunk` rows at a time: returns (lse [N], pos [N], correct [N]).

    This is the fused path's core: each lax.map step materializes only a
    [chunk, M] logits tile in fp32 (M = pool rows), takes its log-sum-exp,
    positive logit, and argmax hit, and drops it — the full [N, M]
    similarity matrix never exists, in forward OR backward.
    `jax.checkpoint` on the chunk body keeps the scan from saving each
    tile as a residual: the backward pass recomputes the tile from the
    (tiny) [chunk, D] inputs, so live logits memory stays O(chunk * M)
    end to end. The softmax-CE value is exactly `lse - pos`, so the math
    (and therefore the gradients autodiff derives) matches the dense
    optax.softmax_cross_entropy_with_integer_labels to fp32 rounding.
    """
    n = rows.shape[0]
    if n % chunk:
        raise ValueError(
            f"loss chunk {chunk} must divide the (per-direction) row count "
            f"{n}: pick train.loss_chunk dividing train.batch_size")
    nch = n // chunk

    @jax.checkpoint
    def one(pair):
        rb, lb = pair
        logits = scale * (rb @ pool.T)                  # [chunk, M] f32
        lse = jax.nn.logsumexp(logits, axis=1)
        pos = jnp.take_along_axis(logits, lb[:, None], axis=1)[:, 0]
        correct = jnp.argmax(logits, axis=1) == lb
        return lse, pos, correct

    lse, pos, corr = jax.lax.map(
        one, (rows.reshape(nch, chunk, rows.shape[-1]),
              labels.reshape(nch, chunk)))
    return lse.reshape(n), pos.reshape(n), corr.reshape(n)


def cosine_contrastive_loss(
    q: jnp.ndarray,                       # [B, D] query vectors
    p: jnp.ndarray,                       # [B, D] gold page vectors
    scale: jnp.ndarray,                   # scalar inverse temperature
    neg: Optional[jnp.ndarray] = None,    # [B, H, D] mined hard negatives
    symmetric: bool = True,
    chunk: int = 0,
) -> Tuple[jnp.ndarray, dict]:
    """Softmax contrastive loss over cosine similarities.

    Row i's positives are the diagonal; its negatives are every other
    in-batch page (global batch under GSPMD) plus, if given, all B*H mined
    hard negatives. `symmetric=True` adds the page->query direction (only
    over the in-batch block — mined negatives have no query side).

    `chunk` > 0 selects the fused/chunked implementation
    (train.loss_chunk): query rows are scored against the full negative
    pool `chunk` rows at a time, with logits + log-sum-exp + the gradient
    contribution computed per tile — the full [B, B(1+H)] similarity
    matrix is never materialized in forward or backward, so the in-batch
    negative pool can grow to whatever the *vectors* (not the logits) fit
    in HBM. Under jit with the batch sharded over the mesh 'data' axis,
    the page pool [B(1+H), D] is what GSPMD all-gathers across shards
    (one small [B, D]-scale collective); each shard then streams its own
    query chunks against the globally-gathered pool — every shard sees
    the global negative pool, one chunk of logits at a time. Numerics:
    identical math to the dense path (softmax-CE == lse - positive
    logit), parity pinned to fp32 tolerance by tests/test_losses_fused.py.
    0 (the default) keeps the dense reference path, byte-for-byte.
    """
    qn = l2_normalize(q)
    pn = l2_normalize(p)
    B = q.shape[0]
    labels = jnp.arange(B)
    if chunk and 0 < chunk < B:
        pool = pn
        if neg is not None:
            nn_ = l2_normalize(neg.reshape(-1, neg.shape[-1]))     # [B*H, D]
            pool = jnp.concatenate([pn, nn_], axis=0)              # [B+BH, D]
        lse, pos, corr = _chunk_stats(qn, pool, labels, scale, chunk)
        loss_qp = (lse - pos).mean()
        if symmetric:
            lse_pq, pos_pq, _ = _chunk_stats(pn, qn, labels, scale, chunk)
            loss = 0.5 * (loss_qp + (lse_pq - pos_pq).mean())
        else:
            loss = loss_qp
        in_batch_acc = corr.mean()
        return loss, {"loss": loss, "in_batch_acc": in_batch_acc,
                      "scale": scale}
    logits = scale * (qn @ pn.T)                                   # [B, B]
    if neg is not None:
        nn_ = l2_normalize(neg.reshape(-1, neg.shape[-1]))         # [B*H, D]
        extra = scale * (qn @ nn_.T)                               # [B, B*H]
        logits_qp = jnp.concatenate([logits, extra], axis=1)       # [B, B+BH]
    else:
        logits_qp = logits
    loss_qp = optax.softmax_cross_entropy_with_integer_labels(
        logits_qp, labels).mean()
    if symmetric:
        loss_pq = optax.softmax_cross_entropy_with_integer_labels(
            logits.T, labels).mean()
        loss = 0.5 * (loss_qp + loss_pq)
    else:
        loss = loss_qp
    in_batch_acc = (logits_qp.argmax(axis=1) == labels).mean()
    return loss, {"loss": loss, "in_batch_acc": in_batch_acc,
                  "scale": scale}
