"""Transformer encoder shared by the BERT-mini and mT5 towers
(SURVEY.md §3 #7-8; BASELINE.json:9,11).

One implementation, two variants:
  * variant="bert" — learned absolute positions, LayerNorm, GELU MLP
    (BERT-mini geometry: L=4, d=256, A=4).
  * variant="t5"   — T5 relative-position buckets shared across layers,
    RMSNorm, gated-GELU MLP, no biases (mT5-base encoder geometry:
    L=12, d=768, A=12, ff=2048).

TPU-first choices: pre-norm blocks (stable in bfloat16), softmax in float32,
everything else bfloat16 on the MXU, static [B, L] shapes, no Python control
flow dependent on data. Attention/MLP matmul dims are the tensor-parallel
('model' mesh axis) sharding surface — see parallel/sharding.py rules keyed
on the param names used here (wq/wk/wv/wo, wi/wi_0/wi_1/wo_mlp).
"""
from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


def _relative_position_bucket(rel_pos: jnp.ndarray, num_buckets: int = 32,
                              max_distance: int = 128) -> jnp.ndarray:
    """T5 bidirectional relative-position bucketing."""
    num_buckets //= 2
    ret = (rel_pos > 0).astype(jnp.int32) * num_buckets
    n = jnp.abs(rel_pos)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    val_if_large = max_exact + (
        jnp.log(n.astype(jnp.float32) / max_exact + 1e-6)
        / np.log(max_distance / max_exact)
        * (num_buckets - max_exact)
    ).astype(jnp.int32)
    val_if_large = jnp.minimum(val_if_large, num_buckets - 1)
    return ret + jnp.where(is_small, n, val_if_large)


class RmsNorm(nn.Module):
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        xf = x.astype(jnp.float32)
        var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
        y = xf * jax.lax.rsqrt(var + 1e-6)
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],))
        return (y * scale).astype(self.dtype)


class Attention(nn.Module):
    """kind: 'dense' (materialised scores), 'flash' (Pallas kernel,
    ops/flash_attention.py), or 'ring' (sequence-parallel over the mesh
    'seq' axis, parallel/ring_attention.py). For the T5 variant, dense/flash
    take the materialised rel_bias while ring takes rel_bias_table — the
    ring rebuilds its bias block per step from global positions instead of
    ever holding the O(L²) bias.

    `seg` (sequence packing, train.pack_pages): [B, L] segment ids
    (0 = pad, s >= 1 = packed page s) restrict attention to
    within-segment pairs — dense builds the [B, L, L] block mask, flash
    compares segment ids per score tile inside the kernel (no [B, L, L]
    in HBM). The T5 rel_bias stays the GLOBAL-position bias: segments
    are contiguous in the row, so within-segment relative distance
    equals global distance, and cross-segment entries are masked."""
    num_heads: int
    model_dim: int
    use_bias: bool
    dtype: jnp.dtype = jnp.bfloat16
    kind: str = "dense"
    mesh: Any = None          # jax.sharding.Mesh, required for kind='ring'

    @nn.compact
    def __call__(self, x: jnp.ndarray, pad_mask: jnp.ndarray,
                 rel_bias: jnp.ndarray | None,
                 rel_bias_table: jnp.ndarray | None = None,
                 seg: jnp.ndarray | None = None) -> jnp.ndarray:
        head_dim = self.model_dim // self.num_heads
        B, L, _ = x.shape
        # Three separate projections, DELIBERATELY not fused into one [d,3d]
        # dot: measured on v5e (round 4), the fused dot wins 2.7x in
        # isolation (x read once, wider N) but LOSES 2-10% inside the full
        # model — the post-matmul q/k/v slices materialize three [B,L,H,Dh]
        # copies and XLA already overlaps the separate dots with neighboring
        # work. Interleaved A/B at bench shapes: fused 59.8/15.7 ms
        # (train/embed), separate 59.0/14.1 ms. See docs/MFU.md.
        dense = lambda name: nn.Dense(self.model_dim, use_bias=self.use_bias,
                                      dtype=self.dtype, name=name)
        shape = (B, L, self.num_heads, head_dim)
        q = dense("wq")(x).reshape(shape)
        k = dense("wk")(x).reshape(shape)
        v = dense("wv")(x).reshape(shape)
        bhld = lambda t: t.transpose(0, 2, 1, 3)
        if self.kind == "flash":
            from dnn_page_vectors_tpu.ops.flash_attention import flash_attention
            bias = None if rel_bias is None else rel_bias[0]  # [H, L, L]
            out = flash_attention(bhld(q), bhld(k), bhld(v), pad_mask, bias,
                                  seg=seg)
            out = bhld(out.astype(self.dtype))                # [B, L, H, Dh]
        elif self.kind == "ring":
            from dnn_page_vectors_tpu.parallel.ring_attention import ring_attention
            assert self.mesh is not None, "ring attention needs a mesh"
            assert seg is None, \
                "sequence packing (train.pack_pages) supports dense/flash " \
                "attention only — the ring path shards L itself"
            # ring consumes the bias TABLE (rebuilt per step); a materialised
            # [1,H,L,L] bias here means a caller wired the wrong operand
            assert rel_bias is None, "ring attention takes rel_bias_table"
            out = ring_attention(self.mesh, bhld(q), bhld(k), bhld(v),
                                 pad_mask, bias_table=rel_bias_table,
                                 bucket_fn=(None if rel_bias_table is None
                                            else _relative_position_bucket))
            out = bhld(out.astype(self.dtype))
        else:
            scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(head_dim)
            scores = scores.astype(jnp.float32)
            if rel_bias is not None:
                scores = scores + rel_bias
            big_neg = jnp.asarray(-1e9, jnp.float32)
            if seg is None:
                allowed = pad_mask[:, None, None, :]
            else:
                # block-diagonal segment mask: token i may attend j only
                # inside its own packed page (and never to pad, seg 0)
                allowed = ((seg[:, None, :] == seg[:, :, None])
                           & (seg > 0)[:, None, :]
                           & pad_mask[:, None, :])[:, None]   # [B,1,L,L]
            scores = jnp.where(allowed, scores, big_neg)
            probs = nn.softmax(scores, axis=-1).astype(self.dtype)
            out = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
        out = out.reshape(B, L, self.model_dim)
        return dense("wo")(out)


class Block(nn.Module):
    num_heads: int
    model_dim: int
    mlp_dim: int
    variant: str
    dropout: float
    dtype: jnp.dtype = jnp.bfloat16
    attention_kind: str = "dense"
    mesh: Any = None

    @nn.compact
    def __call__(self, x, pad_mask, rel_bias, rel_bias_table=None,
                 deterministic: bool = True, seg=None):
        norm = (lambda n: RmsNorm(dtype=self.dtype, name=n)) if self.variant == "t5" \
            else (lambda n: nn.LayerNorm(dtype=self.dtype, name=n))
        use_bias = self.variant != "t5"

        h = norm("ln_attn")(x)
        h = Attention(self.num_heads, self.model_dim, use_bias,
                      dtype=self.dtype, kind=self.attention_kind,
                      mesh=self.mesh, name="attn")(h, pad_mask, rel_bias,
                                                   rel_bias_table, seg=seg)
        h = nn.Dropout(self.dropout)(h, deterministic=deterministic)
        x = x + h

        h = norm("ln_mlp")(x)
        if self.variant == "t5":  # gated GELU, no biases (mT5 geometry)
            # separate gate/value dots, DELIBERATELY not fused into one
            # [d, 2*mlp] projection: measured at mT5-base geometry on v5e
            # (round 4), the fused variant's post-matmul de-interleave made
            # the forward 34% slower (62.7 vs 46.8 ms). See docs/MFU.md.
            wi0 = nn.Dense(self.mlp_dim, use_bias=False, dtype=self.dtype,
                           name="wi_0")(h)
            wi1 = nn.Dense(self.mlp_dim, use_bias=False, dtype=self.dtype,
                           name="wi_1")(h)
            h = nn.gelu(wi0) * wi1
            h = nn.Dense(self.model_dim, use_bias=False, dtype=self.dtype,
                         name="wo_mlp")(h)
        else:
            h = nn.Dense(self.mlp_dim, dtype=self.dtype, name="wi")(h)
            h = nn.gelu(h)
            h = nn.Dense(self.model_dim, dtype=self.dtype, name="wo_mlp")(h)
        h = nn.Dropout(self.dropout)(h, deterministic=deterministic)
        return x + h


class TransformerEncoder(nn.Module):
    vocab_size: int
    num_layers: int = 4
    num_heads: int = 4
    model_dim: int = 256
    mlp_dim: int = 1024
    out_dim: int = 256
    max_len: int = 128
    dropout: float = 0.1
    variant: str = "bert"          # bert | t5
    dtype: jnp.dtype = jnp.bfloat16
    attention_kind: str = "dense"  # dense | flash | ring
    mesh: Any = None               # required for attention_kind='ring'

    @nn.compact
    def __call__(self, ids: jnp.ndarray, deterministic: bool = True,
                 seg: jnp.ndarray | None = None,
                 pos: jnp.ndarray | None = None,
                 nseg: int = 0) -> jnp.ndarray:
        # ids: [B, L] subword ids, 0 = pad.
        #
        # Sequence packing (train.pack_pages, data/loader.py pack_segments):
        # `seg` [B, L] marks which packed page each token belongs to
        # (0 = pad, 1..nseg = page slot); attention is restricted to
        # within-segment pairs and pooling runs PER SEGMENT, returning
        # [B, nseg, D] — one vector per packed page. `pos` [B, L] gives
        # per-segment LOCAL positions so BERT's absolute position
        # embedding restarts at 0 for every packed page (the T5 relative
        # bias needs no restart: segments are contiguous, so
        # within-segment relative distance equals global distance and
        # cross-segment entries are masked). seg=None is the unpacked
        # path, byte-identical to pre-packing behavior: [B, D].
        B, L = ids.shape
        pad_mask = ids > 0
        x = nn.Embed(self.vocab_size, self.model_dim, dtype=self.dtype,
                     name="tok_embed")(ids)
        rel_bias = None
        rel_bias_table = None
        if self.variant == "bert":
            pemb = self.param("pos_embed", nn.initializers.normal(0.02),
                              (self.max_len, self.model_dim))
            if pos is None:
                x = x + pemb[:L].astype(self.dtype)[None]
            else:
                x = x + pemb[pos].astype(self.dtype)        # [B, L, d]
        else:
            # shared-across-layers relative position bias (T5 style)
            table = self.param("rel_bias", nn.initializers.normal(0.02),
                               (32, self.num_heads))
            if self.attention_kind == "ring":
                # never materialise [L, L] here: the ring rebuilds its bias
                # block per step from global positions (ring_attention.py)
                rel_bias_table = table
            else:
                gpos = jnp.arange(L)
                buckets = _relative_position_bucket(
                    gpos[None, :] - gpos[:, None])
                rel_bias = table[buckets].transpose(2, 0, 1)[None]  # [1,H,L,L]
                rel_bias = rel_bias.astype(jnp.float32)
        x = nn.Dropout(self.dropout)(x, deterministic=deterministic)
        for i in range(self.num_layers):
            x = Block(self.num_heads, self.model_dim, self.mlp_dim,
                      self.variant, self.dropout, dtype=self.dtype,
                      attention_kind=self.attention_kind, mesh=self.mesh,
                      name=f"block{i}")(x, pad_mask, rel_bias, rel_bias_table,
                                        deterministic, seg=seg)
        x = (RmsNorm(dtype=self.dtype, name="ln_final") if self.variant == "t5"
             else nn.LayerNorm(dtype=self.dtype, name="ln_final"))(x)
        if seg is not None:
            # per-segment masked mean pool -> one vector per packed page
            assert nseg > 0, "seg requires nseg (segments per packed row)"
            onehot = (seg[:, :, None]
                      == jnp.arange(1, nseg + 1)[None, None, :]
                      ).astype(jnp.float32)                  # [B, L, S]
            tot = jnp.einsum("bld,bls->bsd", x.astype(jnp.float32), onehot)
            cnt = jnp.maximum(onehot.sum(1), 1.0)            # [B, S]
            pooled = tot / cnt[..., None]
            out = nn.Dense(self.out_dim, dtype=jnp.float32,
                           name="proj")(pooled)
            return out                                       # [B, S, D] f32
        # masked mean pool
        m = pad_mask[..., None].astype(jnp.float32)
        pooled = (x.astype(jnp.float32) * m).sum(1) / jnp.maximum(m.sum(1), 1.0)
        out = nn.Dense(self.out_dim, dtype=jnp.float32, name="proj")(pooled)
        return out                                                  # [B, D] f32
