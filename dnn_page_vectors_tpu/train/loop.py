"""Contrastive training loop (SURVEY.md §3 #12; call stack §4.1).

The hot loop is ONE jit-compiled `train_step` with donated state:
  encode both towers -> global-batch cosine-contrastive loss -> grad ->
  optax update. Under a >1-device mesh the same step is compiled with the
  batch sharded over 'data' and params sharded by parallel/sharding.py; XLA
  emits the gradient psum / page-vector all-gather over ICI (the reference's
  torch-DDP/NCCL role, BASELINE.json:5). Everything host-side (tokenization,
  logging, checkpointing) stays off the compiled path.
"""
from __future__ import annotations

import os
import sys
import time
from functools import partial
from typing import Any, Dict, Iterator, Optional, Tuple

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np
import optax

from dnn_page_vectors_tpu.config import Config
from dnn_page_vectors_tpu.data.loader import (
    TrainBatcher, build_corpus, build_tokenizer, prefetch_to_device)
from dnn_page_vectors_tpu.data.toy import ToyCorpus
from dnn_page_vectors_tpu.models.factory import build_two_tower
from dnn_page_vectors_tpu.models.losses import cosine_contrastive_loss
from dnn_page_vectors_tpu.parallel.mesh import fit_mesh_to_devices, make_mesh
from dnn_page_vectors_tpu.parallel.sharding import (
    batch_sharding, param_shardings, put_global, replicated, shard_params,
    stacked_batch_sharding)
from dnn_page_vectors_tpu.train.optimizer import make_optimizer
from dnn_page_vectors_tpu.utils import faults, telemetry
from dnn_page_vectors_tpu.utils.logging import MetricsLogger
from dnn_page_vectors_tpu.utils.profiling import PipelineProfiler


@flax.struct.dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: jnp.ndarray          # int32 scalar


def make_train_step(model, tx, loss_chunk: int = 0):
    """Build the (un-jitted) global-batch train step; caller jits with
    shardings + donation.

    `loss_chunk` > 0 selects the fused/chunked contrastive loss
    (train.loss_chunk, models/losses.py): the [B, B(1+H)] logits never
    materialize — per-chunk log-sum-exp tiles stream against the
    GSPMD-gathered global page pool instead."""

    def train_step(state: TrainState, batch: Dict[str, jnp.ndarray],
                   base_rng: jax.Array) -> Tuple[TrainState, Dict[str, jnp.ndarray]]:
        rng = jax.random.fold_in(base_rng, state.step)

        def loss_fn(params):
            q, p, neg, scale = model.apply(
                params, batch["query"], batch["page"],
                batch.get("neg_page"), deterministic=False,
                rngs={"dropout": rng},
                page_seg=batch.get("page_seg"),
                page_pos=batch.get("page_pos"))
            return cosine_contrastive_loss(q, p, scale, neg,
                                           chunk=loss_chunk)

        (loss, metrics), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        metrics = dict(metrics)
        metrics["grad_norm"] = optax.global_norm(grads)
        return TrainState(params=params, opt_state=opt_state,
                          step=state.step + 1), metrics

    return train_step


class Trainer:
    """Wires config -> data -> model -> mesh -> compiled step (§4.1)."""

    def __init__(self, cfg: Config, corpus: Optional[ToyCorpus] = None,
                 hard_negative_lookup=None, workdir: Optional[str] = None,
                 tokenizers: Optional[Tuple[Any, Any]] = None):
        """`tokenizers=(query_tok, page_tok)` bypasses build_tokenizer —
        anything with .vocab_size and .encode_batch works. Used by bench.py
        to drive true-vocab-size embedding tables with synthetic ids
        (training a 250k SentencePiece is data prep, not step cost)."""
        self.cfg = cfg
        self.workdir = workdir or cfg.workdir
        os.makedirs(self.workdir, exist_ok=True)
        self.corpus = corpus if corpus is not None else build_corpus(cfg)
        self.query_tok, self.page_tok = (
            tokenizers if tokenizers is not None
            else build_tokenizer(cfg, self.corpus, cache_dir=self.workdir))
        fitted = fit_mesh_to_devices(cfg.mesh)
        want = (cfg.mesh.data, cfg.mesh.model, cfg.mesh.seq)
        got = (fitted.data, fitted.model, fitted.seq)
        if want != got:
            if cfg.mesh.strict:
                raise RuntimeError(
                    f"mesh.strict: config wants {want} devices but only "
                    f"{len(jax.devices())} are visible")
            print(f"WARNING: mesh {want} shrunk to {got} for "
                  f"{len(jax.devices())} visible device(s); set "
                  "mesh.strict=true to fail instead", file=sys.stderr)
        self.mesh = make_mesh(fitted)
        self.model = build_two_tower(cfg, self.page_tok.vocab_size,
                                     mesh=self.mesh)
        self.tx = make_optimizer(cfg.train)
        self.hard_negative_lookup = hard_negative_lookup
        self._compiled = None
        self._compiled_multi = None

    # -- state ------------------------------------------------------------
    def init_state(self, seed: Optional[int] = None) -> TrainState:
        seed = self.cfg.train.seed if seed is None else seed
        rng = jax.random.PRNGKey(seed)
        d = self.cfg.data
        # dummy batch must divide over the 'data' axis (ring attention's
        # shard_map enforces divisibility even at init-trace time)
        b = max(2, self.mesh.shape["data"])
        dummy_q = jnp.zeros((b, d.query_len) + self._tok_extra(), jnp.int32)
        dummy_p = jnp.zeros((b, d.page_len) + self._tok_extra(), jnp.int32)
        params = self.model.init(rng, dummy_q, dummy_p)
        params = shard_params(params, self.mesh)
        # Moments (zeros_like) inherit param shardings, but optax also makes
        # fresh scalars (adam's count) that land committed on device 0; every
        # leaf must live on THIS mesh or jit rejects the mixed device sets.
        mesh_devs = frozenset(self.mesh.devices.flat)
        def _on_mesh(leaf):
            sh = getattr(leaf, "sharding", None)
            if sh is not None and frozenset(sh.device_set) == mesh_devs:
                return leaf
            return put_global(leaf, replicated(self.mesh))
        opt_state = jax.tree_util.tree_map(_on_mesh, self.tx.init(params))
        step = put_global(jnp.zeros((), jnp.int32), replicated(self.mesh))
        return TrainState(params=params, opt_state=opt_state, step=step)

    def _tok_extra(self) -> tuple:
        return ((self.cfg.data.trigrams_per_word,)
                if self.cfg.data.tokenizer == "trigram" else ())

    def base_rng(self) -> jax.Array:
        """Replicated base key for the per-step dropout fold_in, built with
        train.dropout_rng (default rbg — see config.py for the measured
        threefry cost this avoids). Typed keys can't pass through numpy, so
        the multi-process-safe placement goes via key_data/wrap_key_data."""
        key = jax.random.key(self.cfg.train.seed + 1,
                             impl=self.cfg.train.dropout_rng)
        data = put_global(jax.random.key_data(key), replicated(self.mesh))
        return jax.random.wrap_key_data(data, impl=self.cfg.train.dropout_rng)

    # -- compiled step ----------------------------------------------------
    def compiled_step(self, state: TrainState):
        if self._compiled is None:
            step_fn = make_train_step(self.model, self.tx,
                                      loss_chunk=self.cfg.train.loss_chunk)
            state_sh = jax.tree_util.tree_map(lambda x: x.sharding, state)
            self._compiled = jax.jit(
                step_fn,
                in_shardings=(state_sh, batch_sharding(self.mesh),
                              replicated(self.mesh)),
                out_shardings=(state_sh, replicated(self.mesh)),
                donate_argnums=(0,),
            )
        return self._compiled

    def _make_batcher(self, start_step: int,
                      profiler: Optional[PipelineProfiler] = None
                      ) -> TrainBatcher:
        pack = max(1, self.cfg.train.pack_pages)
        if pack > 1:
            if self.cfg.model.encoder not in ("bert", "t5"):
                raise ValueError(
                    "train.pack_pages needs a transformer page tower "
                    f"(bert/t5), not {self.cfg.model.encoder!r}: segment "
                    "masks only exist for attention encoders")
            rows = self.cfg.train.batch_size // pack
            if rows % self.mesh.shape["data"]:
                raise ValueError(
                    f"packed row batch {rows} (batch_size/pack_pages) must "
                    f"divide the mesh data axis {self.mesh.shape['data']}")
        return TrainBatcher(
            self.corpus, self.query_tok, self.page_tok,
            batch_size=self.cfg.train.batch_size, seed=self.cfg.train.seed,
            start_step=start_step,
            hard_negative_lookup=self.hard_negative_lookup,
            workers=self.cfg.data.tokenize_workers, profiler=profiler,
            pack=pack)

    def batches(self, start_step: int = 0,
                profiler: Optional[PipelineProfiler] = None) -> Iterator[Any]:
        return prefetch_to_device(
            iter(self._make_batcher(start_step, profiler=profiler)),
            sharding=batch_sharding(self.mesh), profiler=profiler)

    def stacked_batches(self, start_step: int = 0, k: int = 1,
                        profiler: Optional[PipelineProfiler] = None
                        ) -> Iterator[Any]:
        """[K, B, ...] stacks of K consecutive batches for the scan_steps
        fused dispatch; same data order as batches()."""
        batcher = self._make_batcher(start_step, profiler=profiler)

        def _stack(it):
            while True:
                group = [b for _, b in zip(range(k), it)]
                if len(group) < k:
                    return
                yield {key: np.stack([g[key] for g in group])
                       for key in group[0]}

        return prefetch_to_device(_stack(iter(batcher)),
                                  sharding=stacked_batch_sharding(self.mesh),
                                  profiler=profiler)

    def compiled_multi_step(self, state: TrainState):
        """Train-K-steps-in-one-dispatch: lax.scan over a [K, ...] batch
        stack, donated carry; K is the stack's leading dim (jit retraces per
        K, so one cached wrapper serves any stack size). Semantically
        identical to K calls of the single step (same rng folding: the step
        counter advances inside the scan); metrics returned are the LAST
        step's, matching what a per-step loop would log at the boundary."""
        if self._compiled_multi is None:
            step_fn = make_train_step(self.model, self.tx,
                                      loss_chunk=self.cfg.train.loss_chunk)

            def multi(state, stacked, base_rng):
                def body(st, batch):
                    return step_fn(st, batch, base_rng)
                state, ms = jax.lax.scan(body, state, stacked)
                return state, jax.tree_util.tree_map(lambda x: x[-1], ms)

            state_sh = jax.tree_util.tree_map(lambda x: x.sharding, state)
            self._compiled_multi = jax.jit(
                multi,
                in_shardings=(state_sh, stacked_batch_sharding(self.mesh),
                              replicated(self.mesh)),
                out_shardings=(state_sh, replicated(self.mesh)),
                donate_argnums=(0,),
            )
        return self._compiled_multi

    # -- driver -----------------------------------------------------------
    # graftcheck: hot
    def train(self, steps: Optional[int] = None,
              state: Optional[TrainState] = None,
              log: Optional[MetricsLogger] = None,
              ckpt_manager=None,
              profiler: Optional[PipelineProfiler] = None
              ) -> Tuple[TrainState, Dict[str, float]]:
        """Runs `steps` more steps. The data stream resumes at state.step, so
        a restored run sees the same batch order as an uninterrupted one.
        With ckpt_manager, saves (async) every cfg.train.checkpoint_every
        steps — the crash-recovery half of SURVEY.md §5.3.

        Pipeline observability: per-stage wall times (produce_wait / read /
        tokenize / h2d / compute dispatch) accumulate in `profiler` (one is
        created when omitted) and land in every logged metrics line as
        stage_*_s keys — a host-bound run shows up as produce_wait
        dominating, not as an unexplained low pages/sec."""
        cfg = self.cfg
        steps = cfg.train.steps if steps is None else steps
        state = self.init_state() if state is None else state
        scan_k = max(1, cfg.train.scan_steps)
        if scan_k > 1:
            # Fused-dispatch alignment is validated up front, BEFORE any
            # step runs and regardless of whether a ckpt_manager is passed
            # (ADVICE r3: a run launched without a manager used to hit the
            # checkpoint_every error only when it later resumed with one).
            # Deliberately NOT in __init__: inference commands construct a
            # Trainer for its model/tokenizers and must not fail on
            # train-only settings.
            for name, every in (("log_every", cfg.train.log_every),
                                ("checkpoint_every",
                                 cfg.train.checkpoint_every)):
                if every % scan_k:
                    raise ValueError(
                        f"train.{name}={every} must be a multiple of "
                        f"train.scan_steps={scan_k}: host-side events can "
                        "only fire at fused-dispatch boundaries")
            if steps % scan_k:
                raise ValueError(
                    f"steps={steps} must be a multiple of "
                    f"train.scan_steps={scan_k}")
            step_fn = self.compiled_multi_step(state)
        else:
            step_fn = self.compiled_step(state)
        base_rng = self.base_rng()
        # default logger mirrors every numeric scalar into the process
        # registry (docs/OBSERVABILITY.md) — jsonl shape unchanged
        log = log or MetricsLogger(self.workdir,
                                   registry=telemetry.default_registry())
        pages_per_step = cfg.train.batch_size
        n_dev = self.mesh.devices.size
        # MFU next to pages/sec/chip so every logged rate is interpretable
        # against hardware peak (same analytic counts as bench.py)
        from dnn_page_vectors_tpu.utils.flops import (
            device_peak_flops, train_flops_per_pair)
        peak = device_peak_flops(self.mesh.devices.flat[0])
        flops_pair = train_flops_per_pair(cfg, cfg.train.batch_size)
        # graftcheck: off=host-sync -- one-time sync before the loop
        start_step = int(state.step)
        prof = PipelineProfiler() if profiler is None else profiler
        # train-loop throughput as registry instruments (docs/
        # OBSERVABILITY.md): a windowed steps counter gives live steps/sec
        # mid-run; the gauges mirror the numbers the metrics line reports
        _reg = telemetry.default_registry()
        _m_steps = _reg.counter("train.steps",
                                window_s=telemetry.DEFAULT_WINDOW_S)
        it = (self.stacked_batches(start_step=start_step, k=scan_k,
                                   profiler=prof)
              if scan_k > 1 else self.batches(start_step=start_step,
                                              profiler=prof))
        last: Dict[str, float] = {}
        t0 = time.perf_counter()
        for c in range(steps // scan_k):
            batch = next(it)
            with prof.stage("compute"):   # dispatch; async past the first
                state, metrics = step_fn(state, batch, base_rng)
            _m_steps.inc(scan_k)
            i = (c + 1) * scan_k         # steps completed this call
            if i % cfg.train.log_every == 0 or i == steps:
                metrics = {k: float(v) for k, v in metrics.items()}
                with prof.stage("sync"):
                    # graftcheck: off=host-sync -- log-cadence drain:
                    # fires every log_every steps, not per step
                    jax.block_until_ready(state.params)
                dt = time.perf_counter() - t0
                # graftcheck: off=host-sync -- after the log-cadence
                # drain above; the value is already on host
                done = int(state.step) - start_step
                pps_chip = done * pages_per_step / dt / n_dev
                metrics["pages_per_sec_per_chip"] = pps_chip
                _reg.gauge("train.pages_per_sec_per_chip").set(pps_chip)
                if peak:
                    metrics["mfu"] = pps_chip * flops_pair / peak
                    _reg.gauge("train.mfu").set(metrics["mfu"])
                try:  # HBM headroom next to throughput (memory_stats()
                      # is None on CPU and on the tunneled axon backend)
                    stats = self.mesh.devices.flat[0].memory_stats()
                    if stats and "bytes_in_use" in stats:
                        metrics["hbm_gb_in_use"] = round(
                            stats["bytes_in_use"] / 2**30, 3)
                except Exception:
                    pass
                # graftcheck: off=host-sync -- post-drain host value
                metrics["step"] = int(state.step)
                # per-stage pipeline breakdown next to the rate it explains
                metrics.update(prof.summary())
                # recovery-path activity (injected faults, I/O retries,
                # checkpoint rollbacks) surfaces in the same line — a run
                # that limped through failures must say so in its metrics
                fc = faults.counters()
                if fc:
                    metrics["fault_counters"] = fc
                log.write(metrics)
                last = metrics
            if (ckpt_manager is not None
                    and i % cfg.train.checkpoint_every == 0
                    and i < steps):      # final save is the caller's
                # graftcheck: off=host-sync -- checkpoint-cadence sync
                ckpt_manager.save(int(state.step), state)
        return state, last
