"""Optimizer + LR schedule (SURVEY.md §3 #11): adamw, warmup-cosine."""
from __future__ import annotations

import optax

from dnn_page_vectors_tpu.config import TrainConfig


def make_optimizer(cfg: TrainConfig) -> optax.GradientTransformation:
    schedule = optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=cfg.learning_rate,
        warmup_steps=max(cfg.warmup_steps, 1),
        decay_steps=max(cfg.steps, cfg.warmup_steps + 1),
        end_value=cfg.learning_rate * 0.1,
    )
    if cfg.optimizer == "sgd":
        opt = optax.sgd(schedule)
    elif cfg.optimizer == "adamw":
        opt = optax.adamw(schedule, weight_decay=cfg.weight_decay)
    else:
        raise ValueError(f"unknown optimizer {cfg.optimizer!r}")
    return optax.chain(optax.clip_by_global_norm(1.0), opt)
