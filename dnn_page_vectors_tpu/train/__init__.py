"""Trainer layer: optimizer, jitted train step, loop, checkpointing
(SURVEY.md §2 layer 4)."""
from dnn_page_vectors_tpu.train.loop import Trainer, TrainState

__all__ = ["Trainer", "TrainState"]
