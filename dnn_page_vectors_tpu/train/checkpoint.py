"""Checkpoint/resume (SURVEY.md §3 #23, §5.3-5.4).

Orbax-backed checkpointing of params + opt state + step, with retention.
The data cursor needs no separate state: the batcher derives (epoch, offset)
deterministically from the restored step (loader.py TrainBatcher.start_step),
so a resumed run continues the exact batch order of an uninterrupted one.
Orbax handles multi-host coordination and restore-with-sharding on real
pods; the same API runs single-process in the sandbox.
"""
from __future__ import annotations

import os
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp


class CheckpointManager:
    def __init__(self, directory: str, max_to_keep: int = 3):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True, enable_async_checkpointing=True),
        )

    def save(self, step: int, state: Any, wait: bool = False) -> None:
        self._mgr.save(step, args=ocp.args.StandardSave(state))
        if wait:
            self._mgr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, state_like: Any, step: Optional[int] = None) -> Any:
        """Restore into the structure/shardings of `state_like` (an abstract
        or concrete state pytree)."""
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoint in {self.directory}")
        abstract = jax.tree_util.tree_map(ocp.utils.to_shape_dtype_struct,
                                          state_like)
        return self._mgr.restore(step, args=ocp.args.StandardRestore(abstract))

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()
