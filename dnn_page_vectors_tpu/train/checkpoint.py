"""Checkpoint/resume (SURVEY.md §3 #23, §5.3-5.4).

Orbax-backed checkpointing of params + opt state + step, with retention.
The data cursor needs no separate state: the batcher derives (epoch, offset)
deterministically from the restored step (loader.py TrainBatcher.start_step),
so a resumed run continues the exact batch order of an uninterrupted one.
Orbax handles multi-host coordination and restore-with-sharding on real
pods; the same API runs single-process in the sandbox.

Robustness (docs/ROBUSTNESS.md): saves run under the shared transient-I/O
retry; restore-of-latest VALIDATES the restored pytree (structure, shapes,
finite floats) and rolls back to the newest OLDER step when the latest
checkpoint is corrupt — a torn save costs checkpoint_every steps of
recomputation, never the run. An explicitly requested step never falls
back: callers asking for step N get step N or a FileNotFoundError naming
the directory and the steps that do exist.
"""
from __future__ import annotations

import os
from typing import Any, List, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from dnn_page_vectors_tpu.utils import faults, telemetry


class CheckpointManager:
    def __init__(self, directory: str, max_to_keep: int = 3):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._closed = False
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True, enable_async_checkpointing=True),
        )

    def save(self, step: int, state: Any, wait: bool = False) -> None:
        plan = faults.active()
        attempt = {"n": 0}

        def _save():
            plan.check("ckpt_save")
            try:
                # a retried attempt may find the step dir half-created by
                # the failed one; force= overwrites instead of erroring
                self._mgr.save(step, args=ocp.args.StandardSave(state),
                               force=attempt["n"] > 0)
            finally:
                attempt["n"] += 1

        faults.retry(_save, op="ckpt_save")
        if wait:
            self._mgr.wait_until_finished()
        if plan.pending("ckpt_file"):
            # scheduled on-disk checkpoint corruption: make the save durable
            # first so the damage hits the finished artifact — exactly what
            # media rot or a torn write does to a real checkpoint
            self._mgr.wait_until_finished()
            plan.corrupt_dir("ckpt_file",
                             os.path.join(self.directory, str(step)))

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self) -> List[int]:
        return sorted(self._mgr.all_steps())

    def restore(self, state_like: Any, step: Optional[int] = None) -> Any:
        """Restore into the structure/shardings of `state_like` (an abstract
        or concrete state pytree).

        step=None restores the newest step that restores AND validates
        cleanly, rolling back through older steps when the latest is
        corrupt (each skip is logged and counted). An explicit step= is a
        contract, not a preference: a missing step raises FileNotFoundError
        (directory + available steps), a corrupt one re-raises its error.
        """
        abstract = jax.tree_util.tree_map(ocp.utils.to_shape_dtype_struct,
                                          state_like)
        steps = self.all_steps()
        if step is not None:
            if step not in steps:
                raise FileNotFoundError(
                    f"no checkpoint for step {step} in {self.directory} "
                    f"(available steps: {steps or 'none'})")
            return self._restore_validated(step, abstract)
        if not steps:
            raise FileNotFoundError(f"no checkpoint in {self.directory}")
        errors = []
        for s in reversed(steps):
            try:
                out = self._restore_validated(s, abstract)
            except Exception as e:  # noqa: BLE001 — orbax/tensorstore raise
                # a zoo of exception types for torn files; any of them means
                # "this checkpoint is unusable", which is exactly the case
                # rollback exists for
                errors.append(f"step {s}: {type(e).__name__}: {e}")
                faults.count("ckpt_restore_failed")
                continue
            if errors:
                faults.count("ckpt_rollback")
                # lifecycle event (docs/OBSERVABILITY.md): a rollback means
                # newer training work was silently lost — dashboards alert
                # on this transition, not just a counter
                telemetry.default_registry().event("ckpt_rollback", {
                    "restored_step": s, "skipped": len(errors),
                    "directory": self.directory})
                faults.warn(
                    f"checkpoint rollback in {self.directory}: restored "
                    f"step {s}; skipped corrupt newer checkpoint(s): "
                    + "; ".join(e[:200] for e in errors))
            return out
        raise RuntimeError(
            f"every checkpoint in {self.directory} failed to restore: "
            + "; ".join(e[:200] for e in errors))

    def _restore_validated(self, step: int, abstract: Any) -> Any:
        out = self._mgr.restore(step,
                                args=ocp.args.StandardRestore(abstract))
        err = _validate_state(out, abstract)
        if err:
            raise ValueError(f"restored step {step} failed validation: {err}")
        return out

    def close(self) -> None:
        """Idempotent: a close() in a finally block after an earlier close
        (or after the manager failed mid-operation) must never raise and
        mask the original exception."""
        if self._closed:
            return
        self._closed = True
        try:
            self._mgr.wait_until_finished()
        finally:
            self._mgr.close()


def _validate_state(state: Any, abstract: Any) -> Optional[str]:
    """Structure + shape/dtype + finiteness check of a restored pytree.
    Catches the corruption orbax itself can't see: a restore that
    'succeeded' into the right shapes but carries garbage floats."""
    got_td = jax.tree_util.tree_structure(state)
    want_td = jax.tree_util.tree_structure(abstract)
    if got_td != want_td:
        return f"tree structure {got_td} != expected {want_td}"
    for (path, leaf), ref in zip(jax.tree_util.tree_leaves_with_path(state),
                                 jax.tree_util.tree_leaves(abstract)):
        name = jax.tree_util.keystr(path)
        arr = np.asarray(leaf)
        if tuple(arr.shape) != tuple(ref.shape):
            return f"{name}: shape {arr.shape} != expected {ref.shape}"
        if np.issubdtype(arr.dtype, np.floating) and \
                not np.isfinite(arr).all():
            return f"{name}: non-finite values"
    return None
