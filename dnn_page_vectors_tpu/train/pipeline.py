"""Periodic re-mining pipeline: train -> embed -> mine -> continue-train
(SURVEY.md §4.4; VERDICT r1 #5 — config 4's loop as ONE command instead of a
manual CLI sequence).

Each round trains `steps_per_round`, embeds the corpus with the CURRENT
params into a fresh store generation, mines hard negatives with the CURRENT
model, and feeds the refreshed table into the next round's batches — so
negatives stay hard as the model improves (the point of periodic re-mining,
BASELINE.json:10). Round boundaries checkpoint through the ordinary manager,
so a killed pipeline resumes into the same schedule.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional

import numpy as np

from dnn_page_vectors_tpu.config import Config
from dnn_page_vectors_tpu.infer.bulk_embed import BulkEmbedder
from dnn_page_vectors_tpu.infer.vector_store import prepare_store
from dnn_page_vectors_tpu.mine.ann import HardNegatives, mine_hard_negatives
from dnn_page_vectors_tpu.train.loop import Trainer
from dnn_page_vectors_tpu.utils.logging import MetricsLogger


def run_pipeline(cfg: Config, rounds: int = 2,
                 steps_per_round: Optional[int] = None,
                 trainer: Optional[Trainer] = None,
                 state=None, ckpt_manager=None,
                 eval_every_round: bool = True) -> Dict[str, object]:
    """Alternate train and re-mine for `rounds` rounds.

    Returns {"state", "recalls": [per-round recall@k], "negatives"}.
    `steps_per_round` defaults to cfg.train.steps // rounds.
    """
    if cfg.train.hard_negatives <= 0:
        raise ValueError("pipeline needs train.hard_negatives > 0 "
                         "(otherwise plain 'train' is the right command)")
    steps_per_round = steps_per_round or max(1, cfg.train.steps // rounds)
    trainer = trainer or Trainer(cfg)
    state = state if state is not None else trainer.init_state()
    log = MetricsLogger(trainer.workdir)
    store_dir = os.path.join(trainer.workdir, "store")
    negs_path = os.path.join(trainer.workdir, "hard_negatives.npy")

    # resume: a restored state mid-pipeline re-enters the right round and
    # picks up the last mined table
    if os.path.exists(negs_path) and trainer.hard_negative_lookup is None:
        trainer.hard_negative_lookup = HardNegatives.load(negs_path)

    embedder: Optional[BulkEmbedder] = None
    recalls: List[float] = []
    negs = trainer.hard_negative_lookup
    start_round = int(state.step) // steps_per_round
    for r in range(start_round, rounds):
        state, metrics = trainer.train(steps=steps_per_round, state=state,
                                       log=log, ckpt_manager=ckpt_manager)
        if embedder is None:
            embedder = BulkEmbedder(cfg, trainer.model, state.params,
                                    trainer.page_tok, trainer.mesh,
                                    query_tok=trainer.query_tok)
        else:
            from dnn_page_vectors_tpu.parallel.sharding import shard_params
            embedder.params = shard_params(state.params, trainer.mesh)
        # vectors from older params are stale: reset + stamp the new step
        # (stale-safe even when geometry overrides changed too, ADVICE r4)
        store = prepare_store(store_dir, cfg.model.out_dim,
                              cfg.eval.store_shard_size,
                              cfg.eval.store_dtype, int(state.step))
        embedder.embed_corpus(trainer.corpus, store, log=log)
        if eval_every_round:
            from dnn_page_vectors_tpu.evals.recall import evaluate_recall
            recall, nq = evaluate_recall(embedder, trainer.corpus, store,
                                         k=cfg.eval.recall_k)
            recalls.append(recall)
            log.write({"pipeline_round": r, "step": int(state.step),
                       f"recall@{cfg.eval.recall_k}": recall})
        if r + 1 < rounds:                  # last round's mine feeds nothing
            # out_path: the miner fills a memmap in query blocks and the
            # returned table is file-backed — the [nq, H] table never has
            # to fit in RAM, and persistence for resume comes for free
            negs = mine_hard_negatives(
                embedder, trainer.corpus, store,
                num_negatives=cfg.train.hard_negatives, out_path=negs_path)
            trainer.hard_negative_lookup = negs
    return {"state": state, "recalls": recalls, "negatives": negs}
