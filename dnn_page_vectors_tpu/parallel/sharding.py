"""Sharding rules: param-path regex -> PartitionSpec (SURVEY.md §3 #13-14).

DP: every batch array is sharded on its leading dim over 'data'.
TP: transformer matmuls are sharded over 'model' by the rules below, keyed
on the param names in models/transformer.py. Everything unmatched is
replicated. XLA propagates these annotations through the whole program and
inserts the ICI collectives (the reference's NCCL role, BASELINE.json:5).
"""
from __future__ import annotations

import re
from typing import Any, List, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# (path-regex, spec). First match wins. Paths look like
# "params/page_tower/block0/attn/wq/kernel".
TP_RULES: List[Tuple[str, P]] = [
    # attention: qkv project model_dim -> heads (shard output/head dim)
    (r".*/attn/w[qkv]/kernel$", P(None, "model")),
    (r".*/attn/w[qkv]/bias$", P("model")),
    # attention output: heads -> model_dim (shard input/head dim)
    (r".*/attn/wo/kernel$", P("model", None)),
    # MLP in: model_dim -> mlp_dim (shard mlp dim)
    (r".*/(wi|wi_0|wi_1)/kernel$", P(None, "model")),
    (r".*/(wi|wi_0|wi_1)/bias$", P("model")),
    # MLP out: mlp_dim -> model_dim
    (r".*/wo_mlp/kernel$", P("model", None)),
    # token embedding: shard the embed dim (gather output stays sharded on
    # the feature axis, feeding the TP matmuls without a reshard)
    (r".*/tok_embed/embedding$", P(None, "model")),
]


def _path_str(path: Tuple[Any, ...]) -> str:
    parts = []
    for k in path:
        parts.append(str(getattr(k, "key", getattr(k, "idx", k))))
    return "/".join(parts)


def spec_for_param(path_str: str) -> P:
    for pattern, spec in TP_RULES:
        if re.match(pattern, path_str):
            return spec
    return P()


def param_shardings(params: Any, mesh: Mesh) -> Any:
    """Pytree of NamedSharding matching `params`. With mesh model=1 every
    rule degenerates to replication, so the same code path serves pure-DP."""
    def _one(path, _leaf):
        return NamedSharding(mesh, spec_for_param(_path_str(path)))
    return jax.tree_util.tree_map_with_path(_one, params)


def put_global(x: Any, sharding: NamedSharding) -> jax.Array:
    """device_put that also works when `sharding` spans devices of OTHER
    processes (multi-host training): each process supplies its addressable
    shards from its local copy via make_array_from_callback. The host value
    must be identical on every process (true for seeded init and restored
    checkpoints — the only callers)."""
    if sharding.is_fully_addressable:
        return jax.device_put(x, sharding)
    arr = np.asarray(x)
    return jax.make_array_from_callback(arr.shape, sharding,
                                        lambda idx: arr[idx])


def shard_params(params: Any, mesh: Mesh) -> Any:
    return jax.tree_util.tree_map(put_global, params,
                                  param_shardings(params, mesh))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Leading-axis 'data' sharding for every batch array (rank-agnostic:
    P('data') leaves trailing dims replicated)."""
    return NamedSharding(mesh, P("data"))


def stacked_batch_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for a [K, B, ...] stack of K batches (the scan_steps fused
    dispatch): scan dim replicated, batch dim sharded over 'data'."""
    return NamedSharding(mesh, P(None, "data"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
