"""Parallelism/runtime layer (SURVEY.md §2 layer 3, §3 #13-18).

The reference scaled with torch-DDP gradient all-reduce over NCCL
(BASELINE.json:5). The TPU-native equivalent implemented here is GSPMD:
construct a `jax.sharding.Mesh` with ('data', 'model') axes, annotate the
batch over 'data' (DP) and the transformer matmuls over 'model' (TP), and
let XLA insert psum / all-gather / reduce-scatter over ICI inside the one
compiled program. There is no user-level collective call on the train path.
"""
from dnn_page_vectors_tpu.parallel.mesh import (
    make_mesh, fit_mesh_to_devices, multihost_init)
from dnn_page_vectors_tpu.parallel.sharding import (
    batch_sharding, replicated, param_shardings, shard_params)

__all__ = ["make_mesh", "fit_mesh_to_devices", "multihost_init",
           "batch_sharding", "replicated", "param_shardings", "shard_params"]
