"""Multi-host inference utilities (SURVEY.md §4.2; VERDICT r3 Missing #1).

Training is SPMD: every process enters the same jitted program and XLA's
collectives stitch the global batch together (parallel/mesh.py). Bulk
inference is the opposite shape: ``encode_page`` has NO cross-example
communication, so a multi-host embed job gains nothing from global-mesh
lockstep — it only inherits its failure modes (every dispatch blocks on the
slowest host; outputs land non-addressable and cannot be written to the
local store). The TPU-native design is per-host independence:

  * each process builds a mesh over ONLY its local devices (`local_mesh`),
  * embeds a disjoint set of store shards (``si % process_count ==
    process_index``, infer/bulk_embed.py) and writes them under its own
    writer manifest (infer/vector_store.py),
  * and the only cross-process traffic is barriers and tiny host-value
    allgathers (recall hit counts, mined negative tables) — never vectors.

Every helper degrades to a no-op in the single-process case so callers need
no branching.
"""
from __future__ import annotations

from typing import Any, List, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from dnn_page_vectors_tpu.config import MeshConfig
from dnn_page_vectors_tpu.parallel.mesh import fit_mesh_to_devices, make_mesh


def process_info() -> Tuple[int, int]:
    return jax.process_index(), jax.process_count()


def partition_shard_ranges(counts: Sequence[int], parts: int
                           ) -> List[Tuple[int, int]]:
    """Contiguous [lo, hi) shard-index ranges splitting `counts` (rows per
    shard, in shard order) into at most `parts` partitions balanced by row
    count — the ownership map of partitioned serving (infer/partition.py,
    docs/SCALING.md "Partitioned serving"): partition p owns shards
    [lo_p, hi_p), its slice of the IVF posting lists, and its cut of the
    HBM hot set. Contiguity is the point: a partition's id space is an
    interval, so in a real multi-host deployment each host's shard files,
    posting files, and append ranges stay disjoint on disk and the
    existing per-writer append leases give mutual exclusion unchanged.

    Deterministic (pure arithmetic over the shard table): every host —
    or every host-simulated worker — derives the identical split from the
    same manifest. `parts` is clamped to the shard count; every returned
    range is non-empty."""
    n = len(counts)
    if n == 0:
        return [(0, 0)]
    P = max(1, min(int(parts), n))
    cum = np.cumsum(np.asarray(counts, np.int64))
    total = int(cum[-1])
    cuts: List[int] = []
    prev = 0
    for p in range(1, P):
        target = total * p / P
        j = int(np.searchsorted(cum, target))
        # cut on whichever side of the target is closer (ties take the
        # extra shard): cutting at j puts cum[j-1] rows left of the cut,
        # at j+1 puts cum[j]
        if j < n and abs(int(cum[j]) - target) <= \
                abs((int(cum[j - 1]) if j else 0) - target):
            j += 1
        # keep every partition non-empty: at least one shard on each side
        j = max(prev + 1, min(j, n - (P - p)))
        cuts.append(j)
        prev = j
    bounds = [0] + cuts + [n]
    return list(zip(bounds[:-1], bounds[1:]))


def barrier(name: str) -> None:
    """Blocks until every process reaches the same named point."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(name)


def allgather_hosts(x: np.ndarray) -> np.ndarray:
    """[process_count, ...] stack of every process's host value. The value
    must have the same shape/dtype on all processes (pad first if not)."""
    if jax.process_count() == 1:
        return np.asarray(x)[None]
    from jax.experimental import multihost_utils
    return np.asarray(multihost_utils.process_allgather(np.asarray(x)))


def local_mesh(cfg: MeshConfig) -> Mesh:
    """A mesh over THIS process's devices only, with the config's model/seq
    axes preserved where the local device count allows."""
    devs = jax.local_devices()
    fitted = fit_mesh_to_devices(cfg, devices=devs)
    return make_mesh(fitted, devices=devs)


def is_local_mesh(mesh: Mesh) -> bool:
    pi = jax.process_index()
    return all(d.process_index == pi for d in mesh.devices.flat)


def inference_mesh(cfg: MeshConfig, fallback: Mesh) -> Mesh:
    """The mesh embed/eval/mine should run on: the caller's (global) mesh in
    the single-process case, a process-local mesh under multi-process."""
    if jax.process_count() == 1:
        return fallback
    return local_mesh(cfg)


def host_replicated_copy(tree: Any) -> Any:
    """Numpy copy of a (replicated) global pytree, so it can be re-placed on
    a process-local mesh. TP-sharded params spanning hosts cannot be pulled
    this way — restore them from a checkpoint directly onto the target mesh
    instead (orbax restores into any sharding)."""
    def _one(x):
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            if not x.is_fully_replicated:
                raise ValueError(
                    "param is sharded across processes; multi-host inference "
                    "re-places params on a process-local mesh and needs them "
                    "replicated (pure DP) — for cross-host TP params, restore "
                    "the checkpoint onto the local mesh instead")
        return np.asarray(x)
    return jax.tree_util.tree_map(_one, tree)
