"""Device-mesh construction + multi-host control plane (SURVEY.md §3 #18).

Mesh axes:
  * 'data'  — data parallelism; batch dim sharded here; gradient psum rides
              ICI (within a slice) / DCN (across slices), replacing the
              reference's NCCL all-reduce (BASELINE.json:5).
  * 'model' — tensor parallelism for the big transformer matmuls (mT5-base
              config; SURVEY.md §3 #14).

`jax.distributed.initialize` is the only cross-process step in the whole
framework (SURVEY.md §4.5); every later collective lives inside compiled
XLA programs.
"""
from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh

from dnn_page_vectors_tpu.config import MeshConfig


def multihost_init(coordinator: Optional[str] = None,
                   num_processes: Optional[int] = None,
                   process_id: Optional[int] = None) -> None:
    """One process per TPU host. No-op when single-process (the common dev
    case and the sandbox case). On a real pod slice the TPU runtime provides
    coordinator/topology via env and bare initialize() suffices."""
    if num_processes is not None and num_processes > 1:
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=num_processes,
                                   process_id=process_id)
    elif os.environ.get("JAX_COORDINATOR_ADDRESS"):
        jax.distributed.initialize()


def make_mesh(cfg: MeshConfig, devices: Optional[list] = None) -> Mesh:
    """('data', 'model', 'seq') mesh; size-1 axes cost nothing and keep every
    PartitionSpec in the codebase valid on every mesh. `devices` defaults to
    all devices (the SPMD training mesh); multihost.local_mesh passes
    jax.local_devices() for the per-host inference meshes."""
    if devices is None:
        devices = jax.devices()
    need = cfg.num_devices
    if len(devices) < need:
        raise ValueError(
            f"mesh {cfg.data}x{cfg.model}x{cfg.seq} needs {need} devices, "
            f"have {len(devices)}; use fit_mesh_to_devices() for dev runs")
    arr = np.asarray(devices[:need]).reshape(cfg.data, cfg.model, cfg.seq)
    return Mesh(arr, ("data", "model", "seq"))


def fit_mesh_to_devices(cfg: MeshConfig,
                        devices: Optional[list] = None) -> MeshConfig:
    """Shrink a config's mesh to the devices actually present, preserving the
    model and seq axes when possible. Lets the v5p-64 configs run in the
    1-chip sandbox / 8-fake-device CPU tests unchanged."""
    n = len(devices if devices is not None else jax.devices())
    model = min(cfg.model, n)
    while n % model:
        model -= 1
    rem = n // model
    seq = min(cfg.seq, rem)
    while rem % seq:
        seq -= 1
    rem //= seq
    data = min(cfg.data, rem)
    while rem % data:
        data -= 1
    return MeshConfig(data=data, model=model, seq=seq, strict=cfg.strict)
