"""Ring attention: exact sequence/context parallelism over a 'seq' mesh axis.

Long pages are sharded along the sequence dimension across devices. Each
device keeps its local Q block resident and accumulates online-softmax
statistics (running max m, denominator l, f32 accumulator) against one KV
block at a time while `lax.ppermute` rotates the KV blocks (+ their padding
mask) around the ring — after axis_size steps every device has seen the full
global sequence and holds the exact attention output for its Q shard.
Communication rides ICI neighbor-to-neighbor (the ring), overlapping with
the per-block compute; peak memory per device is O(L_local) instead of O(L).

This is the TPU-native answer to the reference's long-context scaling
requirement: the collective is compiled by XLA (no user-level NCCL), and the
same function body runs under `jax.shard_map` on any ('data','model','seq')
mesh. Used by the transformer towers when model.attention == "ring".

T5 relative-position bias across the ring: materialising the global
[H, L, L] bias would reintroduce the O(L²) memory the ring removes, so each
step instead rebuilds its [L_loc, L_loc] bias block from global positions —
a device at ring position d processing ring step t holds the KV block of
device (d - t) mod n, so both sides' global offsets are known and the
bucket->table gather is recomputed per step in VMEM-sized pieces.
"""
from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

_NEG_INF = -1e30


def _ring_attention_local(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                          kv_mask: jnp.ndarray,
                          bias_table: Optional[jnp.ndarray],
                          axis_name: str,
                          bucket_fn: Optional[Callable] = None) -> jnp.ndarray:
    """Per-shard body (runs under shard_map).

    q, k, v: [B, H, L_loc, Dh] local blocks; kv_mask: [B, L_loc];
    bias_table: optional [num_buckets, H] T5 relative-position table
    (replicated), with bucket_fn mapping signed distances to bucket ids.
    Returns [B, H, L_loc, Dh] float32 — the exact global-attention output
    for the local queries.
    """
    from dnn_page_vectors_tpu.utils.compat import axis_size
    n = axis_size(axis_name)
    my = lax.axis_index(axis_name)
    scale = 1.0 / np.sqrt(q.shape[-1])
    qf = q.astype(jnp.float32) * scale
    B, H, L, Dh = q.shape
    q_pos = my * L + jnp.arange(L)                           # global q rows

    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, t):
        acc, m, l, k_cur, v_cur, mask_cur = carry
        s = jnp.einsum("bhld,bhsd->bhls", qf, k_cur.astype(jnp.float32),
                       preferred_element_type=jnp.float32)
        if bias_table is not None:
            # KV block now resident came from ring position (my - t) mod n
            kv_pos = ((my - t) % n) * L + jnp.arange(L)
            buckets = bucket_fn(kv_pos[None, :] - q_pos[:, None])  # [L, L]
            bias = bias_table[buckets]                       # [L, L, H]
            s = s + bias.transpose(2, 0, 1)[None].astype(jnp.float32)
        s = jnp.where(mask_cur[:, None, None, :], s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l = alpha * l + p.sum(axis=-1)
        acc = acc * alpha[..., None] + jnp.einsum(
            "bhls,bhsd->bhld", p, v_cur.astype(jnp.float32),
            preferred_element_type=jnp.float32)
        # rotate KV + mask to the next device; overlaps with next compute
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        mask_nxt = lax.ppermute(mask_cur, axis_name, perm)
        return (acc, m_new, l, k_nxt, v_nxt, mask_nxt), None

    acc0 = jnp.zeros((B, H, L, Dh), jnp.float32)
    m0 = jnp.full((B, H, L), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, L), jnp.float32)
    (acc, _, l, _, _, _), _ = lax.scan(
        step, (acc0, m0, l0, k, v, kv_mask), jnp.arange(n))
    return acc / jnp.maximum(l, 1e-30)[..., None]


def ring_attention(mesh: Mesh, q: jnp.ndarray, k: jnp.ndarray,
                   v: jnp.ndarray, kv_mask: jnp.ndarray,
                   bias_table: Optional[jnp.ndarray] = None,
                   bucket_fn: Optional[Callable] = None,
                   seq_axis: str = "seq", batch_axis: Optional[str] = "data"
                   ) -> jnp.ndarray:
    """shard_map wrapper: q/k/v [B, H, L, Dh] with L sharded over `seq_axis`
    (and B over `batch_axis` if present in the mesh); kv_mask [B, L].
    bias_table [num_buckets, H] + bucket_fn enable the T5 variant (bias is
    rebuilt per ring step from global positions — see module docstring)."""
    n_seq = mesh.shape[seq_axis]
    if q.shape[2] % n_seq or k.shape[2] % n_seq:
        raise ValueError(
            f"ring attention: sequence length {q.shape[2]} must be divisible "
            f"by mesh axis '{seq_axis}' of size {n_seq}; pad "
            "data.page_len/query_len to a multiple of mesh.seq")
    if (bias_table is None) != (bucket_fn is None):
        raise ValueError("bias_table and bucket_fn must be given together")
    qkv_spec = P(batch_axis, None, seq_axis, None)
    mask_spec = P(batch_axis, seq_axis)
    fn = functools.partial(_ring_attention_local, axis_name=seq_axis,
                           bucket_fn=bucket_fn)
    if bias_table is None:
        fn_ = lambda q_, k_, v_, m_: fn(q_, k_, v_, m_, None)
        in_specs = (qkv_spec, qkv_spec, qkv_spec, mask_spec)
        args = (q, k, v, kv_mask)
    else:
        fn_ = fn
        in_specs = (qkv_spec, qkv_spec, qkv_spec, mask_spec, P())
        args = (q, k, v, kv_mask, bias_table)
    from dnn_page_vectors_tpu.utils.compat import shard_map_unchecked
    return shard_map_unchecked(
        fn_, mesh=mesh, in_specs=in_specs, out_specs=qkv_spec,
    )(*args)
