"""Ring attention: exact sequence/context parallelism over a 'seq' mesh axis.

Long pages are sharded along the sequence dimension across devices. Each
device keeps its local Q block resident and accumulates online-softmax
statistics (running max m, denominator l, f32 accumulator) against one KV
block at a time while `lax.ppermute` rotates the KV blocks (+ their padding
mask) around the ring — after axis_size steps every device has seen the full
global sequence and holds the exact attention output for its Q shard.
Communication rides ICI neighbor-to-neighbor (the ring), overlapping with
the per-block compute; peak memory per device is O(L_local) instead of O(L).

This is the TPU-native answer to the reference's long-context scaling
requirement: the collective is compiled by XLA (no user-level NCCL), and the
same function body runs under `jax.shard_map` on any ('data','model','seq')
mesh. Used by the transformer towers when model.attention == "ring".
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

_NEG_INF = -1e30


def _ring_attention_local(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                          kv_mask: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """Per-shard body (runs under shard_map).

    q, k, v: [B, H, L_loc, Dh] local blocks; kv_mask: [B, L_loc].
    Returns [B, H, L_loc, Dh] float32 — the exact global-attention output
    for the local queries.
    """
    n = lax.axis_size(axis_name)
    scale = 1.0 / np.sqrt(q.shape[-1])
    qf = q.astype(jnp.float32) * scale
    B, H, L, Dh = q.shape

    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, _):
        acc, m, l, k_cur, v_cur, mask_cur = carry
        s = jnp.einsum("bhld,bhsd->bhls", qf, k_cur.astype(jnp.float32),
                       preferred_element_type=jnp.float32)
        s = jnp.where(mask_cur[:, None, None, :], s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l = alpha * l + p.sum(axis=-1)
        acc = acc * alpha[..., None] + jnp.einsum(
            "bhls,bhsd->bhld", p, v_cur.astype(jnp.float32),
            preferred_element_type=jnp.float32)
        # rotate KV + mask to the next device; overlaps with next compute
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        mask_nxt = lax.ppermute(mask_cur, axis_name, perm)
        return (acc, m_new, l, k_nxt, v_nxt, mask_nxt), None

    acc0 = jnp.zeros((B, H, L, Dh), jnp.float32)
    m0 = jnp.full((B, H, L), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, L), jnp.float32)
    (acc, _, l, _, _, _), _ = lax.scan(
        step, (acc0, m0, l0, k, v, kv_mask), None, length=n)
    return acc / jnp.maximum(l, 1e-30)[..., None]


def ring_attention(mesh: Mesh, q: jnp.ndarray, k: jnp.ndarray,
                   v: jnp.ndarray, kv_mask: jnp.ndarray,
                   seq_axis: str = "seq", batch_axis: Optional[str] = "data"
                   ) -> jnp.ndarray:
    """shard_map wrapper: q/k/v [B, H, L, Dh] with L sharded over `seq_axis`
    (and B over `batch_axis` if present in the mesh); kv_mask [B, L]."""
    n_seq = mesh.shape[seq_axis]
    if q.shape[2] % n_seq or k.shape[2] % n_seq:
        raise ValueError(
            f"ring attention: sequence length {q.shape[2]} must be divisible "
            f"by mesh axis '{seq_axis}' of size {n_seq}; pad "
            "data.page_len/query_len to a multiple of mesh.seq")
    qkv_spec = P(batch_axis, None, seq_axis, None)
    mask_spec = P(batch_axis, seq_axis)
    fn = functools.partial(_ring_attention_local, axis_name=seq_axis)
    return jax.shard_map(
        fn, mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, mask_spec),
        out_specs=qkv_spec,
        check_vma=False,
    )(q, k, v, kv_mask)
