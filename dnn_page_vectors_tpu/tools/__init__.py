"""Developer tooling that ships inside the package (stdlib-only)."""
