"""graftcheck — project-invariant static analysis (docs/ANALYSIS.md).

    python -m dnn_page_vectors_tpu.cli lint            # JSON report, rc!=0
    python -m dnn_page_vectors_tpu.cli lint --write-baseline

Nine rule families turn the repo's load-bearing conventions into
machine-checked rules: determinism (seeded RNG / no wall clock on
byte-pinned paths), lock discipline (`# guarded-by:` annotations),
lock-order / deadlock analysis (`# lock-order:` hierarchy declarations),
thread & resource lifecycle (join/daemon/close-on-error-path), asyncio
hygiene (no blocking calls on the event loop), jit purity + host-sync
hygiene, manifest-mediated file I/O, wire-protocol conformance (the DPV1
frame table), and doc/knob/marker drift. Stdlib-only: runs without jax
installed.
"""
from dnn_page_vectors_tpu.tools.analyze.core import (  # noqa: F401
    BASELINE_NAME, REPO_ROOT, RULES, FileContext, Finding, ProjectContext,
    Report, Rule, analyze, analyze_source, load_baseline, write_baseline)

# importing the rule modules registers every rule with the registry
from dnn_page_vectors_tpu.tools.analyze import (  # noqa: F401,E402
    rules_async, rules_determinism, rules_drift, rules_io, rules_jit,
    rules_lifecycle, rules_lockorder, rules_locks, rules_proto)

RULE_FAMILIES = sorted({r.family for r in RULES.values()})
