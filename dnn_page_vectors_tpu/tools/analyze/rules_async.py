"""Rule family 8 — asyncio hygiene (docs/ANALYSIS.md).

The front end (`infer/server.py`) frames every client connection on ONE
event loop; a single blocking call inside an `async def` stalls every
connection at once — the whole point of dispatching the device work to an
executor evaporates, silently, and only under load. The loop-discipline
contract, machine-checked:

  * no blocking primitives on the loop: `time.sleep` (use
    `asyncio.sleep`), blocking socket constructors/methods
    (`socket.create_connection`, `.recv`/`.sendall`/`.accept`), bare
    `open(...)` file I/O, or direct device pulls (`jax.device_get`,
    `block_until_ready`) — device work belongs behind `run_in_executor`;
  * every `create_task`/`ensure_future` result is stored or awaited — a
    discarded task is garbage-collected mid-flight and its exceptions
    vanish (the "fire and forget and lose" bug);
  * no handler swallows `asyncio.CancelledError`: a bare `except:` (or
    `except BaseException:`) without a re-raise eats the cancellation a
    graceful shutdown depends on. `except Exception:` is fine —
    CancelledError does not inherit from it.

Sync helpers *called from* async code are out of scope here — they run on
the executor; only the `async def` bodies themselves are the event loop's
territory. Nested sync defs and lambdas inside an async function are
skipped for the same reason (they are executor payloads).
"""
from __future__ import annotations

import ast
from typing import Iterator, Optional

from dnn_page_vectors_tpu.tools.analyze.core import (
    FileContext, Finding, Rule, qualname, register)

_BLOCKING_CALLS = {
    "time.sleep": "`time.sleep` blocks the event loop — "
                  "`await asyncio.sleep(...)`",
    "socket.create_connection": "blocking socket dial on the event loop "
                                "— use asyncio.open_connection",
    "socket.socketpair": "blocking socket setup on the event loop",
    "socket.getaddrinfo": "blocking DNS resolution on the event loop — "
                          "use loop.getaddrinfo",
    "jax.device_get": "device pull on the event loop — dispatch through "
                      "run_in_executor",
    "jax.block_until_ready": "device sync on the event loop — dispatch "
                             "through run_in_executor",
}
_BLOCKING_METHODS = {"recv", "recv_into", "sendall", "accept",
                     "block_until_ready"}


def _own_async_nodes(fn: ast.AsyncFunctionDef):
    """Nodes belonging to this async def's own body — nested defs and
    lambdas pruned (they execute elsewhere, usually on the executor)."""
    stack = list(fn.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


@register
class AsyncHygieneRule(Rule):
    name = "async-hygiene"
    family = "async"
    doc = ("no blocking calls / file I/O / device pulls inside `async "
           "def`; create_task results kept; no bare except swallowing "
           "CancelledError")
    scope = None          # any module may grow an async def

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.AsyncFunctionDef):
                yield from self._check_async(ctx, node)

    def _check_async(self, ctx: FileContext,
                     fn: ast.AsyncFunctionDef) -> Iterator[Finding]:
        for node in _own_async_nodes(fn):
            if isinstance(node, ast.Call):
                yield from self._check_call(ctx, fn, node)
            elif isinstance(node, ast.Expr) \
                    and isinstance(node.value, ast.Call):
                f = node.value.func
                if isinstance(f, ast.Attribute) \
                        and f.attr in ("create_task", "ensure_future"):
                    yield ctx.finding(
                        self.name, node,
                        f"`{f.attr}` result discarded — the task can be "
                        "garbage-collected mid-flight and its exception "
                        "is lost; store the handle or await it")
            elif isinstance(node, ast.ExceptHandler):
                yield from self._check_handler(ctx, node)

    def _check_call(self, ctx: FileContext, fn: ast.AsyncFunctionDef,
                    call: ast.Call) -> Iterator[Finding]:
        q = qualname(call.func, ctx.aliases)
        if q in _BLOCKING_CALLS:
            yield ctx.finding(
                self.name, call,
                f"{_BLOCKING_CALLS[q]} (inside `async def {fn.name}`)")
        elif isinstance(call.func, ast.Name) and call.func.id == "open":
            yield ctx.finding(
                self.name, call,
                f"file I/O on the event loop (inside `async def "
                f"{fn.name}`) — run it on the executor")
        elif (isinstance(call.func, ast.Attribute)
              and call.func.attr in _BLOCKING_METHODS):
            yield ctx.finding(
                self.name, call,
                f"blocking `.{call.func.attr}(...)` on the event loop "
                f"(inside `async def {fn.name}`) — use the stream/"
                "executor API")

    def _check_handler(self, ctx: FileContext,
                       handler: ast.ExceptHandler) -> Iterator[Finding]:
        bare = handler.type is None
        broad = self._names_base_exception(ctx, handler.type)
        if not (bare or broad):
            return
        reraises = any(isinstance(n, ast.Raise) and n.exc is None
                       for st in handler.body for n in ast.walk(st))
        if reraises:
            return
        what = "bare `except:`" if bare else "`except BaseException:`"
        yield ctx.finding(
            self.name, handler,
            f"{what} inside an async def swallows CancelledError — a "
            "graceful shutdown can no longer cancel this coroutine; "
            "catch `Exception` (CancelledError is not one) or re-raise")

    @staticmethod
    def _names_base_exception(ctx: FileContext,
                              type_node: Optional[ast.AST]) -> bool:
        if type_node is None:
            return False
        nodes = (list(type_node.elts)
                 if isinstance(type_node, ast.Tuple) else [type_node])
        return any(qualname(n, ctx.aliases) == "BaseException"
                   for n in nodes)
