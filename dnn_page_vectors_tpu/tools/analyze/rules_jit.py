"""Rule family 3 — jit purity and host-sync hygiene (docs/ANALYSIS.md).

`jit-purity`: a function under `@jax.jit` / `shard_map` traces ONCE; Python
side effects inside it (print, telemetry/registry calls, appending to
captured lists) run at trace time only and then silently never again —
a classic source of "my counter stopped moving" bugs. Flagged in the
compiled-op homes (`ops/`, `index/`, `models/`).

`host-sync`: functions annotated `# graftcheck: hot` (the serving dispatch
and train-step inner loops) must not force a device->host sync per element
— `.item()`, `np.asarray`, `jax.device_get`, `block_until_ready`, or
`float(...)`/`int(...)` of an expression. A hot loop earns ONE packed
transfer at the end; anything per-row is a latency cliff. Intended syncs
carry a reasoned pragma so the contract stays visible in the diff.
"""
from __future__ import annotations

import ast
from typing import Iterator, Optional, Set

from dnn_page_vectors_tpu.tools.analyze.core import (
    FileContext, Finding, Rule, qualname, register, PKG_NAME)

_JIT_NAMES = {"jax.jit", "jit"}
_SHARD_NAMES = {"shard_map", "jax.experimental.shard_map.shard_map"}
_SYNC_CALLS = {"numpy.asarray", "numpy.array", "jax.device_get"}
_SYNC_METHODS = {"item", "block_until_ready"}
_MUTATORS = {"append", "extend", "add", "update", "pop", "insert",
             "setdefault", "remove", "clear"}


def _is_jit_decorated(fn, aliases) -> Optional[str]:
    """The decorator spelling when fn is jit/shard_map-compiled."""
    for dec in fn.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        q = qualname(target, aliases)
        if q in _JIT_NAMES or (q and q.split(".")[-1] == "shard_map"):
            return q
        if q in ("functools.partial", "partial") and isinstance(dec, ast.Call):
            if dec.args:
                inner = qualname(dec.args[0], aliases)
                if inner in _JIT_NAMES or (
                        inner and inner.split(".")[-1] == "shard_map"):
                    return f"partial({inner})"
    return None


def _local_names(fn) -> Set[str]:
    names: Set[str] = set()
    args = fn.args
    for a in (args.posonlyargs + args.args + args.kwonlyargs
              + ([args.vararg] if args.vararg else [])
              + ([args.kwarg] if args.kwarg else [])):
        names.add(a.arg)
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            names.add(node.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            names.add(node.name)
    return names


@register
class JitPurityRule(Rule):
    name = "jit-purity"
    family = "jit"
    doc = ("Python side effects (print / registry events / captured-state "
           "mutation) inside jit- or shard_map-compiled functions")
    scope = (f"{PKG_NAME}/ops/", f"{PKG_NAME}/index/", f"{PKG_NAME}/models/")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            how = _is_jit_decorated(fn, ctx.aliases)
            if how is None:
                continue
            local = _local_names(fn)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                if (isinstance(node.func, ast.Name)
                        and node.func.id == "print"):
                    yield ctx.finding(
                        self.name, node,
                        f"print() inside `@{how}` runs at trace time only "
                        "— use jax.debug.print or hoist to the host")
                elif isinstance(node.func, ast.Attribute):
                    q = qualname(node.func, ctx.aliases) or ""
                    if node.func.attr == "event" or ".registry" in q or \
                            q.startswith("registry."):
                        yield ctx.finding(
                            self.name, node,
                            f"telemetry call inside `@{how}` fires once at "
                            "trace time — emit from the host caller")
                    elif (node.func.attr in _MUTATORS
                          and isinstance(node.func.value, ast.Name)
                          and node.func.value.id not in local):
                        yield ctx.finding(
                            self.name, node,
                            f"`{node.func.value.id}.{node.func.attr}(...)` "
                            f"mutates captured state inside `@{how}` — "
                            "trace-time-only side effect")


@register
class HostSyncRule(Rule):
    name = "host-sync"
    family = "jit"
    doc = ("per-element device->host syncs inside `# graftcheck: hot` "
           "serving-dispatch / train-step loops")
    scope = None        # fires only on annotated functions, package-wide

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not ctx.is_hot(fn):
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                q = qualname(node.func, ctx.aliases)
                if q in _SYNC_CALLS:
                    yield ctx.finding(
                        self.name, node,
                        f"`{q}(...)` in a hot loop forces a device sync — "
                        "batch the transfer outside, or pragma with the "
                        "reason it is the one packed d2h")
                elif (isinstance(node.func, ast.Attribute)
                      and node.func.attr in _SYNC_METHODS):
                    yield ctx.finding(
                        self.name, node,
                        f"`.{node.func.attr}()` in a hot loop is a "
                        "per-call device sync")
                elif (isinstance(node.func, ast.Name)
                      and node.func.id in ("float", "int") and node.args
                      and isinstance(node.args[0], (ast.Call, ast.Subscript,
                                                    ast.Attribute))):
                    yield ctx.finding(
                        self.name, node,
                        f"`{node.func.id}(...)` of an expression in a hot "
                        "loop blocks on the device if the value is an "
                        "array — hoist or pragma with a reason")
