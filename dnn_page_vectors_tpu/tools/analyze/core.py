"""graftcheck core: the rule registry, pragma/baseline machinery, and the
analysis runner (docs/ANALYSIS.md).

The analyzer is stdlib-only (`ast` + `tokenize`) on purpose: `cli lint` and
the tier-1 `lint`-marked tests must run on a box with no jax/numpy installed,
so the project invariants stay enforceable everywhere the source checks out.

Vocabulary:
  * A *rule* inspects one parsed file (`Rule.check`) or the whole project
    tree (`Rule.project = True`, `Rule.check_project`) and yields `Finding`s.
  * A *pragma* is an in-source suppression comment:
        # graftcheck: off=rule-a,rule-b -- <mandatory reason>
    On a code line it suppresses that line; on a comment-only line ABOVE
    the module's first statement it suppresses the whole file; on any
    other comment-only line it suppresses the next code line. `off`
    without `=rules` covers every rule. A pragma WITHOUT a reason
    suppresses nothing and is itself reported (rule `pragma`), so
    silence always carries a justification.
        # graftcheck: hot
    on a `def` line marks a serving/train hot loop for the host-sync rule.
  * The *baseline* is a JSON file of accepted pre-existing findings keyed on
    (rule, path, stripped source line) — line-number free, so renumbering a
    file never invalidates it. Baselined findings don't fail the run; keys
    that no longer match anything are reported as stale so the file only
    ever shrinks.
"""
from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import re
import tokenize
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

# tools/analyze/core.py -> tools/analyze -> tools -> package -> repo root
PKG_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
REPO_ROOT = os.path.dirname(PKG_ROOT)
PKG_NAME = os.path.basename(PKG_ROOT)
BASELINE_NAME = ".graftcheck-baseline.json"

PRAGMA_RE = re.compile(
    r"#\s*graftcheck:\s*(off|hot)\b(?:=([\w,-]+))?(?:\s*--\s*(\S.*))?")
# the marker may trail prose inside the comment ("# (ts, value) pairs;
# guarded-by: _lock") but must live in a comment, not a docstring
GUARDED_BY_RE = re.compile(r"#.*?\bguarded-by:\s*(?:self\.)?([A-Za-z_]\w*)")
HOLDS_LOCK_RE = re.compile(r"#.*?\bholds-lock:\s*(?:self\.)?([A-Za-z_]\w*)")


# ---------------------------------------------------------------------------
# findings
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str            # repo-relative, posix separators
    line: int
    col: int
    msg: str
    snippet: str = ""    # stripped source line; the line-number-free half
                         # of the baseline key

    @property
    def key(self) -> str:
        return f"{self.rule}::{self.path}::{self.snippet}"

    def human(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] {self.msg}"

    def to_dict(self) -> Dict[str, object]:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class Pragma:
    kind: str                        # "off" | "hot"
    rules: Optional[Tuple[str, ...]]  # None = every rule
    reason: str
    line: int
    file_scope: bool

    def covers(self, rule: str) -> bool:
        return self.rules is None or rule in self.rules


# ---------------------------------------------------------------------------
# per-file context
# ---------------------------------------------------------------------------

def _collect_aliases(tree: ast.AST) -> Dict[str, str]:
    """Local name -> dotted import path, e.g. {"np": "numpy",
    "jit": "jax.jit"}. Names never imported resolve to themselves so
    un-aliased module-style chains (`time.time`) still qualify."""
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
            for a in node.names:
                if a.name == "*":
                    continue
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def qualname(node: ast.AST, aliases: Dict[str, str]) -> Optional[str]:
    """Resolve a Name/Attribute chain to a dotted path through the file's
    imports; None for anything rooted in an expression (calls, subscripts,
    `self.x`, ...)."""
    if isinstance(node, ast.Name):
        return aliases.get(node.id, node.id)
    if isinstance(node, ast.Attribute):
        base = qualname(node.value, aliases)
        return None if base is None else f"{base}.{node.attr}"
    return None


class FileContext:
    """One parsed source file handed to every in-scope file rule.

    Pragmas (and the comment tokens behind them) are parsed lazily on
    first use: most files carry no `graftcheck:` marker at all, and the
    tokenize pass is the expensive half of context construction — the
    `cli lint --changed` fast path leans on skipping it."""

    def __init__(self, relpath: str, source: str):
        self.path = relpath.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source)
        self.aliases = _collect_aliases(self.tree)
        self._pragmas: Optional[List[Pragma]] = None
        self._comments: Optional[List[Tuple[int, str]]] = None

    @property
    def comments(self) -> List[Tuple[int, str]]:
        """(lineno, text) of every real COMMENT token in the file."""
        if self._comments is None:
            self._comments = ([] if "#" not in self.source
                              else list(iter_comments(self.source)))
        return self._comments

    @property
    def pragmas(self) -> List["Pragma"]:
        if self._pragmas is None:
            raw = (parse_pragmas(self.source)
                   if "graftcheck:" in self.source else [])
            self._pragmas = self._resolve_pragmas(raw)
        return self._pragmas

    def _resolve_pragmas(self, raw: List["Pragma"]) -> List["Pragma"]:
        """Comment-only `off` pragmas above the first statement keep file
        scope; later ones re-anchor to the next code line (the
        disable-next-line idiom, so long lines need no trailing tag)."""
        body = self.tree.body
        first_code = body[0].lineno if body else len(self.lines) + 1
        if (body and isinstance(body[0], ast.Expr)
                and isinstance(body[0].value, ast.Constant)
                and isinstance(body[0].value.value, str)):
            first_code = (body[1].lineno if len(body) > 1
                          else len(self.lines) + 1)
        out = []
        for p in raw:
            if (p.kind == "off" and p.file_scope
                    and p.line >= first_code):
                target = p.line
                for i in range(p.line, len(self.lines)):
                    text = self.lines[i].strip()
                    if text and not text.startswith("#"):
                        target = i + 1
                        break
                p = dataclasses.replace(p, file_scope=False, line=target)
            out.append(p)
        return out

    def snippet(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def finding(self, rule: str, node_or_line, msg: str) -> Finding:
        if isinstance(node_or_line, int):
            line, col = node_or_line, 0
        else:
            line, col = node_or_line.lineno, node_or_line.col_offset
        return Finding(rule, self.path, line, col, msg, self.snippet(line))

    def is_hot(self, fn: ast.AST) -> bool:
        """True when the def's signature lines — or the comment line
        directly above the def — carry `# graftcheck: hot`."""
        body_start = fn.body[0].lineno if getattr(fn, "body", None) else (
            fn.lineno + 1)
        hot = {p.line for p in self.pragmas if p.kind == "hot"}
        return any(ln in hot
                   for ln in range(fn.lineno - 1, body_start + 1))

    def guarded_by(self, line: int) -> Optional[str]:
        """Lock name from a `# guarded-by: <lock>` comment on this line."""
        m = GUARDED_BY_RE.search(self.snippet(line))
        return m.group(1) if m else None

    def holds_lock(self, fn: ast.AST) -> frozenset:
        """Locks a `# holds-lock: <lock>` comment on the def's signature
        lines (or the line above) asserts every caller already holds —
        the called-with-lock-held helper contract."""
        body_start = fn.body[0].lineno if getattr(fn, "body", None) else (
            fn.lineno + 1)
        locks = set()
        for ln in range(fn.lineno - 1, body_start + 1):
            m = HOLDS_LOCK_RE.search(self.snippet(ln))
            if m:
                locks.add(m.group(1))
        return frozenset(locks)


def iter_comments(source: str) -> Iterator[Tuple[int, str]]:
    """(lineno, text) for every COMMENT token; tolerant of half-written
    fixtures the tokenizer chokes on."""
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                yield tok.start[0], tok.string
    except (tokenize.TokenError, IndentationError):
        return


def parse_pragmas(source: str) -> List[Pragma]:
    pragmas: List[Pragma] = []
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError):  # half-written fixture
        tokens = []
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = PRAGMA_RE.search(tok.string)
        if not m:
            continue
        kind, rules, reason = m.group(1), m.group(2), m.group(3)
        code_prefix = tok.line[:tok.start[1]].strip()
        pragmas.append(Pragma(
            kind=kind,
            rules=tuple(r for r in rules.split(",") if r) if rules else None,
            reason=(reason or "").strip(),
            line=tok.start[0],
            file_scope=not code_prefix))
    return pragmas


# ---------------------------------------------------------------------------
# project context (drift rules read config/docs/pytest.ini, not one file)
# ---------------------------------------------------------------------------

class ProjectContext:
    def __init__(self, root: str, pkg: str = PKG_NAME):
        self.root = root
        self.pkg = pkg
        self._cache: Dict[str, Optional[str]] = {}
        self._fctx: Dict[str, Optional[FileContext]] = {}

    def file_context(self, relpath: str) -> Optional[FileContext]:
        """The shared parsed context for a package file (None when the
        file is missing or unparsable — the `parse` finding belongs to
        the runner). Project rules use this instead of re-parsing, so
        one `analyze()` parses every file at most once."""
        if relpath not in self._fctx:
            src = self.read(relpath)
            try:
                self._fctx[relpath] = (None if src is None
                                       else FileContext(relpath, src))
            except SyntaxError:
                self._fctx[relpath] = None
        return self._fctx[relpath]

    def read(self, relpath: str) -> Optional[str]:
        if relpath not in self._cache:
            path = os.path.join(self.root, relpath)
            try:
                with open(path, encoding="utf-8") as f:
                    self._cache[relpath] = f.read()
            except (OSError, UnicodeDecodeError):
                self._cache[relpath] = None
        return self._cache[relpath]

    def glob(self, reldir: str, suffix: str) -> List[str]:
        out: List[str] = []
        base = os.path.join(self.root, reldir)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for name in sorted(filenames):
                if name.endswith(suffix):
                    rel = os.path.relpath(os.path.join(dirpath, name),
                                          self.root)
                    out.append(rel.replace(os.sep, "/"))
        return out

    def finding(self, rule: str, relpath: str, line: int, msg: str,
                snippet: str = "") -> Finding:
        if not snippet:
            text = self.read(relpath)
            if text:
                lines = text.splitlines()
                if 1 <= line <= len(lines):
                    snippet = lines[line - 1].strip()
        return Finding(rule, relpath.replace(os.sep, "/"), line, 0, msg,
                       snippet)


# ---------------------------------------------------------------------------
# rule registry
# ---------------------------------------------------------------------------

class Rule:
    name: str = ""
    family: str = ""          # determinism | locks | jit | io | drift | meta
    doc: str = ""
    # path prefixes (dirs end with "/") or exact repo-relative files this
    # rule inspects; None = every package file (file rules) / n.a. (project)
    scope: Optional[Tuple[str, ...]] = None
    project: bool = False

    def in_scope(self, relpath: str) -> bool:
        if self.scope is None:
            return True
        return any(relpath == s or (s.endswith("/") and relpath.startswith(s))
                   for s in self.scope)

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        return iter(())

    def check_project(self, ctx: ProjectContext) -> Iterator[Finding]:
        return iter(())


RULES: Dict[str, Rule] = {}


def register(cls):
    rule = cls()
    assert rule.name and rule.name not in RULES, rule.name
    RULES[rule.name] = rule
    return cls


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

def load_baseline(path: str) -> Dict[str, Dict[str, object]]:
    """Baseline key -> entry. Missing file = empty baseline."""
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError):
        return {}
    out: Dict[str, Dict[str, object]] = {}
    for entry in data.get("entries", []):
        key = f"{entry['rule']}::{entry['path']}::{entry.get('snippet', '')}"
        out[key] = entry
    return out


def write_baseline(path: str, findings: Sequence[Finding]) -> None:
    entries = sorted(
        ({"rule": f.rule, "path": f.path, "snippet": f.snippet}
         for f in findings),
        key=lambda e: (e["rule"], e["path"], e["snippet"]))
    # dedupe identical keys (several hits on one line collapse to one entry)
    seen, unique = set(), []
    for e in entries:
        k = (e["rule"], e["path"], e["snippet"])
        if k not in seen:
            seen.add(k)
            unique.append(e)
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1, "entries": unique}, f, indent=1,
                  sort_keys=True)
        f.write("\n")


# ---------------------------------------------------------------------------
# runner
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class Report:
    findings: List[Finding]                   # active: fail the run
    suppressed: List[Dict[str, object]]       # pragma'd, with reasons
    baselined: List[Finding]
    stale_baseline: List[str]
    files_scanned: int
    rules_run: List[str]

    @property
    def exit_code(self) -> int:
        return 1 if self.findings else 0

    def to_dict(self) -> Dict[str, object]:
        return {
            "findings": [f.to_dict() for f in self.findings],
            "suppressed": self.suppressed,
            "baselined": [f.to_dict() for f in self.baselined],
            "stale_baseline": list(self.stale_baseline),
            "counts": {
                "findings": len(self.findings),
                "suppressed": len(self.suppressed),
                "baselined": len(self.baselined),
                "stale_baseline": len(self.stale_baseline),
            },
            "files_scanned": self.files_scanned,
            "rules": self.rules_run,
            "exit_code": self.exit_code,
        }


def iter_package_files(root: str, pkg: str = PKG_NAME) -> List[str]:
    ctx = ProjectContext(root, pkg)
    return ctx.glob(pkg, ".py")


def _pragma_findings(ctx_pragmas: Dict[str, List[Pragma]],
                     paths_with_source: Dict[str, FileContext]) -> List[Finding]:
    out: List[Finding] = []
    for path, pragmas in ctx_pragmas.items():
        fctx = paths_with_source.get(path)
        for p in pragmas:
            if p.kind == "off" and not p.reason:
                snippet = fctx.snippet(p.line) if fctx else ""
                out.append(Finding(
                    "pragma", path, p.line, 0,
                    "graftcheck suppression without a reason — append "
                    "`-- <why this is safe>`",
                    snippet))
    return out


def analyze(root: Optional[str] = None,
            rules: Optional[Iterable[str]] = None,
            baseline_path: Optional[str] = None,
            pkg: str = PKG_NAME,
            paths: Optional[Iterable[str]] = None) -> Report:
    """Run the registry over `<root>/<pkg>` plus the project-level rules.

    `rules` restricts to a subset of rule names (default: all). The
    baseline defaults to `<root>/.graftcheck-baseline.json`. `paths`
    (repo-relative) restricts FILE-scoped rules to those files — the
    `cli lint --changed` fast mode; project-level rules (the drift and
    protocol contracts are whole-repo properties) still run everywhere,
    and stale-baseline reporting is suppressed because unscanned files
    cannot vouch for their entries.
    """
    root = root or REPO_ROOT
    if baseline_path is None:
        baseline_path = os.path.join(root, BASELINE_NAME)
    active_rules = [RULES[n] for n in (rules or sorted(RULES))]
    proj = ProjectContext(root, pkg)

    raw: List[Finding] = []
    contexts: Dict[str, FileContext] = {}
    files = iter_package_files(root, pkg)
    restricted = paths is not None
    if restricted:
        wanted = {str(p).replace(os.sep, "/") for p in paths}
        files = [f for f in files if f in wanted]
    for rel in files:
        source = proj.read(rel)
        if source is None:
            continue
        try:
            fctx = FileContext(rel, source)
        except SyntaxError as e:
            raw.append(Finding("parse", rel, e.lineno or 0, 0,
                               f"syntax error: {e.msg}"))
            proj._fctx[rel] = None
            continue
        contexts[rel] = fctx
        proj._fctx[rel] = fctx          # project rules reuse the parse
        for rule in active_rules:
            if rule.project or not rule.in_scope(rel):
                continue
            raw.extend(rule.check(fctx))
    for rule in active_rules:
        if rule.project:
            raw.extend(rule.check_project(proj))

    # pragma application: findings on a .py file consult that file's pragmas
    pragmas_by_path: Dict[str, List[Pragma]] = {
        p: c.pragmas for p, c in contexts.items()}
    for f in raw:
        # project rules may land findings on files outside the package
        # sweep (tests/, config fixtures); parse their pragmas on demand
        if f.path not in pragmas_by_path and f.path.endswith(".py"):
            fc = proj.file_context(f.path)
            if fc is not None:
                contexts[f.path] = fc
                pragmas_by_path[f.path] = fc.pragmas
            elif proj.read(f.path) is not None:
                pragmas_by_path[f.path] = []

    raw.extend(_pragma_findings(pragmas_by_path, contexts))

    active: List[Finding] = []
    suppressed: List[Dict[str, object]] = []
    for f in raw:
        reason = _suppression(f, pragmas_by_path.get(f.path, []))
        if reason is not None:
            d = f.to_dict()
            d["reason"] = reason
            suppressed.append(d)
        else:
            active.append(f)

    baseline = load_baseline(baseline_path)
    matched_keys = set()
    final: List[Finding] = []
    baselined: List[Finding] = []
    for f in active:
        if f.key in baseline:
            matched_keys.add(f.key)
            baselined.append(f)
        else:
            final.append(f)
    stale = [] if restricted else sorted(set(baseline) - matched_keys)

    final.sort(key=lambda f: (f.path, f.line, f.rule))
    return Report(findings=final, suppressed=suppressed, baselined=baselined,
                  stale_baseline=stale, files_scanned=len(files),
                  rules_run=[r.name for r in active_rules])


def _suppression(f: Finding, pragmas: List[Pragma]) -> Optional[str]:
    """Reason string when a reasoned `off` pragma covers this finding."""
    if f.rule == "pragma":
        return None          # the meta-rule cannot be pragma'd away
    for p in pragmas:
        if p.kind != "off" or not p.reason or not p.covers(f.rule):
            continue
        if p.file_scope or p.line == f.line:
            return p.reason
    return None


def analyze_source(source: str, relpath: str,
                   rules: Optional[Iterable[str]] = None) -> List[Finding]:
    """Run file rules over one in-memory snippet at a virtual repo-relative
    path (fixture tests); pragma semantics apply, baseline does not.
    Pragma-without-reason findings are included."""
    fctx = FileContext(relpath, source)
    raw: List[Finding] = []
    for name in (rules or sorted(RULES)):
        rule = RULES[name]
        if rule.project or not rule.in_scope(fctx.path):
            continue
        raw.extend(rule.check(fctx))
    raw.extend(_pragma_findings({fctx.path: fctx.pragmas}, {fctx.path: fctx}))
    out = []
    for f in raw:
        if _suppression(f, fctx.pragmas) is None:
            out.append(f)
    return sorted(out, key=lambda f: (f.line, f.rule))
