"""Rule family 7 — thread & resource lifecycle (docs/ANALYSIS.md).

The serving fleet spawns threads and opens sockets/executors on every
connection, and the places leaks hide are exactly the paths tests rarely
walk: the error path between an `open` and its `try`, the reader thread
nobody joins, the socket a raised REGISTER leaves dangling. This rule
makes the cleanup contract static:

  * every `threading.Thread` STARTED must be daemonized (`daemon=True`
    at construction or a `t.daemon = True` before start) or reachably
    joined — locally (`t.join(...)` in the same function), or by the
    owning class when the handle is stored on `self` (any method that
    reads the attribute and joins);
  * every socket / file / executor / `subprocess.Popen` opened must be
    closed via a context manager, a `finally` the rule can reach, or an
    ownership transfer (returned, stored on an object, passed onward —
    whoever receives it is checked at ITS binding site);
  * cleanup must cover the ERROR path: a `close()` that only runs on the
    happy path is a finding, and so is a `try/finally` whose protected
    resource was opened several call-bearing statements BEFORE the `try`
    (anything raising in that window leaks the resource).

Handles stored on `self.<attr>` are accepted when some method of the
class reads the attribute and calls a closer (`close`/`shutdown`/
`join`/`terminate`/...) — the `close()`-method idiom every service here
uses.
"""
from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set, Tuple

from dnn_page_vectors_tpu.tools.analyze.core import (
    FileContext, Finding, Rule, qualname, register, PKG_NAME)

_CREATORS = {
    "threading.Thread": "thread",
    "socket.socket": "socket",
    "socket.create_connection": "socket",
    "socket.socketpair": "socket",
    "concurrent.futures.ThreadPoolExecutor": "executor",
    "concurrent.futures.ProcessPoolExecutor": "executor",
    "subprocess.Popen": "popen",
}
_CLOSERS = {"close", "shutdown", "stop", "terminate", "kill", "wait",
            "join", "release"}
_KIND_NOUN = {"thread": "thread", "socket": "socket", "file": "file",
              "executor": "executor", "popen": "subprocess"}


def _creator_kind(call: ast.Call, aliases) -> Optional[str]:
    if isinstance(call.func, ast.Name) and call.func.id == "open":
        return "file"
    q = qualname(call.func, aliases)
    return _CREATORS.get(q) if q else None


def _kw_true(call: ast.Call, name: str) -> bool:
    for kw in call.keywords:
        if kw.arg == name and isinstance(kw.value, ast.Constant):
            return bool(kw.value.value)
    return False


def _names_in(node: ast.AST, name: str) -> bool:
    return any(isinstance(n, ast.Name) and n.id == name
               for n in ast.walk(node))


def _own_nodes(fn: ast.AST):
    """Every node of `fn`'s body, nested function/lambda bodies pruned
    (they are analyzed as their own functions)."""
    stack = list(fn.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


@register
class LifecycleRule(Rule):
    name = "lifecycle"
    family = "lifecycle"
    doc = ("started threads must be daemonized or reachably joined; "
           "sockets/files/executors/Popen must close via with/finally/"
           "ownership, covering the error path")
    scope = (f"{PKG_NAME}/infer/", f"{PKG_NAME}/maintenance/",
             f"{PKG_NAME}/loadgen/", f"{PKG_NAME}/utils/telemetry.py")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for cls, fn in self._functions(ctx.tree):
            yield from self._check_fn(ctx, cls, fn)

    def _functions(self, tree: ast.Module):
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                for sub in ast.walk(node):
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        yield node, sub
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield None, node
                for sub in ast.walk(node):
                    if sub is not node and isinstance(
                            sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        yield None, sub

    # -- per function ------------------------------------------------------

    def _check_fn(self, ctx: FileContext, cls: Optional[ast.ClassDef],
                  fn: ast.AST) -> Iterator[Finding]:
        finally_nodes = self._finally_nodes(fn)
        locals_: List[Tuple[str, str, ast.Call, ast.stmt, list]] = []
        for parent_list, st in self._own_stmts(fn):
            if isinstance(st, (ast.With, ast.AsyncWith)):
                continue            # context-managed: the gold standard
            creators = [(n, _creator_kind(n, ctx.aliases))
                        for n in ast.walk(st) if isinstance(n, ast.Call)]
            creators = [(n, k) for n, k in creators if k]
            if not creators:
                continue
            if isinstance(st, ast.Assign) and len(st.targets) == 1:
                target = st.targets[0]
                bound = self._binds(st.value, creators)
                if bound is not None and isinstance(target, ast.Name):
                    locals_.append((target.id, bound[1], bound[0], st,
                                    parent_list))
                    continue
                if bound is not None and self._is_self_attr(target):
                    yield from self._check_self_attr(
                        ctx, cls, target.attr, bound[1], bound[0])
                    continue
                if bound is not None and isinstance(target,
                                                    ast.Attribute):
                    continue        # stored on another object: theirs now
            if isinstance(st, ast.Expr):
                yield from self._check_dropped(ctx, st.value, creators)
            # other shapes (return/yield/call-argument) transfer
            # ownership to the receiver
        for name, kind, call, st, parent_list in locals_:
            yield from self._check_local(ctx, fn, finally_nodes, name,
                                         kind, call, st, parent_list)

    def _own_stmts(self, fn: ast.AST):
        """(parent statement list, statement) pairs, nested defs pruned."""
        stack = [fn.body]
        while stack:
            body = stack.pop()
            for st in body:
                if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                    continue
                yield body, st
                for _, val in ast.iter_fields(st):
                    if isinstance(val, list) and val \
                            and isinstance(val[0], ast.stmt):
                        stack.append(val)
                    elif isinstance(val, list):
                        for v in val:
                            sub = getattr(v, "body", None)
                            if (isinstance(sub, list) and sub
                                    and isinstance(sub[0], ast.stmt)):
                                stack.append(sub)

    @staticmethod
    def _is_self_attr(node: ast.AST) -> bool:
        return (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self")

    @staticmethod
    def _binds(value: ast.AST, creators) -> Optional[Tuple[ast.Call, str]]:
        """The creator call a simple assignment binds: the value itself,
        an IfExp/BoolOp arm, or a comprehension element. A creator buried
        as another call's ARGUMENT is not bound here (the receiver owns
        it)."""
        heads = [value]
        if isinstance(value, ast.IfExp):
            heads = [value.body, value.orelse]
        elif isinstance(value, ast.BoolOp):
            heads = list(value.values)
        elif isinstance(value, (ast.ListComp, ast.SetComp,
                                ast.GeneratorExp)):
            heads = [value.elt]
        for call, kind in creators:
            if call in heads:
                return call, kind
        return None

    def _finally_nodes(self, fn: ast.AST) -> Set[int]:
        out: Set[int] = set()
        for node in _own_nodes(fn):
            if isinstance(node, ast.Try):
                for st in node.finalbody:
                    for sub in ast.walk(st):
                        out.add(id(sub))
        return out

    # -- the three ownership shapes ---------------------------------------

    def _check_dropped(self, ctx: FileContext, value: ast.AST,
                       creators) -> Iterator[Finding]:
        for call, kind in creators:
            if kind == "thread":
                if not _kw_true(call, "daemon"):
                    yield ctx.finding(
                        self.name, call,
                        "thread constructed and dropped — pass "
                        "`daemon=True` or keep the handle and join it")
            elif value is call or (isinstance(value, ast.Call)
                                   and call in ast.walk(value.func)):
                yield ctx.finding(
                    self.name, call,
                    f"{_KIND_NOUN[kind]} opened and dropped — nothing "
                    "can ever close it; bind it and close in a finally")

    def _check_self_attr(self, ctx: FileContext,
                         cls: Optional[ast.ClassDef], attr: str,
                         kind: str, call: ast.Call) -> Iterator[Finding]:
        if kind == "thread" and _kw_true(call, "daemon"):
            return
        if cls is not None and self._class_cleans(cls, attr, kind):
            return
        want = "join" if kind == "thread" else "close/shutdown"
        yield ctx.finding(
            self.name, call,
            f"`self.{attr}` holds a {_KIND_NOUN[kind]} but no method of "
            f"{cls.name if cls else 'this class'} reads it and calls "
            f"{want} — leaked on shutdown"
            + (" (or pass daemon=True)" if kind == "thread" else ""))

    def _class_cleans(self, cls: ast.ClassDef, attr: str,
                      kind: str) -> bool:
        closers = {"join"} if kind == "thread" else _CLOSERS
        for fn in ast.walk(cls):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            reads = any(
                isinstance(n, ast.Attribute) and n.attr == attr
                and isinstance(n.value, ast.Name) and n.value.id == "self"
                for n in ast.walk(fn))
            closes = any(
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr in closers
                for n in ast.walk(fn))
            if reads and closes:
                return True
        return False

    def _check_local(self, ctx: FileContext, fn: ast.AST,
                     finally_nodes: Set[int], name: str, kind: str,
                     call: ast.Call, st: ast.stmt,
                     parent_list: list) -> Iterator[Finding]:
        closes: List[ast.Call] = []
        started = daemon = escapes = False
        if kind == "thread" and _kw_true(call, "daemon"):
            daemon = True
        for node in _own_nodes(fn):
            if isinstance(node, ast.Call):
                if (isinstance(node.func, ast.Attribute)
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id == name):
                    if node.func.attr in _CLOSERS:
                        closes.append(node)
                    if node.func.attr == "start":
                        started = True
                for arg in list(node.args) + [kw.value
                                              for kw in node.keywords]:
                    if _names_in(arg, name):
                        escapes = True
            elif isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)):
                if node.value is not None and _names_in(node.value, name):
                    escapes = True
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, (ast.Attribute, ast.Subscript)) \
                            and _names_in(node.value, name):
                        escapes = True
                    if (isinstance(t, ast.Attribute) and t.attr == "daemon"
                            and isinstance(t.value, ast.Name)
                            and t.value.id == name
                            and isinstance(node.value, ast.Constant)
                            and node.value.value):
                        daemon = True

        if kind == "thread":
            joined = any(c.func.attr == "join" for c in closes)
            if started and not daemon and not joined and not escapes:
                yield ctx.finding(
                    self.name, call,
                    f"thread `{name}` is started but neither daemonized "
                    "nor joined — a non-daemon leak keeps the process "
                    "alive; join it (or pass daemon=True)")
            return

        strong = [c for c in closes if id(c) in finally_nodes]
        if strong:
            yield from self._check_window(ctx, name, kind, st,
                                          parent_list)
        elif escapes:
            return                  # ownership transferred
        elif closes:
            yield ctx.finding(
                self.name, closes[0],
                f"`{name}.{closes[0].func.attr}()` runs only on the "
                "happy path — anything raising before it leaks the "
                f"{_KIND_NOUN[kind]}; use `with` or a finally")
        else:
            yield ctx.finding(
                self.name, call,
                f"{_KIND_NOUN[kind]} `{name}` is opened and never "
                "closed on any path — use `with`, a finally, or hand "
                "it to an owner that closes it")

    def _check_window(self, ctx: FileContext, name: str, kind: str,
                      st: ast.stmt, parent_list: list) -> Iterator[Finding]:
        """The creation is closed in a finally: make sure nothing that
        can raise runs between the creation and the protecting try."""
        try:
            idx = parent_list.index(st)
        except ValueError:
            return
        for later in parent_list[idx + 1:]:
            if isinstance(later, ast.Try):
                closed_here = any(
                    isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr in _CLOSERS
                    and isinstance(n.func.value, ast.Name)
                    and n.func.value.id == name
                    for f in later.finalbody for n in ast.walk(f))
                if closed_here:
                    return
            if any(isinstance(n, ast.Call) for n in ast.walk(later)):
                yield ctx.finding(
                    self.name, later,
                    f"statement between `{name} = ...` and its "
                    "try/finally can raise and leak the "
                    f"{_KIND_NOUN[kind]} — open inside the try (or "
                    "close on this error path)")
                return
