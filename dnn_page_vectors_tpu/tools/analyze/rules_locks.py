"""Rule family 2 — lock discipline (docs/ANALYSIS.md, docs/ROBUSTNESS.md).

The serving stack is multithreaded in exactly three places (the micro-
batcher dispatcher, SearchService.refresh, the telemetry registry), and its
concurrency contract has two idioms:

  * mutable shared state is annotated at its construction site with
        self._cache = OrderedDict()   # guarded-by: _cache_lock
    and may then only be touched inside `with self._cache_lock:`;
  * immutable-view state is REPLACED, never mutated — the `_ServeView`
    swap: `self._view = new_view` (whole-statement reference assignment)
    and snapshot reads `view = self._view` are both atomic under the GIL
    and need no lock.

This rule machine-checks both: annotated attributes accessed outside their
lock (except the two swap shapes) are findings, and a `threading.Thread`
target method (plus the same-class methods it calls) mutating an
UN-annotated attribute without any lock held is a finding too — new threads
can't quietly grow unguarded shared state.

A helper that is only ever called with the lock already held declares that
contract on its def line: `# holds-lock: _lock` (the `_prune` idiom in
utils/telemetry.py) — the scanner then treats the lock as held for the
whole body, and the comment documents the calling convention for free.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set

from dnn_page_vectors_tpu.tools.analyze.core import (
    FileContext, Finding, Rule, qualname, register, PKG_NAME)

_MUTATORS = {"append", "extend", "add", "update", "pop", "popitem", "remove",
             "discard", "clear", "setdefault", "insert", "appendleft",
             "popleft", "sort", "reverse"}


def _self_attr(node: ast.AST) -> Optional[str]:
    """`self.<name>` -> name, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


@register
class LockDisciplineRule(Rule):
    name = "locks"
    family = "locks"
    doc = ("`# guarded-by:` attributes touched outside their lock; thread "
           "targets mutating un-annotated shared state")
    scope = (f"{PKG_NAME}/infer/serve.py",
             f"{PKG_NAME}/infer/partition.py",
             f"{PKG_NAME}/infer/transport.py",
             f"{PKG_NAME}/infer/server.py",
             f"{PKG_NAME}/infer/partition_host.py",
             f"{PKG_NAME}/utils/telemetry.py",
             f"{PKG_NAME}/utils/faults.py",   # CircuitBreaker state
             f"{PKG_NAME}/updates/append.py", f"{PKG_NAME}/maintenance/",
             f"{PKG_NAME}/loadgen/driver.py")  # BalancedClient counters

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ctx.tree.body:
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(ctx, node)

    # -- per class ---------------------------------------------------------

    def _check_class(self, ctx: FileContext,
                     cls: ast.ClassDef) -> Iterator[Finding]:
        guarded: Dict[str, str] = {}
        for node in ast.walk(cls):
            target = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target = _self_attr(node.targets[0])
            elif isinstance(node, ast.AnnAssign):
                target = _self_attr(node.target)
            if target:
                # the annotation rides the assignment line, or the comment
                # line directly above it (79-col style)
                lock = (ctx.guarded_by(node.lineno)
                        or ctx.guarded_by(node.lineno - 1))
                if lock:
                    guarded[target] = lock

        methods = {n.name: n for n in cls.body
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        threaded = self._thread_reachable(ctx, cls, methods)

        for name, fn in methods.items():
            if name in ("__init__", "__new__"):
                continue   # construction happens-before publication
            yield from self._scan_stmts(
                ctx, fn.body, ctx.holds_lock(fn), guarded,
                thread_entry=(name in threaded))

    def _thread_reachable(self, ctx: FileContext, cls: ast.ClassDef,
                          methods: Dict[str, ast.AST]) -> Set[str]:
        """Method names reachable from a `threading.Thread(target=...)`
        started on this class (direct target + same-class call closure)."""
        roots: List[str] = []
        for node in ast.walk(cls):
            if not isinstance(node, ast.Call):
                continue
            if qualname(node.func, ctx.aliases) != "threading.Thread":
                continue
            for kw in node.keywords:
                if kw.arg == "target":
                    attr = _self_attr(kw.value)
                    if attr and attr in methods:
                        roots.append(attr)
        reach: Set[str] = set()
        frontier = list(roots)
        while frontier:
            name = frontier.pop()
            if name in reach:
                continue
            reach.add(name)
            for node in ast.walk(methods[name]):
                if isinstance(node, ast.Call):
                    callee = _self_attr(node.func)
                    if callee and callee in methods and callee not in reach:
                        frontier.append(callee)
        return reach

    # -- the lock-context walker ------------------------------------------

    def _scan_stmts(self, ctx, stmts, held, guarded,
                    thread_entry: bool) -> Iterator[Finding]:
        for st in stmts:
            if isinstance(st, (ast.With, ast.AsyncWith)):
                locks = set()
                for item in st.items:
                    attr = _self_attr(item.context_expr)
                    if attr:
                        locks.add(attr)
                yield from self._scan_stmts(ctx, st.body, held | locks,
                                            guarded, thread_entry)
            elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # a nested def runs later, on an unknown thread: it
                # inherits NO held locks
                yield from self._scan_stmts(ctx, st.body, frozenset(),
                                            guarded, thread_entry)
            elif isinstance(st, ast.ClassDef):
                continue
            else:
                children = [f for f in ast.iter_fields(st)]
                body_fields, expr_nodes = [], []
                for fname, val in children:
                    if isinstance(val, list) and val and isinstance(
                            val[0], ast.stmt):
                        body_fields.append(val)
                    elif isinstance(val, list):
                        for v in val:
                            if not isinstance(v, ast.AST):
                                continue
                            # except-handler / match-case arms carry their
                            # own statement bodies: recurse those so a
                            # `with lock:` inside them still registers
                            sub = getattr(v, "body", None)
                            if (isinstance(sub, list) and sub
                                    and isinstance(sub[0], ast.stmt)):
                                body_fields.append(sub)
                            else:
                                expr_nodes.append(v)
                    elif isinstance(val, ast.AST):
                        expr_nodes.append(val)
                if body_fields:
                    # compound statement (if/for/while/try/match): check the
                    # header expressions, then recurse into each body
                    for expr in expr_nodes:
                        yield from self._check_tree(ctx, expr, held, guarded,
                                                    thread_entry, st)
                    for body in body_fields:
                        yield from self._scan_stmts(ctx, body, held, guarded,
                                                    thread_entry)
                else:
                    yield from self._check_simple(ctx, st, held, guarded,
                                                  thread_entry)

    def _check_simple(self, ctx, st, held, guarded,
                      thread_entry: bool) -> Iterator[Finding]:
        allowed: Set[int] = set()
        if isinstance(st, ast.Assign) and len(st.targets) == 1:
            t = st.targets[0]
            if _self_attr(t) in guarded:
                allowed.add(id(t))       # atomic reference swap (store)
            if (_self_attr(st.value) in guarded
                    and all(isinstance(x, ast.Name) for x in st.targets)):
                allowed.add(id(st.value))  # snapshot read of a swapped ref
        yield from self._check_tree(ctx, st, held, guarded, thread_entry,
                                    st, allowed)

    def _check_tree(self, ctx, tree, held, guarded, thread_entry,
                    stmt, allowed=frozenset()) -> Iterator[Finding]:
        for node in ast.walk(tree):
            attr = _self_attr(node)
            if attr is not None and attr in guarded:
                lock = guarded[attr]
                if lock not in held and id(node) not in allowed:
                    kind = ("write" if isinstance(node.ctx, (ast.Store,
                                                             ast.Del))
                            else "read")
                    yield ctx.finding(
                        self.name, node,
                        f"`self.{attr}` is `# guarded-by: {lock}` but this "
                        f"{kind} holds {sorted(held) or 'no lock'} — wrap "
                        f"in `with self.{lock}:` (or swap/snapshot the "
                        "whole reference)")
            if thread_entry and not held and isinstance(node, ast.Call):
                target = _self_attr(getattr(node.func, "value", None))
                if (target and target not in guarded
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in _MUTATORS):
                    yield ctx.finding(
                        self.name, node,
                        f"thread-reachable mutation `self.{target}"
                        f".{node.func.attr}(...)` on an un-annotated "
                        "attribute — annotate it `# guarded-by: <lock>` "
                        "and lock the access, or pragma with the reason "
                        "it is single-writer")
            if thread_entry and not held:
                store_attr = None
                if isinstance(node, ast.AugAssign):
                    store_attr = _self_attr(node.target) or _self_attr(
                        getattr(node.target, "value", None))
                elif isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Subscript):
                            store_attr = _self_attr(t.value)
                if store_attr and store_attr not in guarded:
                    yield ctx.finding(
                        self.name, node,
                        f"thread-reachable in-place write to un-annotated "
                        f"`self.{store_attr}` — annotate it "
                        "`# guarded-by: <lock>` and lock the access")
