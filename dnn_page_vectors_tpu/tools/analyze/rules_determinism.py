"""Rule family 1 — determinism (docs/ANALYSIS.md).

The byte-pinned paths (bulk embed, index build, PQ codebooks, live appends,
the loadgen schedule) all promise "same seed == identical bytes"; their
tests pin digests. Module-state RNG (`np.random.rand`, bare `random.*`),
seedless RNG constructors, wall-clock reads, and PRNGKeys derived from the
clock silently break that promise the day someone adds one — so they are
findings anywhere under the pinned paths.
"""
from __future__ import annotations

import ast
from typing import Iterator

from dnn_page_vectors_tpu.tools.analyze.core import (
    FileContext, Finding, Rule, qualname, register, PKG_NAME)

# np.random.<ctor>(seed) is the sanctioned spelling; the same ctor with NO
# arguments falls back to OS entropy and is exactly the bug this rule hunts
_RNG_CONSTRUCTORS = {"default_rng", "Generator", "SeedSequence", "PCG64",
                     "Philox", "MT19937", "RandomState"}
_STDLIB_SAMPLERS = {
    "random", "randint", "randrange", "choice", "choices", "shuffle",
    "sample", "uniform", "gauss", "normalvariate", "betavariate",
    "expovariate", "triangular", "vonmisesvariate", "paretovariate",
    "weibullvariate", "lognormvariate", "getrandbits", "randbytes", "seed"}
_WALL_CLOCK = {
    "time.time", "time.time_ns",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.datetime.today", "datetime.date.today"}
_PRNG_KEY = {"jax.random.PRNGKey", "jax.random.key"}


@register
class DeterminismRule(Rule):
    name = "determinism"
    family = "determinism"
    doc = ("unseeded/module-state RNG and wall-clock reads on the "
           "byte-pinned embed/index/update/loadgen paths")
    scope = (f"{PKG_NAME}/infer/", f"{PKG_NAME}/index/",
             f"{PKG_NAME}/updates/", f"{PKG_NAME}/loadgen/workload.py",
             f"{PKG_NAME}/maintenance/compact.py",
             f"{PKG_NAME}/maintenance/migrate.py")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            q = qualname(node.func, ctx.aliases)
            if q is None:
                continue
            yield from self._check_call(ctx, node, q)

    def _check_call(self, ctx: FileContext, node: ast.Call,
                    q: str) -> Iterator[Finding]:
        head, _, last = q.rpartition(".")
        if head in ("numpy.random", "np.random"):
            if last in _RNG_CONSTRUCTORS:
                if not node.args and not node.keywords:
                    yield ctx.finding(
                        self.name, node,
                        f"seedless RNG constructor `{q}()` draws OS entropy "
                        "— pass an explicit seed on a byte-pinned path")
            else:
                yield ctx.finding(
                    self.name, node,
                    f"module-state RNG `{q}(...)` is unseeded process "
                    "state — use `np.random.default_rng(seed)`")
        elif head == "random" and last in _STDLIB_SAMPLERS:
            yield ctx.finding(
                self.name, node,
                f"stdlib module-state RNG `{q}(...)` — use a seeded "
                "`random.Random(seed)` or `np.random.default_rng(seed)`")
        elif q == "random.Random" and not node.args and not node.keywords:
            yield ctx.finding(
                self.name, node,
                "seedless `random.Random()` draws OS entropy — pass an "
                "explicit seed")
        elif q in _WALL_CLOCK:
            yield ctx.finding(
                self.name, node,
                f"wall-clock read `{q}()` on a byte-pinned path — derive "
                "schedule/output bytes from the seed (perf_counter is fine "
                "for measuring durations)")
        elif q in _PRNG_KEY:
            for arg in ast.walk(node):
                if (isinstance(arg, ast.Call) and arg is not node
                        and qualname(arg.func, ctx.aliases) in _WALL_CLOCK):
                    yield ctx.finding(
                        self.name, node,
                        f"`{q}` seeded from the wall clock — thread the "
                        "config seed instead")
                    break
