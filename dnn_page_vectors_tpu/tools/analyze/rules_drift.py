"""Rule family 5 — doc/knob/marker drift (docs/ANALYSIS.md).

Generalizes the two hand-rolled drift checks that used to live only in
tests/test_telemetry.py into project-level rules, and adds a third:

  * drift-knobs   — every config dataclass field is documented as
                    `section.field` somewhere under docs/ or README.md, and
                    every `section.field` the docs mention really exists.
  * drift-events  — every `registry.event("name")` emitted in the package
                    appears in the docs/OBSERVABILITY.md event table, and
                    the table advertises no dead events.
  * drift-markers — every `@pytest.mark.<name>` used under tests/ is
                    declared in pytest.ini, and no declared marker is dead.

Everything is parsed with `ast`/regex — no imports of the package, so the
rules run on a jax-less box and on half-broken trees.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Set, Tuple

from dnn_page_vectors_tpu.tools.analyze.core import (
    Finding, ProjectContext, Rule, register, PKG_NAME)

_CONFIG_REL = f"{PKG_NAME}/config.py"
_OBS_DOC = "docs/OBSERVABILITY.md"
_EVENT_RE = re.compile(r"\.event\(\s*[\"']([a-z_]+)[\"']")
_EVENT_ROW_RE = re.compile(r"^\|\s*`([a-z_]+)`", re.M)
_BUILTIN_MARKERS = {"parametrize", "skip", "skipif", "xfail", "usefixtures",
                    "filterwarnings", "timeout"}
# doc tokens that look like `section.word` but are file/module suffixes
_NOT_KNOB_SUFFIX = {"py", "md", "json", "npy", "ini", "txt", "ivf"}


def _line_of(text: str, pos: int) -> int:
    return text.count("\n", 0, pos) + 1


def _config_schema(ctx: ProjectContext):
    """(sections, fields, linenos): section name -> dataclass fields, via
    AST only. sections maps e.g. "serve" -> "ServeConfig"."""
    src = ctx.read(_CONFIG_REL)
    if src is None:
        return {}, {}, {}
    tree = ast.parse(src)
    classes: Dict[str, ast.ClassDef] = {
        n.name: n for n in tree.body if isinstance(n, ast.ClassDef)}
    fields: Dict[str, List[Tuple[str, int]]] = {}
    for name, cls in classes.items():
        fields[name] = [
            (st.target.id, st.lineno) for st in cls.body
            if isinstance(st, ast.AnnAssign) and isinstance(st.target,
                                                            ast.Name)]
    sections: Dict[str, str] = {}
    root_cls = classes.get("Config")
    if root_cls is not None:
        for st in root_cls.body:
            if (isinstance(st, ast.AnnAssign)
                    and isinstance(st.target, ast.Name)
                    and isinstance(st.annotation, ast.Name)
                    and st.annotation.id in classes
                    and st.annotation.id.endswith("Config")):
                sections[st.target.id] = st.annotation.id
    return sections, fields, classes


def _doc_files(ctx: ProjectContext) -> List[str]:
    return ctx.glob("docs", ".md") + [
        p for p in ("README.md",) if ctx.read(p) is not None]


@register
class KnobDriftRule(Rule):
    name = "drift-knobs"
    family = "drift"
    doc = ("every config.py knob documented as `section.field` in docs/ or "
           "README; no doc names a knob that does not exist")
    project = True

    def check_project(self, ctx: ProjectContext) -> Iterator[Finding]:
        sections, fields, _ = _config_schema(ctx)
        if not sections:
            return
        docs = {p: ctx.read(p) or "" for p in _doc_files(ctx)}
        all_docs = "\n".join(docs.values())
        for section, cls_name in sections.items():
            for field, lineno in fields.get(cls_name, []):
                knob = f"{section}.{field}"
                if knob not in all_docs:
                    yield ctx.finding(
                        self.name, _CONFIG_REL, lineno,
                        f"config knob `{knob}` is not documented — add it "
                        "to a knob table under docs/ (docs/CONFIG.md holds "
                        "the train/data/model/eval tables)")
        known = {f"{s}.{f}" for s, cls in sections.items()
                 for f, _ in fields.get(cls, [])}
        # registry instrument names share the `section.` spelling
        # (`serve.recompiles`, `serve.queue_wait_ms`): a doc naming one is
        # documenting a metric, not a knob — collect and exempt them
        instruments = set()
        inst_re = re.compile(
            r"\.(?:counter|gauge|histogram)\(\s*[\"']([a-z_][a-z0-9_.]*)")
        for rel in ctx.glob(ctx.pkg, ".py"):
            instruments.update(inst_re.findall(ctx.read(rel) or ""))
        pat = re.compile(
            r"\b(" + "|".join(map(re.escape, sorted(sections))) +
            r")\.([a-z_][a-z0-9_]*)\b")
        for path, text in docs.items():
            for m in pat.finditer(text):
                knob, suffix = m.group(0), m.group(2)
                if suffix in _NOT_KNOB_SUFFIX or knob in known \
                        or knob in instruments:
                    continue
                if text[m.end():m.end() + 1] == "(":
                    continue   # `faults.counters()`-style API reference
                yield ctx.finding(
                    self.name, path, _line_of(text, m.start()),
                    f"doc names `{knob}` but no such field exists on "
                    f"{sections[m.group(1)]} — stale knob reference")


@register
class EventDriftRule(Rule):
    name = "drift-events"
    family = "drift"
    doc = ("every `registry.event(...)` name appears in the "
           "docs/OBSERVABILITY.md event table and vice versa")
    project = True

    @staticmethod
    def _event_rows(doc: str) -> Dict[str, int]:
        """Backticked names from tables whose header's FIRST cell is
        `event` — other tables in the doc (knobs, the shed-reason list
        the proto-drift rule owns) are not event rows."""
        out: Dict[str, int] = {}
        in_event_table = False
        for i, line in enumerate(doc.splitlines(), 1):
            stripped = line.strip()
            if not stripped.startswith("|"):
                in_event_table = False
                continue
            cells = [c.strip() for c in stripped.strip("|").split("|")]
            if cells and cells[0].lower() == "event":
                in_event_table = True
                continue
            if not in_event_table:
                continue
            m = _EVENT_ROW_RE.match(stripped)
            if m:
                out.setdefault(m.group(1), i)
        return out

    def check_project(self, ctx: ProjectContext) -> Iterator[Finding]:
        doc = ctx.read(_OBS_DOC)
        if doc is None:
            return
        documented = self._event_rows(doc)
        emitted: Dict[str, Tuple[str, int]] = {}
        tools_prefix = f"{ctx.pkg}/tools/"
        for rel in ctx.glob(ctx.pkg, ".py"):
            if rel.startswith(tools_prefix):
                continue   # the analyzer quotes the pattern it hunts
            text = ctx.read(rel) or ""
            for m in _EVENT_RE.finditer(text):
                emitted.setdefault(m.group(1),
                                   (rel, _line_of(text, m.start())))
        if not emitted and len(documented) >= 5:
            # the emit regex went stale (an API rename would zero the scan
            # silently while the doc still advertises a full table — the
            # old hand-rolled test pinned >= 10 emitted names)
            yield ctx.finding(
                self.name, _OBS_DOC, 1,
                "event scan found NOTHING while the doc documents "
                f"{len(documented)} events — `registry.event` spelling "
                "drift?")
        for name, (rel, line) in sorted(emitted.items()):
            if name not in documented:
                yield ctx.finding(
                    self.name, rel, line,
                    f"event `{name}` is emitted here but missing from the "
                    f"{_OBS_DOC} event table")
        for name, line in sorted(documented.items()):
            if name not in emitted:
                yield ctx.finding(
                    self.name, _OBS_DOC, line,
                    f"event `{name}` is documented but never emitted — "
                    "dead table row")


@register
class MarkerDriftRule(Rule):
    name = "drift-markers"
    family = "drift"
    doc = ("every pytest marker used under tests/ is declared in "
           "pytest.ini; no declared marker is unused")
    project = True

    def check_project(self, ctx: ProjectContext) -> Iterator[Finding]:
        ini = ctx.read("pytest.ini")
        if ini is None:
            return
        declared: Dict[str, int] = {}
        in_markers = False
        for i, line in enumerate(ini.splitlines(), 1):
            if re.match(r"\s*markers\s*=", line):
                in_markers = True
                rest = line.split("=", 1)[1].strip()
                if rest:
                    declared.setdefault(rest.split(":")[0].strip(), i)
                continue
            if in_markers:
                if line.strip() and line[:1].isspace():
                    declared.setdefault(line.strip().split(":")[0].strip(), i)
                elif line.strip():
                    in_markers = False
        used: Dict[str, Tuple[str, int]] = {}
        for rel in ctx.glob("tests", ".py"):
            text = ctx.read(rel) or ""
            try:
                tree = ast.parse(text)
            except SyntaxError:
                continue
            # AST, not regex: a fixture STRING quoting `pytest.mark.x`
            # (this analyzer's own tests do) is not a marker usage
            for node in ast.walk(tree):
                if (isinstance(node, ast.Attribute)
                        and isinstance(node.value, ast.Attribute)
                        and node.value.attr == "mark"
                        and isinstance(node.value.value, ast.Name)
                        and node.value.value.id == "pytest"
                        and node.attr not in _BUILTIN_MARKERS):
                    used.setdefault(node.attr, (rel, node.lineno))
        for name, (rel, line) in sorted(used.items()):
            if name not in declared:
                yield ctx.finding(
                    self.name, rel, line,
                    f"marker `@pytest.mark.{name}` is not declared in "
                    "pytest.ini — add it with a one-line description")
        for name, line in sorted(declared.items()):
            if name not in used:
                yield ctx.finding(
                    self.name, "pytest.ini", line,
                    f"marker `{name}` is declared but never used under "
                    "tests/")
