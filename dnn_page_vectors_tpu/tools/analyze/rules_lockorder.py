"""Rule family 6 — lock-order / deadlock analysis (docs/ANALYSIS.md).

The serving fleet holds locks in layers: the gateway registry lock over
per-worker connection locks, the maintenance mutation RLock over the
service stats lock, the refresh lock over the view-build path. A deadlock
needs two threads acquiring the same two locks in opposite orders — a
property no test reliably provokes (the windows are microseconds) but a
static scan proves absent: build the project-wide lock acquisition graph
and any cycle is a potential deadlock.

Edges come from three places:

  * **nested `with`** — `with self._a:` enclosing `with self._b:` is an
    a -> b edge;
  * **call closure** — a method that CALLS another method while holding a
    lock inherits every lock the callee (transitively, through same-class
    calls and imported package-level functions) acquires;
  * **`# holds-lock: X`** — the annotated helper's body is scanned as if
    X were held, so the caller-holds-lock contract feeds the graph too.

A cycle is reported ONCE with every edge's acquisition path (file:line +
how the second lock is reached), so the finding shows both sides of the
race. A self-edge on a plain `threading.Lock` is a self-deadlock and
reported; on an `RLock` it is re-entry and fine.

The intended hierarchy is pinned in source with order declarations:

    # lock-order: MaintenanceService._mlock < MaintenanceService._lock

(chains allowed: `A < B < C`). The rule validates every declaration —
names must be locks that exist, two declarations must not contradict each
other, and an OBSERVED edge against the declared order is a finding even
before it closes a cycle. Lock nodes are named `Class.attr` for instance
locks and `module.NAME` for module-level locks.
"""
from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from dnn_page_vectors_tpu.tools.analyze.core import (
    FileContext, Finding, ProjectContext, Rule, qualname, register)

_LOCK_CTORS = {
    "threading.Lock": "Lock",
    "threading.RLock": "RLock",
    "threading.Condition": "Condition",
    "threading.Semaphore": "Semaphore",
    "threading.BoundedSemaphore": "Semaphore",
}

_DECL_RE = re.compile(r"#\s*lock-order:\s*(\S.*)$")


@dataclasses.dataclass(frozen=True)
class _Edge:
    """One observed `a` held while `b` is acquired, with its witness."""
    a: str
    b: str
    path: str
    line: int
    how: str              # human acquisition-path fragment


@dataclasses.dataclass
class _FnInfo:
    """Per-function lock facts feeding the cross-function closure."""
    key: Tuple[str, Optional[str], str]           # (path, class, name)
    acquires: Dict[str, Tuple[str, int]]          # lock -> first (path, ln)
    edges: List[_Edge]
    # (callee key or dotted name, locks held at the call site, line)
    calls: List[Tuple[object, frozenset, int]]


def _self_attr(node: ast.AST) -> Optional[str]:
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


class _Graph:
    """The project-wide acquisition graph under construction."""

    def __init__(self):
        self.locks: Dict[str, str] = {}          # node -> Lock/RLock/...
        self.fns: Dict[Tuple, _FnInfo] = {}
        self.attr_owner: Dict[str, Optional[str]] = {}  # lockattr -> class
        self.edges: Dict[Tuple[str, str], _Edge] = {}

    def add_lock(self, node: str, kind: str, attr: Optional[str],
                 owner: Optional[str]) -> None:
        self.locks[node] = kind
        if attr is not None:
            # `with worker.wlock:` resolves through attr uniqueness: when
            # exactly ONE class in the project declares the attr, a
            # non-self acquisition still lands on the right node
            if attr in self.attr_owner and self.attr_owner[attr] != owner:
                self.attr_owner[attr] = None     # ambiguous: never resolve
            else:
                self.attr_owner.setdefault(attr, owner)


@register
class LockOrderRule(Rule):
    name = "lock-order"
    family = "lock-order"
    doc = ("cycles in the project-wide lock acquisition graph (nested "
           "`with` + call closure + `# holds-lock:`); `# lock-order:` "
           "declarations validated against observed acquisitions")
    project = True

    # -- harvesting --------------------------------------------------------

    def check_project(self, ctx: ProjectContext) -> Iterator[Finding]:
        graph = _Graph()
        contexts: Dict[str, FileContext] = {}
        decls: List[Tuple[str, int, List[str]]] = []
        for rel in ctx.glob(ctx.pkg, ".py"):
            fctx = ctx.file_context(rel)
            if fctx is None:
                continue          # the parse rule owns broken files
            contexts[rel] = fctx
            self._harvest_locks(graph, fctx)
            if rel.startswith(f"{ctx.pkg}/tools/"):
                continue          # the analyzer quotes its own grammar
            if "lock-order:" not in fctx.source:
                continue          # skip the tokenize pass entirely
            # real COMMENT tokens only — a docstring quoting the
            # declaration grammar is prose, not a declaration
            for line, text in fctx.comments:
                m = _DECL_RE.search(text)
                if m:
                    chain = [t.strip().strip("`")
                             for t in m.group(1).split("<")]
                    decls.append((rel, line, [t for t in chain if t]))
        for fctx in contexts.values():
            self._harvest_fns(graph, fctx, contexts)
        self._close_calls(graph)
        yield from self._report_cycles(graph)
        yield from self._check_decls(ctx, graph, decls)

    def _harvest_locks(self, graph: _Graph, fctx: FileContext) -> None:
        for node in fctx.tree.body:
            if isinstance(node, ast.Assign) and isinstance(node.value,
                                                           ast.Call):
                kind = _LOCK_CTORS.get(
                    qualname(node.value.func, fctx.aliases) or "")
                if kind and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    mod = fctx.path.rsplit("/", 1)[-1][:-3]
                    graph.add_lock(f"{mod}.{node.targets[0].id}", kind,
                                   None, None)
            if not isinstance(node, ast.ClassDef):
                continue
            for sub in ast.walk(node):
                if not (isinstance(sub, ast.Assign)
                        and isinstance(sub.value, ast.Call)):
                    continue
                kind = _LOCK_CTORS.get(
                    qualname(sub.value.func, fctx.aliases) or "")
                attr = (_self_attr(sub.targets[0])
                        if kind and len(sub.targets) == 1 else None)
                if attr:
                    graph.add_lock(f"{node.name}.{attr}", kind, attr,
                                   node.name)

    def _harvest_fns(self, graph: _Graph, fctx: FileContext,
                     contexts: Dict[str, FileContext]) -> None:
        for node in fctx.tree.body:
            if isinstance(node, ast.ClassDef):
                for fn in node.body:
                    if isinstance(fn, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                        self._harvest_one(graph, fctx, node.name, fn)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._harvest_one(graph, fctx, None, node)

    def _harvest_one(self, graph: _Graph, fctx: FileContext,
                     cls: Optional[str],
                     fn: ast.AST) -> None:
        key = (fctx.path, cls, fn.name)
        info = _FnInfo(key, {}, [], [])
        graph.fns[key] = info
        held = frozenset(
            f"{cls}.{name}" for name in fctx.holds_lock(fn)
            if cls and f"{cls}.{name}" in graph.locks)
        self._walk(graph, fctx, cls, info, fn.body, held)

    def _resolve_lock(self, graph: _Graph, fctx: FileContext,
                      cls: Optional[str], expr: ast.AST) -> Optional[str]:
        attr = _self_attr(expr)
        if attr is not None:
            node = f"{cls}.{attr}" if cls else None
            if node in graph.locks:
                return node
            return None
        if isinstance(expr, ast.Name):
            mod = fctx.path.rsplit("/", 1)[-1][:-3]
            node = f"{mod}.{expr.id}"
            return node if node in graph.locks else None
        if isinstance(expr, ast.Attribute) and isinstance(expr.value,
                                                          ast.Name):
            owner = graph.attr_owner.get(expr.attr)
            if owner:
                return f"{owner}.{expr.attr}"
        return None

    def _walk(self, graph: _Graph, fctx: FileContext, cls: Optional[str],
              info: _FnInfo, stmts, held: frozenset) -> None:
        for st in stmts:
            if isinstance(st, (ast.With, ast.AsyncWith)):
                got = []
                for item in st.items:
                    lock = self._resolve_lock(graph, fctx, cls,
                                              item.context_expr)
                    if lock is None:
                        self._exprs(graph, fctx, cls, info,
                                    item.context_expr, held)
                        continue
                    got.append(lock)
                    info.acquires.setdefault(lock, (fctx.path, st.lineno))
                    for h in held:
                        info.edges.append(_Edge(
                            h, lock, fctx.path, st.lineno,
                            f"`{lock}` acquired with `{h}` held"))
                self._walk(graph, fctx, cls, info, st.body,
                           held | frozenset(got))
            elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # a nested def runs later on an unknown thread: no locks
                # inherited, but its own nestings still feed the graph
                self._walk(graph, fctx, cls, info, st.body, frozenset())
            elif isinstance(st, ast.ClassDef):
                continue
            else:
                # header expressions see the current held set; child
                # statement bodies recurse so nested `with` blocks extend
                # it and nested defs reset it
                body_lists = []
                for _, val in ast.iter_fields(st):
                    if isinstance(val, list) and val \
                            and isinstance(val[0], ast.stmt):
                        body_lists.append(val)
                    elif isinstance(val, list):
                        for v in val:
                            sub = getattr(v, "body", None)
                            if (isinstance(sub, list) and sub
                                    and isinstance(sub[0], ast.stmt)):
                                body_lists.append(sub)
                            elif isinstance(v, ast.AST):
                                self._exprs(graph, fctx, cls, info, v,
                                            held)
                    elif isinstance(val, ast.AST):
                        self._exprs(graph, fctx, cls, info, val, held)
                for body in body_lists:
                    self._walk(graph, fctx, cls, info, body, held)

    def _exprs(self, graph: _Graph, fctx: FileContext, cls: Optional[str],
               info: _FnInfo, tree: ast.AST, held: frozenset) -> None:
        """Note every call in an expression subtree, without descending
        into nested function/lambda bodies (those run with no inherited
        locks and are scanned by their own `_walk` when they are defs)."""
        stack = [tree]
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            if isinstance(node, ast.Call):
                self._note_call(graph, fctx, cls, info, node, held)
            stack.extend(ast.iter_child_nodes(node))

    def _note_call(self, graph: _Graph, fctx: FileContext,
                   cls: Optional[str], info: _FnInfo, call: ast.Call,
                   held: frozenset) -> None:
        callee = _self_attr(call.func)
        if callee is not None and cls is not None:
            info.calls.append(((fctx.path, cls, callee), held,
                               call.lineno))
            return
        q = qualname(call.func, fctx.aliases)
        if q and "." in q:
            info.calls.append((q, held, call.lineno))

    # -- closure -----------------------------------------------------------

    def _close_calls(self, graph: _Graph) -> None:
        """Propagate transitive acquisitions through the call graph, then
        materialize call-closure edges for every lock-holding call site."""
        by_dotted: Dict[str, Tuple] = {}
        for (path, cls, name) in graph.fns:
            if cls is None:
                mod = path[:-3].replace("/", ".")
                by_dotted[f"{mod}.{name}"] = (path, cls, name)
                # `from pkg.mod import f` aliases resolve without the
                # package prefix too
                parts = mod.split(".")
                for i in range(1, len(parts)):
                    by_dotted[".".join(parts[i:]) + f".{name}"] = (
                        path, cls, name)

        memo: Dict[Tuple, Dict[str, Tuple[str, int]]] = {}

        def closure(key, stack=()):
            if key in memo:
                return memo[key]
            if key in stack or key not in graph.fns:
                return {}
            info = graph.fns[key]
            out = dict(info.acquires)
            for callee, _, _ in info.calls:
                ck = callee if isinstance(callee, tuple) \
                    else by_dotted.get(callee)
                if ck is None or ck not in graph.fns:
                    continue
                for lock, wit in closure(ck, stack + (key,)).items():
                    out.setdefault(lock, wit)
            memo[key] = out
            return out

        for key, info in graph.fns.items():
            for e in info.edges:
                graph.edges.setdefault((e.a, e.b), e)
            for callee, held, line in info.calls:
                if not held:
                    continue
                ck = callee if isinstance(callee, tuple) \
                    else by_dotted.get(callee)
                if ck is None or ck not in graph.fns:
                    continue
                cname = ck[2] if isinstance(ck, tuple) else callee
                for lock, (wpath, wline) in closure(ck).items():
                    for h in held:
                        graph.edges.setdefault((h, lock), _Edge(
                            h, lock, key[0], line,
                            f"call to {cname}() acquires `{lock}` "
                            f"(at {wpath}:{wline}) with `{h}` held"))

    # -- reporting ---------------------------------------------------------

    def _report_cycles(self, graph: _Graph) -> Iterator[Finding]:
        adj: Dict[str, List[str]] = {}
        for (a, b), _ in sorted(graph.edges.items()):
            if a == b:
                if graph.locks.get(a) != "RLock":
                    e = graph.edges[(a, b)]
                    yield Finding(
                        self.name, e.path, e.line, 0,
                        f"self-deadlock: `{a}` (a non-reentrant "
                        f"{graph.locks.get(a, 'Lock')}) is re-acquired "
                        f"while already held — {e.how} "
                        f"(at {e.path}:{e.line})",
                        "")
                continue
            adj.setdefault(a, []).append(b)

        seen_cycles: Set[frozenset] = set()
        for start in sorted(adj):
            cycle = self._find_cycle(adj, start)
            if cycle is None:
                continue
            key = frozenset(cycle)
            if key in seen_cycles:
                continue
            seen_cycles.add(key)
            pairs = list(zip(cycle, cycle[1:] + cycle[:1]))
            witnesses = "; ".join(
                f"{graph.edges[p].path}:{graph.edges[p].line}: "
                f"{graph.edges[p].how}" for p in pairs)
            first = graph.edges[pairs[0]]
            chain = " -> ".join(f"`{n}`" for n in cycle + [cycle[0]])
            yield Finding(
                self.name, first.path, first.line, 0,
                f"potential deadlock: lock cycle {chain}; acquisition "
                f"paths: {witnesses}", "")

    def _find_cycle(self, adj: Dict[str, List[str]],
                    start: str) -> Optional[List[str]]:
        """A simple cycle through `start`, as the node list, or None."""
        stack = [(start, [start])]
        visited: Set[str] = set()
        while stack:
            node, path = stack.pop()
            for nxt in sorted(adj.get(node, ())):
                if nxt == start:
                    return path
                if nxt in visited or nxt in path:
                    continue
                visited.add(nxt)
                stack.append((nxt, path + [nxt]))
        return None

    def _check_decls(self, ctx: ProjectContext, graph: _Graph,
                     decls: List[Tuple[str, int, List[str]]]
                     ) -> Iterator[Finding]:
        pairs: Dict[Tuple[str, str], Tuple[str, int]] = {}
        for rel, line, chain in decls:
            for tok in chain:
                if tok not in graph.locks:
                    yield ctx.finding(
                        self.name, rel, line,
                        f"lock-order declaration names `{tok}` but no "
                        "such lock exists (nodes are `Class.attr` / "
                        "`module.NAME`) — stale declaration")
            known = [t for t in chain if t in graph.locks]
            for i, a in enumerate(known):
                for b in known[i + 1:]:
                    pairs.setdefault((a, b), (rel, line))
        for (a, b), (rel, line) in sorted(pairs.items()):
            if (b, a) in pairs:
                other = pairs[(b, a)]
                if (a, b) < (b, a):   # report each contradiction once
                    yield ctx.finding(
                        self.name, rel, line,
                        f"contradictory lock-order declarations: "
                        f"`{a}` < `{b}` here but `{b}` < `{a}` at "
                        f"{other[0]}:{other[1]}")
            e = graph.edges.get((b, a))
            if e is not None:
                yield ctx.finding(
                    self.name, e.path, e.line,
                    f"acquisition order violates the declared hierarchy "
                    f"`{a}` < `{b}` ({rel}:{line}): {e.how} "
                    f"(at {e.path}:{e.line})")
