"""Rule family 4 — manifest-mediated file I/O (docs/ANALYSIS.md,
docs/ROBUSTNESS.md).

Every durable artifact in the store's blast radius (shards, posting lists,
PQ codes, generation manifests, checkpoints) is written through one of the
sanctioned writers — `write_shard` / `_atomic_dump` / a CRC-recording
helper built on `crc_file` — so that bytes land with fsync, size+CRC enter
a manifest, and the fault-injection hooks fire. A bare `open(..., "w")` or
`np.save` in those paths produces a file the verify gate cannot vouch for:
corruption hides until a reader trips over it.

A write call is sanctioned when an enclosing function IS one of the
sanctioned writers by name, or itself records a CRC (calls `crc_file`) —
the `_write_npy` pattern in index/ivf.py.
"""
from __future__ import annotations

import ast
from typing import Iterator, List

from dnn_page_vectors_tpu.tools.analyze.core import (
    FileContext, Finding, Rule, qualname, register, PKG_NAME)

_SANCTIONED_NAMES = {"write_shard", "_atomic_dump", "crc_file"}


def _calls_crc_file(fn: ast.AST, aliases) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            q = qualname(node.func, aliases)
            if q and q.split(".")[-1] == "crc_file":
                return True
    return False


def _write_mode(call: ast.Call) -> str:
    """The constant write mode of an open() call, or ''."""
    mode = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str) \
            and "w" in mode.value:
        return mode.value
    return ""


@register
class ManifestIORule(Rule):
    name = "manifest-io"
    family = "io"
    doc = ("bare open(...,'w')/np.save in store-adjacent write paths must "
           "route through write_shard/_atomic_dump/crc_file")
    scope = (f"{PKG_NAME}/index/", f"{PKG_NAME}/updates/",
             f"{PKG_NAME}/train/checkpoint.py", f"{PKG_NAME}/maintenance/")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        yield from self._scan(ctx, ctx.tree, sanctioned=False, stack=[])

    def _scan(self, ctx: FileContext, node: ast.AST, sanctioned: bool,
              stack: List[str]) -> Iterator[Finding]:
        for child in ast.iter_child_nodes(node):
            ok = sanctioned
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                ok = (sanctioned
                      or child.name in _SANCTIONED_NAMES
                      or _calls_crc_file(child, ctx.aliases))
            if isinstance(child, ast.Call) and not ok:
                yield from self._check_write(ctx, child)
            yield from self._scan(ctx, child, ok, stack)

    def _check_write(self, ctx: FileContext,
                     call: ast.Call) -> Iterator[Finding]:
        q = qualname(call.func, ctx.aliases)
        if isinstance(call.func, ast.Name) and call.func.id == "open":
            mode = _write_mode(call)
            if mode:
                yield ctx.finding(
                    self.name, call,
                    f"bare `open(..., \"{mode}\")` writes an unmanifested "
                    "file — route through write_shard/_atomic_dump so "
                    "bytes+CRC land in a manifest with fsync")
        elif q in ("numpy.save", "numpy.savez", "numpy.savez_compressed"):
            yield ctx.finding(
                self.name, call,
                f"bare `{q}(...)` writes an unmanifested array — use the "
                "CRC-recording writer pattern (`_write_npy`/`write_shard`)")
        elif (isinstance(call.func, ast.Attribute)
              and call.func.attr == "tofile"):
            yield ctx.finding(
                self.name, call,
                "bare `.tofile(...)` writes unmanifested bytes — use the "
                "CRC-recording writer pattern")
