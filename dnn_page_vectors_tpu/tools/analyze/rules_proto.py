"""Rule family 9 — wire-protocol conformance (docs/ANALYSIS.md).

The `DPV1` framing lives in exactly one module (`infer/transport.py`) and
exactly one doc (the docs/SERVING.md frame-layout table, plus the shed-
reason list in docs/OBSERVABILITY.md). Peers are written against the DOC;
the fleet runs the CODE — drift between them is a wire bug someone else
debugs months later. Same contract style as `drift-knobs`/`drift-events`:
both directions, machine-checked.

  * every `T_<NAME>` frame-type constant has a `NAME` row in the
    SERVING.md frame-layout table, and every row names a constant that
    exists (several names may share one row: "`HEARTBEAT` / `BYE`");
  * every `T_*` constant is registered in `_TYPES` (a type missing there
    is dead on arrival — `_check_header` rejects it at the socket);
  * every frame type has a bounded-length decode branch: a
    `decode_<name>` function, or an explicit `T_<NAME>` dispatch inside
    some `decode_*` function — EXCEPT types whose documented payload is
    literally `empty`;
  * every `decode_*` function guards its reads — a `len(...)` check, an
    exact-size `Struct.unpack`, or pure dispatch to other decoders — so
    a truncated payload can never index past the buffer silently;
  * every `FLAG_*` capability constant appears (backticked) in
    SERVING.md and vice versa;
  * every shed-reason string passed to `_shed_deadline("...")` anywhere
    in the package appears in the OBSERVABILITY.md "Shed reasons" table
    and vice versa.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, Set, Tuple

from dnn_page_vectors_tpu.tools.analyze.core import (
    Finding, ProjectContext, Rule, register, PKG_NAME)

_TRANSPORT = f"{PKG_NAME}/infer/transport.py"
_SERVING_DOC = "docs/SERVING.md"
_OBS_DOC = "docs/OBSERVABILITY.md"

_ROW_NAME_RE = re.compile(r"`([A-Z][A-Z_0-9]*)`")
_FLAG_DOC_RE = re.compile(r"`(FLAG_[A-Z_0-9]+)`")
_REASON_ROW_RE = re.compile(r"^\|\s*`([a-z][a-z_0-9]*)`")


def _line_of(text: str, pos: int) -> int:
    return text.count("\n", 0, pos) + 1


class _Transport:
    """AST facts about infer/transport.py."""

    def __init__(self, tree: ast.Module):
        self.types: Dict[str, int] = {}       # T_NAME -> lineno
        self.flags: Dict[str, int] = {}       # FLAG_NAME -> lineno
        self.registered: Set[str] = set()     # names inside _TYPES
        self.decoders: Dict[str, ast.FunctionDef] = {}
        self.dispatched: Set[str] = set()     # T_ names used in decode_*
        for node in tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                name = node.targets[0].id
                if name.startswith("T_") and isinstance(node.value,
                                                        ast.Constant):
                    self.types[name] = node.lineno
                elif name.startswith("FLAG_"):
                    self.flags[name] = node.lineno
                elif name == "_TYPES":
                    for n in ast.walk(node.value):
                        if isinstance(n, ast.Name) \
                                and n.id.startswith("T_"):
                            self.registered.add(n.id)
            elif isinstance(node, ast.FunctionDef) \
                    and node.name.startswith("decode_"):
                self.decoders[node.name] = node
                for n in ast.walk(node):
                    if isinstance(n, ast.Name) and n.id.startswith("T_"):
                        self.dispatched.add(n.id)

    def decoder_guarded(self, fn: ast.FunctionDef) -> bool:
        """A length guard: a len() call, an exact-size .unpack(...), or
        pure dispatch to other decode_* functions."""
        for n in ast.walk(fn):
            if not isinstance(n, ast.Call):
                continue
            if isinstance(n.func, ast.Name) and n.func.id == "len":
                return True
            if isinstance(n.func, ast.Attribute) \
                    and n.func.attr == "unpack":
                return True
            if isinstance(n.func, ast.Name) \
                    and n.func.id.startswith("decode_"):
                return True
        return False


def _frame_table(doc: str) -> Dict[str, Tuple[int, str]]:
    """SERVING.md frame rows: NAME -> (lineno, payload cell text). Rows
    whose first cell carries several backticked ALL-CAPS names document
    each of them (the `HEARTBEAT` / `BYE` row)."""
    out: Dict[str, Tuple[int, str]] = {}
    for i, line in enumerate(doc.splitlines(), 1):
        if not line.lstrip().startswith("|"):
            continue
        cells = [c.strip() for c in line.strip().strip("|").split("|")]
        if len(cells) < 2:
            continue
        payload = cells[1]
        for m in _ROW_NAME_RE.finditer(cells[0]):
            out.setdefault(m.group(1), (i, payload))
    return out


def _reason_table(doc: str) -> Dict[str, int]:
    """The OBSERVABILITY.md "Shed reasons" table: reason -> lineno."""
    lines = doc.splitlines()
    out: Dict[str, int] = {}
    in_section = False
    for i, line in enumerate(lines, 1):
        if line.startswith("#") and "Shed reasons" in line:
            in_section = True
            continue
        if in_section and line.startswith("#"):
            break
        if in_section:
            m = _REASON_ROW_RE.match(line)
            if m and m.group(1) != "reason":
                out.setdefault(m.group(1), i)
    return out


@register
class ProtoDriftRule(Rule):
    name = "proto-drift"
    family = "proto"
    doc = ("transport.py frame-type constants / capability flags / shed "
           "reasons match the docs/SERVING.md frame table and "
           "docs/OBSERVABILITY.md reason list both ways; every frame "
           "type decodes bounded")
    project = True

    def check_project(self, ctx: ProjectContext) -> Iterator[Finding]:
        fctx = ctx.file_context(_TRANSPORT)
        if fctx is None:
            return                # missing/broken: the parse rule owns it
        tp = _Transport(fctx.tree)
        serving = ctx.read(_SERVING_DOC)
        if serving is not None:
            yield from self._check_frames(ctx, tp, serving)
            yield from self._check_flags(ctx, tp, serving)
        yield from self._check_decoders(ctx, tp,
                                        serving if serving else "")
        yield from self._check_reasons(ctx)

    # -- frame table, both ways -------------------------------------------

    def _check_frames(self, ctx: ProjectContext, tp: _Transport,
                      serving: str) -> Iterator[Finding]:
        table = _frame_table(serving)
        for const, line in sorted(tp.types.items()):
            name = const[2:]
            if name not in table:
                yield ctx.finding(
                    self.name, _TRANSPORT, line,
                    f"frame type `{const}` has no row in the "
                    f"{_SERVING_DOC} frame-layout table — peers are "
                    "written against the doc; document the layout")
            if const not in tp.registered:
                yield ctx.finding(
                    self.name, _TRANSPORT, line,
                    f"frame type `{const}` is not registered in `_TYPES`"
                    " — _check_header REJECTS it at the socket, the "
                    "type is dead on arrival")
        for name, (line, _) in sorted(table.items()):
            if f"T_{name}" not in tp.types:
                yield ctx.finding(
                    self.name, _SERVING_DOC, line,
                    f"frame row `{name}` documents no transport.py "
                    f"constant (`T_{name}` missing) — stale table row")
        for const in sorted(tp.registered - set(tp.types)):
            yield ctx.finding(
                self.name, _TRANSPORT, 1,
                f"`_TYPES` registers `{const}` but no such constant is "
                "defined")

    # -- decode coverage ---------------------------------------------------

    def _check_decoders(self, ctx: ProjectContext, tp: _Transport,
                        serving: str) -> Iterator[Finding]:
        table = _frame_table(serving)
        for const, line in sorted(tp.types.items()):
            name = const[2:]
            payload = (table.get(name) or (0, ""))[1].strip().lower()
            if payload == "empty":
                continue          # nothing to decode, nothing to bound
            if f"decode_{name.lower()}" in tp.decoders:
                continue
            if const in tp.dispatched:
                continue          # handled by a decode_*_any dispatcher
            yield ctx.finding(
                self.name, _TRANSPORT, line,
                f"frame type `{const}` has no bounded-length decode "
                f"branch (no `decode_{name.lower()}` and no dispatch in "
                "any decode_* function) — an undecodable frame hangs "
                "protocol evolution on the receiver")
        for fname, fn in sorted(tp.decoders.items()):
            if not tp.decoder_guarded(fn):
                yield ctx.finding(
                    self.name, _TRANSPORT, fn.lineno,
                    f"decoder `{fname}` has no length guard (no len() "
                    "check, exact-size unpack, or decode_* dispatch) — "
                    "a truncated payload can read past the buffer")

    # -- capability flags, both ways --------------------------------------

    def _check_flags(self, ctx: ProjectContext, tp: _Transport,
                     serving: str) -> Iterator[Finding]:
        documented = {m.group(1): _line_of(serving, m.start())
                      for m in _FLAG_DOC_RE.finditer(serving)}
        for flag, line in sorted(tp.flags.items()):
            if flag not in documented:
                yield ctx.finding(
                    self.name, _TRANSPORT, line,
                    f"capability flag `{flag}` is not documented in "
                    f"{_SERVING_DOC} — negotiation bits are wire "
                    "contract, document the capability")
        for flag, line in sorted(documented.items()):
            if flag not in tp.flags:
                yield ctx.finding(
                    self.name, _SERVING_DOC, line,
                    f"doc names capability flag `{flag}` but "
                    f"transport.py defines no such constant — stale")

    # -- shed reasons, both ways ------------------------------------------

    def _check_reasons(self, ctx: ProjectContext) -> Iterator[Finding]:
        emitted: Dict[str, Tuple[str, int]] = {}
        for rel in ctx.glob(ctx.pkg, ".py"):
            if rel.startswith(f"{ctx.pkg}/tools/"):
                continue          # the analyzer quotes what it hunts
            fctx = ctx.file_context(rel)
            if fctx is None or "_shed_deadline" not in fctx.source:
                continue
            for node in ast.walk(fctx.tree):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "_shed_deadline"
                        and node.args
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    emitted.setdefault(node.args[0].value,
                                       (rel, node.lineno))
        if not emitted:
            return
        doc = ctx.read(_OBS_DOC)
        if doc is None:
            return
        documented = _reason_table(doc)
        if not documented:
            yield ctx.finding(
                self.name, _OBS_DOC, 1,
                f"{_OBS_DOC} has no \"Shed reasons\" table while the "
                f"package sheds with {len(emitted)} distinct reasons — "
                "add the table (docs/ANALYSIS.md `proto-drift`)")
            return
        for reason, (rel, line) in sorted(emitted.items()):
            if reason not in documented:
                yield ctx.finding(
                    self.name, rel, line,
                    f"shed reason `{reason}` is emitted here but "
                    f"missing from the {_OBS_DOC} \"Shed reasons\" "
                    "table")
        for reason, line in sorted(documented.items()):
            if reason not in emitted:
                yield ctx.finding(
                    self.name, _OBS_DOC, line,
                    f"shed reason `{reason}` is documented but nothing "
                    "sheds with it — dead table row")
