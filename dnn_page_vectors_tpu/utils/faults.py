"""Deterministic fault injection + transient-I/O retry (docs/ROBUSTNESS.md).

The crash-safety story (manifest-as-resume-unit in infer/vector_store.py,
deterministic resume-from-step in train/checkpoint.py) is only real if it
survives actual failures. This module supplies both halves of the proof:

  * `FaultPlan` — a SEEDED schedule of injected faults (`IOError`, file
    truncation, bit flips, delays) keyed on named operations. Production
    code calls `active().check(op)` before an I/O or staging operation and
    `active().corrupt(op, path)` after a file lands on disk; with no plan
    installed both are ~free no-ops. One plan + one seed reproduces the
    exact same failure sequence on every run, so every recovery path is a
    deterministic test, not a prayer.

  * `retry(fn, ...)` — the shared exponential-backoff-with-jitter wrapper
    for transient I/O, applied to shard writeback, manifest dumps, and
    checkpoint saves. A transient fault costs a retry; a persistent one
    re-raises the original exception at the original call site.

  * module-level fault COUNTERS — every injected fault, retry, shard
    quarantine, checkpoint rollback, and serve degradation bumps a named
    counter, surfaced through the metrics logs (train/embed/serve) and the
    bench record so recovery-path activity is observable, not silent.

Injection points (op names):
  shard_write    write_shard data-file write (check; inside retry) — both
                 the base layout and generation appends go through it
  shard_file     the shard .vec.npy after fsync (corrupt)
  manifest_dump  atomic manifest dump (check; inside retry)
  manifest_file  the manifest tmp file before its rename (corrupt)
  gen_manifest_dump  generation manifest dump (check; inside retry)
  gen_manifest_file  the generation manifest tmp before rename (corrupt) —
                 a torn generation manifest quarantines THAT generation
                 and readers keep the chain before it (docs/UPDATES.md)
  shard_read     store shard load (check)
  ckpt_save      CheckpointManager.save (check; inside retry)
  ckpt_file      the newest checkpoint step dir after save (corrupt_dir)
  hbm_stage      per-shard HBM staging in SearchService (check)
  index_write    IVF index build/update file write (check; inside retry) —
                 scheduling it during IVFIndex.update is the
                 posting-append fault: the index manifest stays untouched
                 and serving falls back to exact, visibly
  index_file     an IVF index file after fsync (corrupt)
  index_read     IVF posting load on open (check)
  compact_write  per-shard compacted-base write (check; docs/MAINTENANCE.md
                 — the compacted shard FILES additionally go through
                 shard_write/shard_file like every shard)
  compact_swap_dump  the compaction's atomic main-manifest flip (check;
                 inside retry) — tearing it here leaves the OLD chain
                 serving and the compact dir invisible
  compact_swap_file  the flip's tmp file before rename (corrupt)
  index_swap_dump    the background rebuild's index-dir pointer flip
                 (check; inside retry)
  index_swap_file    the pointer flip's tmp file before rename (corrupt)
  bg_rebuild     start of a background index rebuild (check) — the
                 build's own writes still carry index_write/index_file
  lease_dump     append-lease file write (check; inside retry)
  lease_file     the lease tmp file before rename (corrupt)

Plan syntax (config `faults.plan` / CLI `--faults`):
  "op:kind:at[:count]" joined by commas; `at` is the 0-based index of the
  matching call that first faults, `count` how many consecutive calls fault
  (default 1 = transient; `*` = persistent). Kinds: io_error, truncate,
  bit_flip, delay. Example — second shard write fails once, the shard-2
  data file is truncated on disk, the latest checkpoint is torn:
  "shard_write:io_error:1,shard_file:truncate:2,ckpt_file:truncate:2"
"""
from __future__ import annotations

import dataclasses
import os
import random
import sys
import threading
import time
from typing import Dict, List, Optional

KINDS = ("io_error", "truncate", "bit_flip", "delay")
PERSISTENT = 1_000_000          # `count` spelling of "every call from `at`"


class InjectedFault(IOError):
    """An injected I/O failure. Subclasses IOError/OSError so production
    retry/except paths treat it exactly like a real transient I/O error —
    the injection layer must never need special-casing in recovery code."""


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    op: str
    kind: str
    at: int = 0          # 0-based index of the first faulted call
    count: int = 1       # consecutive calls faulted from `at`

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; have {KINDS}")
        if self.at < 0 or self.count < 1:
            raise ValueError(f"bad fault schedule at={self.at} "
                             f"count={self.count}")


class FaultPlan:
    """A seeded, scheduled set of faults. Thread-safe: the bulk-embed
    writer thread and tokenizer workers share one plan with the main
    thread. Deterministic: per-op call counters + one seeded RNG decide
    exactly which call faults and which byte/bit a corruption touches."""

    def __init__(self, specs: List[FaultSpec] = (), seed: int = 0):
        self._specs = list(specs)
        self._rng = random.Random(seed)
        self._calls: Dict[str, int] = {}
        self._lock = threading.Lock()

    @classmethod
    def parse(cls, text: str, seed: int = 0) -> "FaultPlan":
        specs = []
        for part in (text or "").split(","):
            part = part.strip()
            if not part:
                continue
            bits = part.split(":")
            if len(bits) not in (2, 3, 4):
                raise ValueError(
                    f"bad fault spec {part!r} (want op:kind[:at[:count]])")
            op, kind = bits[0], bits[1]
            at = int(bits[2]) if len(bits) > 2 else 0
            count = (PERSISTENT if len(bits) > 3 and bits[3] in ("*", "inf")
                     else int(bits[3]) if len(bits) > 3 else 1)
            specs.append(FaultSpec(op=op, kind=kind, at=at, count=count))
        return cls(specs, seed=seed)

    def _fire(self, op: str, kinds: tuple) -> Optional[FaultSpec]:
        """Advance op's call counter; return the spec scheduled to fault
        THIS call (restricted to `kinds`), if any."""
        with self._lock:
            i = self._calls.get(op, 0)
            self._calls[op] = i + 1
            for s in self._specs:
                if (s.op == op and s.kind in kinds
                        and s.at <= i < s.at + s.count):
                    return s
        return None

    def pending(self, op: str) -> bool:
        """True while any spec for `op` has calls left to fault."""
        with self._lock:
            i = self._calls.get(op, 0)
            return any(s.op == op and i < s.at + s.count
                       for s in self._specs)

    # -- injection points --------------------------------------------------
    def check(self, op: str) -> None:
        """Call before an I/O / staging operation: raises InjectedFault or
        sleeps when a fault is scheduled for this call of `op`."""
        if not self._specs:
            return
        spec = self._fire(op, ("io_error", "delay"))
        if spec is None:
            return
        count(f"injected_{op}_{spec.kind}")
        if spec.kind == "delay":
            with self._lock:
                t = 0.01 + 0.04 * self._rng.random()
            time.sleep(t)
            return
        raise InjectedFault(f"injected fault: {op} "
                            f"(call {self._calls[op] - 1}, spec {spec})")

    def corrupt(self, op: str, path: str) -> bool:
        """Call after a file is durably on disk: applies a scheduled
        truncation / bit flip to it. Returns True when the file was
        damaged."""
        if not self._specs:
            return False
        spec = self._fire(op, ("truncate", "bit_flip"))
        if spec is None:
            return False
        self._damage(spec.kind, path)
        count(f"injected_{op}_{spec.kind}")
        return True

    def corrupt_dir(self, op: str, directory: str) -> bool:
        """Like corrupt(), applied to EVERY non-empty file under
        `directory` (recursively). Checkpoint formats keep redundant copies
        of array data (e.g. orbax OCDBT), so damaging one file can be
        silently absorbed; a corrupt-checkpoint injection must reliably
        break the restore or the rollback path under test never runs."""
        if not self._specs:
            return False
        spec = self._fire(op, ("truncate", "bit_flip"))
        if spec is None:
            return False
        hit = False
        for root, _, names in os.walk(directory):
            for n in sorted(names):
                p = os.path.join(root, n)
                try:
                    if os.path.getsize(p) > 0:
                        self._damage(spec.kind, p)
                        hit = True
                except OSError:
                    continue
        if hit:
            count(f"injected_{op}_{spec.kind}")
        return hit

    def _damage(self, kind: str, path: str) -> None:
        size = os.path.getsize(path)
        if kind == "truncate":
            with open(path, "r+b") as f:
                f.truncate(size // 2)
        else:                                       # bit_flip
            with self._lock:
                off = self._rng.randrange(max(size, 1))
                bit = self._rng.randrange(8)
            with open(path, "r+b") as f:
                f.seek(off)
                b = f.read(1)
                f.seek(off)
                f.write(bytes([(b[0] if b else 0) ^ (1 << bit)]))


_NULL_PLAN = FaultPlan()
_ACTIVE: FaultPlan = _NULL_PLAN


def install(plan: FaultPlan) -> FaultPlan:
    """Make `plan` the process-wide active plan (injection points are
    ambient: the store/checkpoint/serve layers must not need a plan handle
    threaded through every signature)."""
    global _ACTIVE
    _ACTIVE = plan
    return plan


def install_from_config(cfg) -> Optional[FaultPlan]:
    """CLI entry: install cfg.faults.plan (when non-empty) and adopt the
    config's retry policy as the module default."""
    f = cfg.faults
    configure_retry(f.retry_attempts, f.retry_backoff_s, f.retry_jitter_s)
    if not f.plan:
        return None
    return install(FaultPlan.parse(f.plan, seed=f.seed))


def active() -> FaultPlan:
    return _ACTIVE


def reset() -> None:
    """Drop the active plan, counters, and retry overrides (test hygiene)."""
    global _ACTIVE, _RETRY
    _ACTIVE = _NULL_PLAN
    _RETRY = dict(_RETRY_DEFAULTS)
    with _COUNTER_LOCK:
        _COUNTERS.clear()


# -- fault counters ---------------------------------------------------------

_COUNTERS: Dict[str, int] = {}
_COUNTER_LOCK = threading.Lock()


def count(event: str, n: int = 1) -> None:
    with _COUNTER_LOCK:
        _COUNTERS[event] = _COUNTERS.get(event, 0) + n
    # mirror into the process-wide metrics registry (docs/OBSERVABILITY.md)
    # so fault/recovery activity shows up in the same exposition as every
    # other instrument — `counters()` stays the dict the metrics lines and
    # tests read
    from dnn_page_vectors_tpu.utils import telemetry
    telemetry.default_registry().counter(f"fault.{event}").inc(n)


def counters() -> Dict[str, int]:
    """Snapshot of every fault/recovery event this process has seen —
    injected_*, retry_*, quarantined_shards, ckpt_rollback, serve_*."""
    with _COUNTER_LOCK:
        return dict(sorted(_COUNTERS.items()))


def warn(msg: str) -> None:
    print(f"WARNING: {msg}", file=sys.stderr)


# -- transient-I/O retry ----------------------------------------------------

_RETRY_DEFAULTS = {"attempts": 3, "backoff": 0.05, "jitter": 0.02}
_RETRY = dict(_RETRY_DEFAULTS)


def configure_retry(attempts: int, backoff: float, jitter: float) -> None:
    _RETRY.update(attempts=max(1, int(attempts)), backoff=float(backoff),
                  jitter=float(jitter))


def retry(fn, op: str = "io", max_attempts: Optional[int] = None,
          backoff: Optional[float] = None, jitter: Optional[float] = None,
          retry_on: tuple = (OSError,), profiler=None):
    """Run fn(); on a transient `retry_on` failure, back off (exponential +
    uniform jitter) and re-run, up to `max_attempts` total attempts. The
    final failure re-raises the ORIGINAL exception — callers' except
    clauses and the resume bookkeeping see the same surface as without
    retry. Backoff sleep lands in `profiler` as stage `io_retry` when one
    is passed."""
    attempts = _RETRY["attempts"] if max_attempts is None else max_attempts
    base = _RETRY["backoff"] if backoff is None else backoff
    jit = _RETRY["jitter"] if jitter is None else jitter
    for attempt in range(attempts):
        try:
            return fn()
        except retry_on as e:
            if attempt + 1 >= attempts:
                raise
            count(f"retry_{op}")
            delay = base * (2 ** attempt) + random.uniform(0.0, jit)
            warn(f"transient {op} failure ({type(e).__name__}: {e}); "
                 f"retry {attempt + 1}/{attempts - 1} in {delay:.3f}s")
            t0 = time.perf_counter()
            time.sleep(delay)
            if profiler is not None:
                profiler.add("io_retry", time.perf_counter() - t0)
