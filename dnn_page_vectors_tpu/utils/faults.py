"""Deterministic fault injection + transient-I/O retry (docs/ROBUSTNESS.md).

The crash-safety story (manifest-as-resume-unit in infer/vector_store.py,
deterministic resume-from-step in train/checkpoint.py) is only real if it
survives actual failures. This module supplies both halves of the proof:

  * `FaultPlan` — a SEEDED schedule of injected faults (`IOError`, file
    truncation, bit flips, delays) keyed on named operations. Production
    code calls `active().check(op)` before an I/O or staging operation and
    `active().corrupt(op, path)` after a file lands on disk; with no plan
    installed both are ~free no-ops. One plan + one seed reproduces the
    exact same failure sequence on every run, so every recovery path is a
    deterministic test, not a prayer.

  * `retry(fn, ...)` — the shared exponential-backoff-with-jitter wrapper
    for transient I/O, applied to shard writeback, manifest dumps, and
    checkpoint saves. A transient fault costs a retry; a persistent one
    re-raises the original exception at the original call site.

  * module-level fault COUNTERS — every injected fault, retry, shard
    quarantine, checkpoint rollback, and serve degradation bumps a named
    counter, surfaced through the metrics logs (train/embed/serve) and the
    bench record so recovery-path activity is observable, not silent.

Injection points (op names):
  shard_write    write_shard data-file write (check; inside retry) — both
                 the base layout and generation appends go through it
  shard_file     the shard .vec.npy after fsync (corrupt)
  manifest_dump  atomic manifest dump (check; inside retry)
  manifest_file  the manifest tmp file before its rename (corrupt)
  gen_manifest_dump  generation manifest dump (check; inside retry)
  gen_manifest_file  the generation manifest tmp before rename (corrupt) —
                 a torn generation manifest quarantines THAT generation
                 and readers keep the chain before it (docs/UPDATES.md)
  shard_read     store shard load (check)
  ckpt_save      CheckpointManager.save (check; inside retry)
  ckpt_file      the newest checkpoint step dir after save (corrupt_dir)
  hbm_stage      per-shard HBM staging in SearchService (check)
  index_write    IVF index build/update file write (check; inside retry) —
                 scheduling it during IVFIndex.update is the
                 posting-append fault: the index manifest stays untouched
                 and serving falls back to exact, visibly
  index_file     an IVF index file after fsync (corrupt)
  index_read     IVF posting load on open (check)
  compact_write  per-shard compacted-base write (check; docs/MAINTENANCE.md
                 — the compacted shard FILES additionally go through
                 shard_write/shard_file like every shard)
  compact_swap_dump  the compaction's atomic main-manifest flip (check;
                 inside retry) — tearing it here leaves the OLD chain
                 serving and the compact dir invisible
  compact_swap_file  the flip's tmp file before rename (corrupt)
  index_swap_dump    the background rebuild's index-dir pointer flip
                 (check; inside retry)
  index_swap_file    the pointer flip's tmp file before rename (corrupt)
  bg_rebuild     start of a background index rebuild (check) — the
                 build's own writes still carry index_write/index_file
  lease_dump     append-lease file write (check; inside retry)
  lease_file     the lease tmp file before rename (corrupt)
  migrate_write  per-shard re-stamped write during a rolling model
                 migration (check; docs/MAINTENANCE.md "Rolling model
                 migration" — the re-embedded shard FILES additionally go
                 through shard_write/shard_file like every shard)
  migrate_swap_dump  a migration unit's atomic main-manifest flip (check;
                 inside retry) — tearing it here leaves the previous
                 stamp mix serving and the migrate dir invisible
  migrate_swap_file  the migration flip's tmp file before rename (corrupt)

Wire injection points (docs/ROBUSTNESS.md "Network failure model") — the
serve fleet's DPV1 frame paths call `active().wire(op)` and act on the
returned spec themselves (only the call site holds the socket):
  wire_send        every framed send (FrameSender.send / write_frame)
  wire_recv        every framed read (read_frame / read_frame_async)
  worker_dial      PartitionWorker dial+REGISTER (check + wire; inside
                   retry_wire)
  gateway_accept   WorkerGateway accept loop, per accepted connection
  cache_peer_send  result-cache peer probes (CACHE_LOOKUP / CACHE_PUT)

Plan syntax (config `faults.plan` / CLI `--faults` / `--chaos`):
  "op:kind:at[:count]" joined by commas; `at` is the 0-based index of the
  matching call that first faults, `count` how many consecutive calls fault
  (default 1 = transient; `*` = persistent). Filesystem kinds: io_error,
  truncate, bit_flip, delay. Wire kinds: conn_drop (close the socket
  mid-stream), frame_delay (seeded stall before a send), frame_trunc (send
  a prefix then close), frame_dup (re-send the frame twice). Example —
  second shard write fails once, the third framed send is torn:
  "shard_write:io_error:1,wire_send:frame_trunc:2"
"""
from __future__ import annotations

import dataclasses
import os
import random
import sys
import threading
import time
from typing import Dict, List, Optional

WIRE_KINDS = ("conn_drop", "frame_delay", "frame_trunc", "frame_dup")
KINDS = ("io_error", "truncate", "bit_flip", "delay") + WIRE_KINDS
PERSISTENT = 1_000_000          # `count` spelling of "every call from `at`"


class InjectedFault(IOError):
    """An injected I/O failure. Subclasses IOError/OSError so production
    retry/except paths treat it exactly like a real transient I/O error —
    the injection layer must never need special-casing in recovery code."""


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    op: str
    kind: str
    at: int = 0          # 0-based index of the first faulted call
    count: int = 1       # consecutive calls faulted from `at`

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; have {KINDS}")
        if self.at < 0 or self.count < 1:
            raise ValueError(f"bad fault schedule at={self.at} "
                             f"count={self.count}")


class FaultPlan:
    """A seeded, scheduled set of faults. Thread-safe: the bulk-embed
    writer thread and tokenizer workers share one plan with the main
    thread. Deterministic: per-op call counters + one seeded RNG decide
    exactly which call faults and which byte/bit a corruption touches."""

    def __init__(self, specs: List[FaultSpec] = (), seed: int = 0):
        self._specs = list(specs)
        self._rng = random.Random(seed)
        self._calls: Dict[str, int] = {}
        self._lock = threading.Lock()

    @classmethod
    def parse(cls, text: str, seed: int = 0) -> "FaultPlan":
        specs = []
        for part in (text or "").split(","):
            part = part.strip()
            if not part:
                continue
            bits = part.split(":")
            if len(bits) not in (2, 3, 4):
                raise ValueError(
                    f"bad fault spec {part!r} (want op:kind[:at[:count]])")
            op, kind = bits[0], bits[1]
            at = int(bits[2]) if len(bits) > 2 else 0
            count = (PERSISTENT if len(bits) > 3 and bits[3] in ("*", "inf")
                     else int(bits[3]) if len(bits) > 3 else 1)
            specs.append(FaultSpec(op=op, kind=kind, at=at, count=count))
        return cls(specs, seed=seed)

    def _fire(self, op: str, kinds: tuple) -> Optional[FaultSpec]:
        """Advance op's call counter; return the spec scheduled to fault
        THIS call (restricted to `kinds`), if any."""
        with self._lock:
            i = self._calls.get(op, 0)
            self._calls[op] = i + 1
            for s in self._specs:
                if (s.op == op and s.kind in kinds
                        and s.at <= i < s.at + s.count):
                    return s
        return None

    def pending(self, op: str) -> bool:
        """True while any spec for `op` has calls left to fault."""
        with self._lock:
            i = self._calls.get(op, 0)
            return any(s.op == op and i < s.at + s.count
                       for s in self._specs)

    # -- injection points --------------------------------------------------
    def check(self, op: str) -> None:
        """Call before an I/O / staging operation: raises InjectedFault or
        sleeps when a fault is scheduled for this call of `op`."""
        if not self._specs:
            return
        spec = self._fire(op, ("io_error", "delay"))
        if spec is None:
            return
        count(f"injected_{op}_{spec.kind}")
        if spec.kind == "delay":
            with self._lock:
                t = 0.01 + 0.04 * self._rng.random()
            time.sleep(t)
            return
        raise InjectedFault(f"injected fault: {op} "
                            f"(call {self._calls[op] - 1}, spec {spec})")

    def wire(self, op: str) -> Optional[FaultSpec]:
        """Call once per framed wire operation (`wire_send`, `wire_recv`,
        `gateway_accept`, ...): advances op's call counter and returns the
        spec scheduled to fault THIS call, if any. Unlike check(), the
        ACTION is the caller's job — only the transport call site holds the
        socket and the frame bytes needed to drop/truncate/duplicate, so
        this method just decides and accounts. io_error and delay specs on
        a wire op fire here too (an io_error behaves like conn_drop at call
        sites without a live socket, e.g. worker_dial)."""
        if not self._specs:
            return None
        spec = self._fire(op, ("io_error", "delay") + WIRE_KINDS)
        if spec is None:
            return None
        count(f"injected_{op}_{spec.kind}")
        return spec

    def wire_delay_s(self) -> float:
        """Seeded stall length for a frame_delay / delay wire spec."""
        with self._lock:
            return 0.01 + 0.04 * self._rng.random()

    def corrupt(self, op: str, path: str) -> bool:
        """Call after a file is durably on disk: applies a scheduled
        truncation / bit flip to it. Returns True when the file was
        damaged."""
        if not self._specs:
            return False
        spec = self._fire(op, ("truncate", "bit_flip"))
        if spec is None:
            return False
        self._damage(spec.kind, path)
        count(f"injected_{op}_{spec.kind}")
        return True

    def corrupt_dir(self, op: str, directory: str) -> bool:
        """Like corrupt(), applied to EVERY non-empty file under
        `directory` (recursively). Checkpoint formats keep redundant copies
        of array data (e.g. orbax OCDBT), so damaging one file can be
        silently absorbed; a corrupt-checkpoint injection must reliably
        break the restore or the rollback path under test never runs."""
        if not self._specs:
            return False
        spec = self._fire(op, ("truncate", "bit_flip"))
        if spec is None:
            return False
        hit = False
        for root, _, names in os.walk(directory):
            for n in sorted(names):
                p = os.path.join(root, n)
                try:
                    if os.path.getsize(p) > 0:
                        self._damage(spec.kind, p)
                        hit = True
                except OSError:
                    continue
        if hit:
            count(f"injected_{op}_{spec.kind}")
        return hit

    def _damage(self, kind: str, path: str) -> None:
        size = os.path.getsize(path)
        if kind == "truncate":
            with open(path, "r+b") as f:
                f.truncate(size // 2)
        else:                                       # bit_flip
            with self._lock:
                off = self._rng.randrange(max(size, 1))
                bit = self._rng.randrange(8)
            with open(path, "r+b") as f:
                f.seek(off)
                b = f.read(1)
                f.seek(off)
                f.write(bytes([(b[0] if b else 0) ^ (1 << bit)]))


_NULL_PLAN = FaultPlan()
_ACTIVE: FaultPlan = _NULL_PLAN


def install(plan: FaultPlan) -> FaultPlan:
    """Make `plan` the process-wide active plan (injection points are
    ambient: the store/checkpoint/serve layers must not need a plan handle
    threaded through every signature)."""
    global _ACTIVE
    _ACTIVE = plan
    return plan


def install_from_config(cfg) -> Optional[FaultPlan]:
    """CLI entry: install cfg.faults.plan (when non-empty) and adopt the
    config's retry policy as the module default."""
    f = cfg.faults
    configure_retry(f.retry_attempts, f.retry_backoff_s, f.retry_jitter_s)
    if not f.plan:
        return None
    return install(FaultPlan.parse(f.plan, seed=f.seed))


def active() -> FaultPlan:
    return _ACTIVE


def reset() -> None:
    """Drop the active plan, counters, and retry overrides (test hygiene)."""
    global _ACTIVE, _RETRY
    _ACTIVE = _NULL_PLAN
    _RETRY = dict(_RETRY_DEFAULTS)
    with _COUNTER_LOCK:
        _COUNTERS.clear()


# -- fault counters ---------------------------------------------------------

_COUNTERS: Dict[str, int] = {}
_COUNTER_LOCK = threading.Lock()


def count(event: str, n: int = 1) -> None:
    with _COUNTER_LOCK:
        _COUNTERS[event] = _COUNTERS.get(event, 0) + n
    # mirror into the process-wide metrics registry (docs/OBSERVABILITY.md)
    # so fault/recovery activity shows up in the same exposition as every
    # other instrument — `counters()` stays the dict the metrics lines and
    # tests read
    from dnn_page_vectors_tpu.utils import telemetry
    telemetry.default_registry().counter(f"fault.{event}").inc(n)


def counters() -> Dict[str, int]:
    """Snapshot of every fault/recovery event this process has seen —
    injected_*, retry_*, quarantined_shards, ckpt_rollback, serve_*."""
    with _COUNTER_LOCK:
        return dict(sorted(_COUNTERS.items()))


def warn(msg: str) -> None:
    print(f"WARNING: {msg}", file=sys.stderr)


# -- transient-I/O retry ----------------------------------------------------

_RETRY_DEFAULTS = {"attempts": 3, "backoff": 0.05, "jitter": 0.02}
_RETRY = dict(_RETRY_DEFAULTS)


def configure_retry(attempts: int, backoff: float, jitter: float) -> None:
    _RETRY.update(attempts=max(1, int(attempts)), backoff=float(backoff),
                  jitter=float(jitter))


def retry(fn, op: str = "io", max_attempts: Optional[int] = None,
          backoff: Optional[float] = None, jitter: Optional[float] = None,
          retry_on: tuple = (OSError,), profiler=None,
          max_backoff: Optional[float] = None):
    """Run fn(); on a transient `retry_on` failure, back off (exponential +
    uniform jitter, capped at `max_backoff` when given) and re-run, up to
    `max_attempts` total attempts. The final failure re-raises the ORIGINAL
    exception — callers' except clauses and the resume bookkeeping see the
    same surface as without retry. Backoff sleep lands in `profiler` as
    stage `io_retry` when one is passed."""
    attempts = _RETRY["attempts"] if max_attempts is None else max_attempts
    base = _RETRY["backoff"] if backoff is None else backoff
    jit = _RETRY["jitter"] if jitter is None else jitter
    for attempt in range(attempts):
        try:
            return fn()
        except retry_on as e:
            if attempt + 1 >= attempts:
                raise
            count(f"retry_{op}")
            delay = base * (2 ** attempt) + random.uniform(0.0, jit)
            if max_backoff is not None:
                delay = min(delay, max_backoff)
            warn(f"transient {op} failure ({type(e).__name__}: {e}); "
                 f"retry {attempt + 1}/{attempts - 1} in {delay:.3f}s")
            t0 = time.perf_counter()
            time.sleep(delay)
            if profiler is not None:
                profiler.add("io_retry", time.perf_counter() - t0)


def retry_wire(fn, op: str = "wire", attempts: Optional[int] = None,
               backoff: Optional[float] = None,
               max_backoff: Optional[float] = None):
    """The WIRE retry profile (docs/ROBUSTNESS.md "Network failure model").

    `retry()`'s defaults are filesystem-tuned (short backoff, no cap —
    disks come back fast or not at all); a dialing worker instead wants a
    bounded exponential ramp so a restarting gateway is not hammered.
    Call-site discipline: only wrap IDEMPOTENT operations — dial, REGISTER
    (re-registration replaces the previous connection), CACHE_LOOKUP.
    Never wrap a CACHE_PUT: a duplicate put after an ambiguous failure can
    resurrect an entry a concurrent refresh just invalidated, so puts stay
    fire-and-forget (SocketSearchClient.cache_put drops on OSError).

    attempts/backoff default from the module retry policy; `max_backoff`
    should carry the caller's `serve.reconnect_max_s` cap."""
    return retry(fn, op=op, max_attempts=attempts, backoff=backoff,
                 retry_on=(OSError,), max_backoff=max_backoff)


# -- circuit breaker --------------------------------------------------------


class CircuitBreaker:
    """Per-target wire circuit breaker (docs/ROBUSTNESS.md "Network
    failure model"). CLOSED: traffic flows, consecutive failures are
    counted. After `failures` consecutive failures the breaker OPENS:
    `allow()` answers False so the caller routes straight to its fallback
    without paying a dial/timeout per request. After `open_s` it admits
    exactly ONE half-open probe; a success closes the breaker, a failure
    re-opens it with the backoff doubled (capped at `max_open_s`).

    `clock` is injectable for fake-clock tests. The optional `on_open` /
    `on_close` callbacks fire on state transitions OUTSIDE the lock (they
    typically emit registry events; holding `_lock` across them would
    pin a lock order against the caller's own locks)."""

    def __init__(self, failures: int = 3, open_s: float = 0.25,
                 max_open_s: float = 30.0, clock=time.monotonic,
                 on_open=None, on_close=None):
        self._threshold = max(1, int(failures))
        self._base_open_s = float(open_s)
        self._max_open_s = float(max_open_s)
        self._clock = clock
        self._on_open = on_open
        self._on_close = on_close
        self._lock = threading.Lock()
        self._state = "closed"            # guarded-by: _lock
        self._failures = 0                # guarded-by: _lock (consecutive)
        self._open_s = float(open_s)      # guarded-by: _lock (current ramp)
        self._opened_at = 0.0             # guarded-by: _lock
        self._trips = 0                   # guarded-by: _lock

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def trips(self) -> int:
        with self._lock:
            return self._trips

    def allow(self) -> bool:
        """May traffic be sent to this target right now? Open → False
        until the backoff elapses, then flips to half-open and admits the
        caller as THE single probe (further calls answer False until the
        probe reports back). Call it last in a routing decision — a True
        answer from a half-open breaker consumes the probe slot."""
        with self._lock:
            if self._state == "closed":
                return True
            if self._state == "open":
                if self._clock() - self._opened_at < self._open_s:
                    return False
                self._state = "half_open"
                return True
            return False                  # half_open: probe already out

    def record_success(self) -> None:
        """A request to the target completed: close + reset the ramp."""
        cb = None
        with self._lock:
            if self._state != "closed":
                cb = self._on_close
            self._state = "closed"
            self._failures = 0
            self._open_s = self._base_open_s
        if cb is not None:
            cb(self)

    def record_failure(self) -> None:
        """A request to the target failed at the wire. The K-th
        consecutive failure opens the breaker; a failed half-open probe
        re-opens it with the backoff doubled."""
        cb = None
        with self._lock:
            self._failures += 1
            if self._state == "half_open":
                self._state = "open"
                self._opened_at = self._clock()
                self._open_s = min(self._open_s * 2.0, self._max_open_s)
                self._trips += 1
                cb = self._on_open
            elif self._state == "closed" and self._failures >= self._threshold:
                self._state = "open"
                self._opened_at = self._clock()
                self._trips += 1
                cb = self._on_open
        if cb is not None:
            cb(self)

    def reset(self) -> None:
        """Forget history (a worker re-registered: liveness is restored,
        the fresh connection deserves a clean slate)."""
        self.record_success()
