"""Utilities: metrics logging, profiling hooks (SURVEY.md §3 #26, §5.1, §5.5)."""
from dnn_page_vectors_tpu.utils.logging import MetricsLogger

__all__ = ["MetricsLogger"]
