"""Central metrics registry (docs/OBSERVABILITY.md).

Before this module, every layer kept its own ad-hoc numbers: SearchService
counted cache hits in plain ints, faults.py had a module dict, the train
and embed loops computed pages/sec inline, and nothing could answer "what
was the QPS in the last ten seconds" — `LatencyStats` knew the p99 *since
boot*, which is not an SLO. This module is the one place those numbers
live:

  * typed instruments — `Counter` (monotonic, optionally with a rolling
    window so `rate()` answers "per second, over the last N seconds"),
    `Gauge` (last-set value), `Histogram` (bounded `Reservoir` for
    since-boot percentiles + a time-windowed deque for live p50/p99) —
    created/fetched by name through `MetricsRegistry`;
  * an **event channel** — a bounded ring of lifecycle transitions (view
    hot-swap, shard quarantine, drift rebuild, degraded/restored,
    checkpoint rollback), each optionally stamped with the trace id of the
    request that observed it (utils/tracing.py), so a latency spike in the
    slow-query log and the refresh that caused it correlate by id;
  * **exposition** — `snapshot()` (JSON-serializable, feeds the metrics
    jsonl and tests) and `prometheus_text()` (text format, feeds
    `cli serve-metrics` and anything that scrapes).

Memory is bounded BY CONSTRUCTION: reservoirs cap their sample buffers
(Algorithm R keeps a uniform sample of an unbounded stream), windows prune
by age and cap by count, the event ring has a maxlen. A registry on a
service that runs for months costs the same bytes as one on a service that
ran for a minute.

`default_registry()` is the process-wide instance for layers that have no
natural owner to hand them one (fault counters, the train/embed loops,
checkpoint rollback); `SearchService` builds its own per-service registry
so concurrent/sequential services never mix counters.
"""
from __future__ import annotations

import json
import re
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

DEFAULT_WINDOW_S = 10.0
# sub-second bucket granularity for windowed counters: bounds the bucket
# ring at window_s / _BUCKET_S entries no matter the event rate
_BUCKET_S = 0.1


class Reservoir:
    """Bounded uniform sample of an unbounded stream (Algorithm R) with
    EXACT running count/sum — percentiles are estimated from the sample,
    count and mean never are. Below `cap` samples the buffer holds every
    observation, so small-n percentiles are exact nearest-rank (the
    property tests/test_profiling.py pins). Thread-safe; seeded, so a
    replayed run samples identically."""

    def __init__(self, cap: int = 4096, seed: int = 0):
        import random
        self._cap = max(1, int(cap))
        self._n = 0                      # guarded-by: _lock
        self._sum = 0.0                  # guarded-by: _lock
        self._buf: List[float] = []      # guarded-by: _lock
        self._rng = random.Random(seed)  # guarded-by: _lock
        self._lock = threading.Lock()

    def add(self, value: float) -> None:
        v = float(value)
        with self._lock:
            self._n += 1
            self._sum += v
            if len(self._buf) < self._cap:
                self._buf.append(v)
            else:
                j = self._rng.randrange(self._n)
                if j < self._cap:
                    self._buf[j] = v

    @property
    def count(self) -> int:
        with self._lock:
            return self._n

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    @property
    def mean(self) -> float:
        with self._lock:
            return self._sum / self._n if self._n else 0.0

    def percentile(self, q: float) -> float:
        """Nearest-rank percentile (q in [0, 100]); 0.0 with no samples.
        Same semantics as the pre-registry LatencyStats: the returned value
        is a sample the stream actually delivered, not an interpolation."""
        with self._lock:
            if not self._buf:
                return 0.0
            s = sorted(self._buf)
        return s[nearest_rank(q, len(s))]


def nearest_rank(q: float, n: int) -> int:
    """0-based index of the nearest-rank q-th percentile in a sorted list
    of n samples: ceil(q*n/100) - 1, clamped to [0, n-1]. q=0 -> the min,
    q=100 -> the max, and the p50 of an even count is the LOWER middle."""
    return max(0, min(n - 1, int(-(-q * n // 100)) - 1))


class Counter:
    """Monotonic counter. With `window_s`, also keeps a ring of sub-second
    buckets so `rate()` reports events/sec over the last window — the live
    view (qps, error rate) the since-boot total can't give."""

    kind = "counter"

    def __init__(self, name: str, window_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.name = name
        self.window_s = float(window_s) if window_s else None
        self._clock = clock
        self._v = 0                          # guarded-by: _lock
        # (bucket_start_ts, count) ring — guarded-by: _lock
        self._buckets: deque = deque()       # guarded-by: _lock
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._v += n
            if self.window_s is not None:
                t = self._clock()
                b = t - (t % _BUCKET_S)
                if self._buckets and self._buckets[-1][0] == b:
                    self._buckets[-1][1] += n
                else:
                    self._buckets.append([b, n])
                self._prune(t)

    def _prune(self, now: float) -> None:  # holds-lock: _lock
        horizon = now - self.window_s
        while self._buckets and self._buckets[0][0] < horizon:
            self._buckets.popleft()

    @property
    def value(self) -> int:
        with self._lock:
            return self._v

    def window_count(self) -> int:
        """Events inside the rolling window (0 when un-windowed)."""
        if self.window_s is None:
            return 0
        with self._lock:
            self._prune(self._clock())
            return sum(n for _, n in self._buckets)

    def rate(self) -> float:
        """Events/sec over the rolling window; 0.0 when un-windowed."""
        if self.window_s is None:
            return 0.0
        return self.window_count() / self.window_s


class Gauge:
    """Last-set value (float)."""

    kind = "gauge"

    def __init__(self, name: str):
        self.name = name
        self._v = 0.0                        # guarded-by: _lock
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self._v = float(value)

    @property
    def value(self) -> float:
        with self._lock:
            return self._v


class Histogram:
    """Streaming distribution: a bounded `Reservoir` answers since-boot
    count/mean/percentiles; a time-windowed deque (pruned by age, capped
    by count) answers the live window's p50/p99 and rate. Both are bounded
    regardless of how long the process lives."""

    kind = "histogram"

    def __init__(self, name: str, window_s: Optional[float] = DEFAULT_WINDOW_S,
                 cap: int = 4096, seed: int = 0,
                 clock: Callable[[], float] = time.monotonic):
        self.name = name
        self.window_s = float(window_s) if window_s else None
        self._clock = clock
        self._res = Reservoir(cap=cap, seed=seed)
        # (ts, value) pairs; guarded-by: _lock
        self._win: deque = deque(maxlen=max(1, int(cap)))
        self._lock = threading.Lock()

    def observe(self, value: float, n: int = 1) -> None:
        v = float(value)
        for _ in range(max(1, int(n))):
            self._res.add(v)
        if self.window_s is not None:
            with self._lock:
                t = self._clock()
                for _ in range(max(1, int(n))):
                    self._win.append((t, v))
                self._prune(t)

    def _prune(self, now: float) -> None:  # holds-lock: _lock
        horizon = now - self.window_s
        while self._win and self._win[0][0] < horizon:
            self._win.popleft()

    @property
    def count(self) -> int:
        return self._res.count

    @property
    def mean(self) -> float:
        return self._res.mean

    @property
    def sum(self) -> float:
        return self._res.sum

    def percentile(self, q: float) -> float:
        return self._res.percentile(q)

    def _window_values(self) -> List[float]:
        if self.window_s is None:
            return []
        with self._lock:
            self._prune(self._clock())
            return [v for _, v in self._win]

    def window_count(self) -> int:
        return len(self._window_values())

    def window_rate(self) -> float:
        if self.window_s is None:
            return 0.0
        return self.window_count() / self.window_s

    def window_percentile(self, q: float) -> float:
        vals = self._window_values()
        if not vals:
            return 0.0
        vals.sort()
        return vals[nearest_rank(q, len(vals))]


class MetricsRegistry:
    """Named instruments + the lifecycle event channel. Instruments are
    get-or-create by name (`counter`/`gauge`/`histogram`), so callers never
    coordinate construction; a name is one kind forever (a second call with
    a different kind raises — silent kind drift is how dashboards lie)."""

    def __init__(self, events: int = 256,
                 clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._instruments: Dict[str, Any] = {}   # guarded-by: _lock
        # guarded-by: _lock
        self._events: deque = deque(maxlen=max(1, int(events)))
        self._lock = threading.Lock()

    def _get(self, name: str, kind: str, factory):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = factory()
                self._instruments[name] = inst
            elif inst.kind != kind:
                raise TypeError(
                    f"instrument {name!r} is a {inst.kind}, not a {kind}")
            return inst

    def counter(self, name: str, window_s: Optional[float] = None) -> Counter:
        return self._get(name, "counter",
                         lambda: Counter(name, window_s=window_s,
                                         clock=self._clock))

    def gauge(self, name: str) -> Gauge:
        return self._get(name, "gauge", lambda: Gauge(name))

    def histogram(self, name: str,
                  window_s: Optional[float] = DEFAULT_WINDOW_S,
                  cap: int = 4096) -> Histogram:
        return self._get(name, "histogram",
                         lambda: Histogram(name, window_s=window_s, cap=cap,
                                           clock=self._clock))

    # -- event channel -----------------------------------------------------
    def event(self, name: str, attrs: Optional[Dict[str, Any]] = None,
              trace_id: Optional[str] = None) -> Dict[str, Any]:
        """Record a lifecycle transition (view hot-swap, shard quarantine,
        drift rebuild, degraded/restored, checkpoint rollback). `trace_id`
        correlates the event with the request trace that observed it."""
        rec = {"ts": round(time.time(), 3), "event": str(name),
               "attrs": dict(attrs or {}), "trace_id": trace_id}
        with self._lock:
            self._events.append(rec)
        return rec

    def events(self, name: Optional[str] = None) -> List[Dict[str, Any]]:
        with self._lock:
            evs = list(self._events)
        return evs if name is None else [e for e in evs
                                         if e["event"] == name]

    # -- exposition --------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """JSON-serializable view of every instrument + the event ring —
        the one structure tests, the metrics jsonl, `cli serve-metrics
        --json`, and the `:metrics` control line all read."""
        with self._lock:
            insts = dict(self._instruments)
        counters: Dict[str, Any] = {}
        gauges: Dict[str, float] = {}
        hists: Dict[str, Any] = {}
        for name, inst in sorted(insts.items()):
            if inst.kind == "counter":
                rec: Dict[str, Any] = {"value": inst.value}
                if inst.window_s is not None:
                    rec["rate_per_s"] = round(inst.rate(), 4)
                    rec["window_s"] = inst.window_s
                counters[name] = rec
            elif inst.kind == "gauge":
                gauges[name] = round(inst.value, 6)
            else:
                rec = {"count": inst.count,
                       "mean": round(inst.mean, 4),
                       "p50": round(inst.percentile(50), 4),
                       "p99": round(inst.percentile(99), 4)}
                if inst.window_s is not None:
                    rec["window"] = {
                        "window_s": inst.window_s,
                        "count": inst.window_count(),
                        "rate_per_s": round(inst.window_rate(), 4),
                        "p50": round(inst.window_percentile(50), 4),
                        "p99": round(inst.window_percentile(99), 4)}
                hists[name] = rec
        return {"counters": counters, "gauges": gauges,
                "histograms": hists, "events": self.events()}

    def prometheus_text(self) -> str:
        """Prometheus text exposition. Counters/gauges as plain samples,
        histograms in summary style (quantile labels + _count/_sum)."""
        with self._lock:
            insts = dict(self._instruments)
        lines: List[str] = []
        for name, inst in sorted(insts.items()):
            pname = _prom_name(name)
            if inst.kind == "counter":
                lines.append(f"# TYPE {pname} counter")
                lines.append(f"{pname} {inst.value}")
                if inst.window_s is not None:
                    lines.append(f"# TYPE {pname}_rate_per_s gauge")
                    lines.append(f"{pname}_rate_per_s {inst.rate():.6g}")
            elif inst.kind == "gauge":
                lines.append(f"# TYPE {pname} gauge")
                lines.append(f"{pname} {inst.value:.6g}")
            else:
                lines.append(f"# TYPE {pname} summary")
                for q in (50, 90, 99):
                    lines.append(f'{pname}{{quantile="{q / 100}"}} '
                                 f"{inst.percentile(q):.6g}")
                lines.append(f"{pname}_count {inst.count}")
                lines.append(f"{pname}_sum {inst.sum:.6g}")
        return "\n".join(lines) + ("\n" if lines else "")

    def to_json(self) -> str:
        return json.dumps(self.snapshot(), sort_keys=True)


def _prom_name(name: str) -> str:
    name = re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    return name if not name[:1].isdigit() else "_" + name


# -- the process-wide default registry --------------------------------------
# For layers with no natural owner to hand them a registry: fault counters
# (utils/faults.py mirrors every count here), the train/embed loop
# throughput instruments, checkpoint rollback events. SearchService builds
# its OWN registry so per-service counters never mix.

_DEFAULT = MetricsRegistry()
_DEFAULT_LOCK = threading.Lock()


def default_registry() -> MetricsRegistry:
    return _DEFAULT


def reset_default() -> MetricsRegistry:
    """Swap in a fresh default registry (test hygiene)."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        _DEFAULT = MetricsRegistry()
        return _DEFAULT
