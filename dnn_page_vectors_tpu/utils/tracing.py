"""Request-scoped tracing (docs/OBSERVABILITY.md).

`PipelineProfiler` answers "which stage binds in aggregate"; this module
answers "why did THIS query take 80 ms": every `search`/`search_many` call
gets a trace id and a span tree following the request through the
micro-batcher (queue_wait), tokenize/encode (with cache-hit annotation),
the ANN probe -> ADC -> exact re-rank (lists scanned, bytes gathered, rows
reranked as span attributes), merge, and format.

Mechanics:

  * `Span` — a named timed node with attributes and children. Spans nest
    through a `contextvars.ContextVar`, so `tracer.span("tokenize")`
    attaches to whatever request is active on the CURRENT thread without
    threading a handle through every signature.
  * the **thread hop** — the micro-batcher coalesces requests from many
    caller threads onto one dispatcher thread, where the contextvar chain
    breaks. The hand-off is explicit: `submit()` captures the caller's
    span (`tracer.current()`); the dispatcher stamps the measured
    `queue_wait` onto it (`Span.child`), runs the coalesced dispatch under
    a detached span, and grafts the finished dispatch subtree into every
    request's tree (`Span.adopt`) before resolving its future. For the
    per-request retry path, `tracer.use(span)` re-activates a caller's
    span on the dispatcher thread directly.
  * the **slow-query log** — a bounded ring of finished traces whose
    duration crossed `obs.slow_ms` (0 captures everything, <0 disables),
    each stored as a JSON-ready dict. The answer to "why was that one
    request slow" survives the request.
  * **export** — `chrome_trace()` renders the recent-trace ring (or any
    trace subset) as Chrome/Perfetto `trace_event` JSON ("ph": "X"
    complete events, microsecond timestamps, span attributes in "args"),
    written by `cli trace`.

Disabled tracing (`obs.enabled=false`) costs one `None`-check per span:
every context manager yields the shared `NULL_SPAN`, whose mutators are
no-ops, so instrumented code never branches on whether tracing is on.
"""
from __future__ import annotations

import contextlib
import contextvars
import itertools
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

# perf_counter -> epoch alignment for export: spans time themselves on the
# monotonic clock, the trace viewer wants wall-clock microseconds
_EPOCH0 = time.time() - time.perf_counter()

_IDS = itertools.count(1)


def _new_id(prefix: str) -> str:
    return f"{prefix}-{os.getpid():x}-{next(_IDS):x}"


class Span:
    """One timed node of a request trace. Not thread-safe per se — a span
    is mutated by the thread it is active on; the batcher hand-off
    serializes mutation through the queue/future protocol."""

    __slots__ = ("name", "trace_id", "span_id", "t0", "dur_s", "attrs",
                 "children", "tid")

    def __init__(self, name: str, trace_id: str,
                 attrs: Optional[Dict[str, Any]] = None,
                 t0: Optional[float] = None):
        self.name = name
        self.trace_id = trace_id
        self.span_id = _new_id("s")
        self.t0 = time.perf_counter() if t0 is None else float(t0)
        self.dur_s: Optional[float] = None
        self.attrs: Dict[str, Any] = dict(attrs or {})
        self.children: List["Span"] = []
        self.tid = threading.get_ident()

    def set_attrs(self, **attrs: Any) -> "Span":
        self.attrs.update(attrs)
        return self

    def child(self, name: str, dur_s: float, t0: Optional[float] = None,
              **attrs: Any) -> "Span":
        """Append an already-FINISHED child (e.g. the batcher's measured
        queue_wait, whose start predates the dispatcher seeing it)."""
        sp = Span(name, self.trace_id, attrs=attrs,
                  t0=self.t0 if t0 is None else t0)
        sp.dur_s = float(dur_s)
        self.children.append(sp)
        return sp

    def adopt(self, span: "Span") -> None:
        """Graft a finished span subtree (the batcher's shared dispatch)
        into this tree. The subtree may be shared by every request of a
        coalesced batch — spans are records, not owners."""
        self.children.append(span)

    def end(self) -> "Span":
        if self.dur_s is None:
            self.dur_s = time.perf_counter() - self.t0
        return self

    @property
    def dur_ms(self) -> float:
        return (self.dur_s or 0.0) * 1000.0

    def names(self) -> List[str]:
        """Every span name in this subtree (test/debug helper)."""
        out = [self.name]
        for c in self.children:
            out.extend(c.names())
        return out

    def find(self, name: str) -> Optional["Span"]:
        if self.name == name:
            return self
        for c in self.children:
            hit = c.find(name)
            if hit is not None:
                return hit
        return None

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "start_ms": round((_EPOCH0 + self.t0) * 1000.0, 3),
            "dur_ms": round(self.dur_ms, 4),
            "attrs": dict(self.attrs),
            "children": [c.to_dict() for c in self.children],
        }


class _NullSpan:
    """Shared no-op span: instrumented code calls set_attrs/child/adopt
    unconditionally whether tracing is on or not."""

    __slots__ = ()
    name = ""
    trace_id = None
    dur_ms = 0.0

    def set_attrs(self, **attrs: Any) -> "_NullSpan":
        return self

    def child(self, *a: Any, **kw: Any) -> "_NullSpan":
        return self

    def adopt(self, span: Any) -> None:
        pass

    def end(self) -> "_NullSpan":
        return self


NULL_SPAN = _NullSpan()


class Tracer:
    """Per-service trace context + the bounded trace/slow-query buffers."""

    def __init__(self, enabled: bool = True, slow_ms: Optional[float] = None,
                 slow_log_size: int = 64, buffer: int = 64):
        self.enabled = bool(enabled)
        # slow_ms: None or negative disables the slow log; 0 captures every
        # request (the "log everything" debugging mode)
        self.slow_ms = (None if slow_ms is None or slow_ms < 0
                        else float(slow_ms))
        self._var: contextvars.ContextVar[Optional[Span]] = \
            contextvars.ContextVar("dnn_pv_span", default=None)
        self._traces: deque = deque(maxlen=max(1, int(buffer)))
        self._slow: deque = deque(maxlen=max(1, int(slow_log_size)))
        self._lock = threading.Lock()

    # -- context -----------------------------------------------------------
    def current(self) -> Optional[Span]:
        """The span active on THIS thread (None outside any trace)."""
        return self._var.get()

    @contextlib.contextmanager
    def trace(self, name: str, record: bool = True, **attrs: Any):
        """Open a new ROOT span (fresh trace id) and activate it. On exit
        the finished trace lands in the recent-trace ring and — when its
        duration crosses `slow_ms` — the slow-query log. `record=False`
        keeps detached internal roots (the batcher's shared dispatch,
        grafted into request trees) out of both buffers."""
        if not self.enabled:
            yield NULL_SPAN
            return
        span = Span(name, trace_id=_new_id("t"), attrs=attrs)
        token = self._var.set(span)
        try:
            yield span
        finally:
            span.end()
            self._var.reset(token)
            if record:
                self._record(span)

    @contextlib.contextmanager
    def span(self, name: str, **attrs: Any):
        """Open a child of the current span. Outside any trace (or with
        tracing disabled) this is a no-op yielding NULL_SPAN — stage
        instrumentation costs nothing on untraced paths."""
        parent = self._var.get() if self.enabled else None
        if parent is None:
            yield NULL_SPAN
            return
        sp = Span(name, parent.trace_id, attrs=attrs)
        token = self._var.set(sp)
        try:
            yield sp
        finally:
            sp.end()
            self._var.reset(token)
            parent.adopt(sp)

    @contextlib.contextmanager
    def use(self, span: Optional[Span]):
        """Explicit cross-thread hand-off: re-activate a caller's span on
        THIS thread (the micro-batcher's per-request retry path)."""
        if not self.enabled or span is None or span is NULL_SPAN:
            yield
            return
        token = self._var.set(span)
        try:
            yield
        finally:
            self._var.reset(token)

    @contextlib.contextmanager
    def root_or_span(self, name: str, **attrs: Any):
        """A root trace when no span is active, a child span otherwise —
        public entry points (`search_many`) are roots for direct callers
        and sub-spans when a batcher dispatch is already tracing."""
        cm = (self.span(name, **attrs) if self.current() is not None
              else self.trace(name, **attrs))
        with cm as sp:
            yield sp

    def _record(self, root: Span) -> None:
        with self._lock:
            self._traces.append(root)
            if self.slow_ms is not None and root.dur_ms >= self.slow_ms:
                self._slow.append(root.to_dict())

    # -- buffers -----------------------------------------------------------
    def traces(self) -> List[Dict[str, Any]]:
        """Recent finished traces, oldest first (JSON-ready dicts)."""
        with self._lock:
            roots = list(self._traces)
        return [r.to_dict() for r in roots]

    def last_trace(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self._traces[-1].to_dict() if self._traces else None

    def slow_queries(self) -> List[Dict[str, Any]]:
        """Finished traces that crossed `slow_ms`, oldest first."""
        with self._lock:
            return list(self._slow)

    def clear(self) -> None:
        with self._lock:
            self._traces.clear()
            self._slow.clear()

    # -- export ------------------------------------------------------------
    def chrome_trace(self, traces: Optional[List[Dict[str, Any]]] = None
                     ) -> Dict[str, Any]:
        """Render traces (default: the recent ring) as Chrome/Perfetto
        `trace_event` JSON — load in chrome://tracing or ui.perfetto.dev.
        Spans shared across coalesced requests are emitted once."""
        events: List[Dict[str, Any]] = []
        seen: set = set()
        pid = os.getpid()

        def _emit(d: Dict[str, Any], tid_fallback: int) -> None:
            if d["span_id"] in seen:
                return
            seen.add(d["span_id"])
            events.append({
                "ph": "X",
                "name": d["name"],
                "cat": "request",
                "pid": pid,
                "tid": tid_fallback,
                "ts": round(d["start_ms"] * 1000.0, 1),    # microseconds
                "dur": round(max(d["dur_ms"], 0.0) * 1000.0, 1),
                "args": {**d["attrs"], "trace_id": d["trace_id"],
                         "span_id": d["span_id"]},
            })
            for c in d["children"]:
                _emit(c, tid_fallback)

        for i, t in enumerate(self.traces() if traces is None else traces):
            _emit(t, i)
        return {"traceEvents": events, "displayTimeUnit": "ms"}
