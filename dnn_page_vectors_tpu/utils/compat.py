"""JAX version compatibility shims.

The codebase targets the current jax API (`jax.shard_map` with
`check_vma=`, `lax.pcast`); CPU dev boxes and CI images may carry an older
jax where shard_map still lives in `jax.experimental.shard_map` (with the
`check_rep=` spelling) and `lax.pcast` does not exist yet. Everything that
needs these goes through this module so the version split lives in exactly
one place.
"""
from __future__ import annotations

import jax
from jax import lax

try:                                     # jax >= 0.6: public API
    from jax import shard_map as _shard_map
    _CHECK_KW = "check_vma"
except ImportError:                      # older jax: experimental home
    from jax.experimental.shard_map import shard_map as _shard_map
    _CHECK_KW = "check_rep"


def shard_map_unchecked(fn, mesh, in_specs, out_specs):
    """shard_map with the static replication/varying-axis checker off —
    the documented escape hatch for collective-then-replicated-merge bodies
    the checker can't infer. Spelled `check_vma=False` on current jax,
    `check_rep=False` before the rename."""
    return _shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **{_CHECK_KW: False})


def axis_size(axis_name):
    """STATIC size of a mapped axis from inside shard_map. `lax.axis_size`
    on current jax; on older jax, `lax.psum(1, axis)` — special-cased for
    non-tracer args — returns the same concrete Python int."""
    fn = getattr(lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    return lax.psum(1, axis_name)


def pcast_varying(x, axes):
    """lax.pcast(x, axes, to="varying") where available. Older jax has no
    varying-axis types at all — there a constant carry is already legal
    under check_rep=False, so the identity is the correct no-op."""
    pcast = getattr(lax, "pcast", None)
    if pcast is None:
        return x
    return pcast(x, axes, to="varying")
