"""Metrics/observability (SURVEY.md §3 #26, §5.5).

Emits the two baseline metrics verbatim — pages/sec/chip and Recall@10
(BASELINE.json:2) — as jsonl under workdir, mirrored to stdout.
"""
from __future__ import annotations

import json
import os
import sys
import time
from typing import Any, Dict, Optional


class MetricsLogger:
    def __init__(self, workdir: Optional[str] = None, name: str = "metrics",
                 echo: bool = True):
        self.echo = echo
        self._f = None
        if workdir:
            os.makedirs(workdir, exist_ok=True)
            self._f = open(os.path.join(workdir, f"{name}.jsonl"), "a")

    def write(self, metrics: Dict[str, Any]) -> None:
        rec = {"ts": time.time(), **{
            k: (float(v) if hasattr(v, "item") else v)
            for k, v in metrics.items()}}
        line = json.dumps(rec, sort_keys=True)
        if self._f:
            self._f.write(line + "\n")
            self._f.flush()
        if self.echo:
            print(line, file=sys.stderr)

    def close(self) -> None:
        if self._f:
            self._f.close()
