"""Metrics/observability (SURVEY.md §3 #26, §5.5; docs/OBSERVABILITY.md).

Emits the two baseline metrics verbatim — pages/sec/chip and Recall@10
(BASELINE.json:2) — as jsonl under workdir, mirrored to stdout.

Re-based on the metrics registry (utils/telemetry.py): when a `registry`
is attached, every numeric scalar written also lands as a registry gauge
under the same key, so the jsonl line, the Prometheus exposition, and the
JSON snapshot all read the SAME number from the same write — the jsonl
output shape ({"ts": ..., sorted keys}) is unchanged.

Lifecycle: a context manager (`with MetricsLogger(...) as log:`), and
`write()` after `close()` is tolerated — the file handle is gone, so the
line goes to stderr/registry only instead of raising (serve.py flushes
final metrics through close(); a late writer must not take the service
down over a log line).
"""
from __future__ import annotations

import json
import os
import sys
import time
from typing import Any, Dict, Optional


class MetricsLogger:
    def __init__(self, workdir: Optional[str] = None, name: str = "metrics",
                 echo: bool = True, registry=None):
        self.echo = echo
        self.registry = registry
        self._f = None
        self._closed = False
        if workdir:
            os.makedirs(workdir, exist_ok=True)
            self._f = open(os.path.join(workdir, f"{name}.jsonl"), "a")

    def write(self, metrics: Dict[str, Any]) -> None:
        rec = {"ts": time.time(), **{
            k: (float(v) if hasattr(v, "item") else v)
            for k, v in metrics.items()}}
        if self.registry is not None:
            for k, v in rec.items():
                if k != "ts" and isinstance(v, (int, float)) \
                        and not isinstance(v, bool):
                    self.registry.gauge(k).set(float(v))
        line = json.dumps(rec, sort_keys=True)
        if self._f is not None and not self._closed:
            self._f.write(line + "\n")
            self._f.flush()
        if self.echo:
            print(line, file=sys.stderr)

    @property
    def closed(self) -> bool:
        return self._closed

    def close(self) -> None:
        if self._f is not None and not self._closed:
            self._f.close()
        self._closed = True

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
