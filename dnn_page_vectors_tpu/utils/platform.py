"""Platform selection guard.

Some sandboxes preload jax from a sitecustomize that force-registers an
accelerator plugin, which overrides the JAX_PLATFORMS environment variable a
user (or the test/dryrun driver) set when launching the process. Re-asserting
the env var through jax.config restores the documented env semantics; without
this, a CPU-requested run can hang trying to initialise a busy/absent
accelerator backend.
"""
from __future__ import annotations

import os


def honor_jax_platforms_env() -> None:
    plat = os.environ.get("JAX_PLATFORMS")
    if not plat:
        return
    import jax

    try:
        if jax.config.jax_platforms != plat:
            jax.config.update("jax_platforms", plat)
    except Exception:
        pass


def hard_sync(tree) -> None:
    """Barrier that provably waits for device execution to finish.

    On the tunneled 'axon' TPU backend, ``jax.block_until_ready`` returns
    after dispatch, not execution — measured >2000 TFLOP/s "throughput" on a
    197 TFLOP/s chip when timing with it (round-3 diagnosis of the impossible
    MFU>1 in BENCH_r02-era timings). Pulling one element of the result back
    to the host cannot complete until the producing computation has, so every
    timing path must use this instead of block_until_ready.
    """
    import jax
    import numpy as np

    for leaf in jax.tree_util.tree_leaves(tree):
        np.asarray(jax.device_get(leaf.ravel()[0:1]))
