"""Platform selection guard.

Some sandboxes preload jax from a sitecustomize that force-registers an
accelerator plugin, which overrides the JAX_PLATFORMS environment variable a
user (or the test/dryrun driver) set when launching the process. Re-asserting
the env var through jax.config restores the documented env semantics; without
this, a CPU-requested run can hang trying to initialise a busy/absent
accelerator backend.
"""
from __future__ import annotations

import os


def honor_jax_platforms_env() -> None:
    plat = os.environ.get("JAX_PLATFORMS")
    if not plat:
        return
    import jax

    try:
        if jax.config.jax_platforms != plat:
            jax.config.update("jax_platforms", plat)
    except Exception:
        pass
