"""Tracing/profiling hooks (SURVEY.md §5.1): jax.profiler traces around the
train/embed hot loops, TensorBoard-readable, behind a --profile CLI flag —
plus PipelineProfiler, the per-STAGE wall-time accounting the traces can't
give cheaply: where an end-to-end pages/sec number hides which stage binds
(host production vs H2D vs compute vs D2H vs store writeback), the stage
breakdown says it in one metrics line.
"""
from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Dict


class PipelineProfiler:
    """Cumulative per-stage wall time for the host<->device pipelines.

    Stages are free-form names; the bulk-embed and train loops use:
      produce_wait  consumer blocked waiting for a host batch (prefetch gap)
      read          corpus record reads inside tokenizer workers
      tokenize      encode_batch inside tokenizer workers
      h2d           device_put / make_array_from_process_local_data
      compute       jitted dispatch (async under JAX — small when pipelined)
      d2h           materializing device results to numpy
      write         shard concat + write_shard on the writer thread
      write_wait    device loop blocked on the bounded writeback budget

    The serving path (infer/serve.py) uses:
      queue_wait    request sat in the micro-batcher queue before dispatch
      tokenize      encode_batch over the coalesced cache-miss queries
      encode        compiled query-tower dispatch (+ host materialize)
      topk          per-shard sharded_topk dispatches (or the streaming
                    sweep on a non-resident store)
      merge         device cross-shard merge + the one packed transfer
      format        page-id mapping + snippet assembly

    Seconds are CUMULATIVE ACROSS THREADS — a pool of N tokenizer workers
    adds each worker's time, so `read`/`tokenize` can exceed wall clock.
    That is the point: the ratios between stages (and the consumer-side
    `produce_wait`) say which stage binds, not how long the job took.
    Thread-safe: producers, tokenizer workers, and the writer thread all
    add into one instance.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._sec: Dict[str, float] = {}
        self._n: Dict[str, int] = {}
        self._bytes: Dict[str, int] = {}

    def add(self, name: str, seconds: float) -> None:
        with self._lock:
            self._sec[name] = self._sec.get(name, 0.0) + seconds
            self._n[name] = self._n.get(name, 0) + 1

    def add_bytes(self, name: str, nbytes: int) -> None:
        """Byte volume moved by a stage (h2d/d2h transfers): with the
        stage's cumulative seconds this makes the achieved MB/s of a
        transfer stage computable from one metrics line —
        `embed_d2h_mbytes_per_sec` in the bulk-embed log and bench."""
        with self._lock:
            self._bytes[name] = self._bytes.get(name, 0) + int(nbytes)

    def stage_bytes(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._bytes)

    @contextlib.contextmanager
    def stage(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(name, time.perf_counter() - t0)

    def reset(self) -> None:
        with self._lock:
            self._sec.clear()
            self._n.clear()
            self._bytes.clear()

    def stages(self) -> Dict[str, float]:
        """{stage: cumulative seconds} snapshot."""
        with self._lock:
            return dict(self._sec)

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._n)

    def summary(self, prefix: str = "stage_") -> Dict[str, float]:
        """Flat metrics-ready dict: {f'{prefix}{stage}_s': seconds,
        f'{prefix}{stage}_n': calls}. Stable key shape so dashboards/tests
        can pin on e.g. stage_produce_wait_s — and the per-stage call count
        next to the cumulative seconds makes mean-per-call computable from
        ONE metrics line."""
        with self._lock:
            out: Dict[str, float] = {}
            for k in sorted(self._sec):
                out[f"{prefix}{k}_s"] = round(self._sec[k], 4)
                out[f"{prefix}{k}_n"] = self._n.get(k, 0)
                if k in self._bytes:
                    out[f"{prefix}{k}_bytes"] = self._bytes[k]
            return out


class LatencyStats:
    """Per-request latency samples -> distribution numbers (count, mean,
    p50/p99). PipelineProfiler answers "which stage binds" with cumulative
    seconds; this answers the serving question it can't — what one caller
    experiences under load, where the tail (p99) matters more than the
    mean. Thread-safe: concurrent search() callers add into one instance.

    Memory is BOUNDED: samples land in a seeded reservoir
    (utils/telemetry.Reservoir, Algorithm R) of `cap` slots instead of an
    ever-growing list, so a long-lived service neither leaks nor re-sorts
    an unbounded buffer per percentile call. Below `cap` samples the
    reservoir holds every observation, so count/mean AND the nearest-rank
    percentiles are exactly what the unbounded version reported (pinned by
    tests/test_profiling.py); past `cap`, count and mean stay exact and
    percentiles are estimated from a uniform sample.
    """

    def __init__(self, cap: int = 4096, seed: int = 0) -> None:
        from dnn_page_vectors_tpu.utils.telemetry import Reservoir
        self._res = Reservoir(cap=cap, seed=seed)

    def add(self, seconds: float) -> None:
        self._res.add(float(seconds))

    @contextlib.contextmanager
    def timed(self):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(time.perf_counter() - t0)

    def __len__(self) -> int:
        return self._res.count

    def percentile_ms(self, q: float) -> float:
        """Nearest-rank percentile (q in [0, 100]) in milliseconds; 0.0
        with no samples. p50 of an even count is the lower middle sample —
        a latency the service actually delivered, not an interpolation."""
        return self._res.percentile(q) * 1000.0

    def summary(self, prefix: str = "lat_") -> Dict[str, float]:
        return {f"{prefix}count": self._res.count,
                f"{prefix}mean_ms": round(self._res.mean * 1000.0, 3),
                f"{prefix}p50_ms": round(self.percentile_ms(50), 3),
                f"{prefix}p99_ms": round(self.percentile_ms(99), 3)}


@contextlib.contextmanager
def maybe_profile(enabled: bool, workdir: str):
    if not enabled:
        yield
        return
    import jax
    trace_dir = os.path.join(workdir, "trace")
    os.makedirs(trace_dir, exist_ok=True)
    with jax.profiler.trace(trace_dir):
        yield
