"""Tracing/profiling hooks (SURVEY.md §5.1): jax.profiler traces around the
train/embed hot loops, TensorBoard-readable, behind a --profile CLI flag."""
from __future__ import annotations

import contextlib
import os


@contextlib.contextmanager
def maybe_profile(enabled: bool, workdir: str):
    if not enabled:
        yield
        return
    import jax
    trace_dir = os.path.join(workdir, "trace")
    os.makedirs(trace_dir, exist_ok=True)
    with jax.profiler.trace(trace_dir):
        yield
