"""Analytic FLOP counts + per-chip peak-FLOPs table, for MFU reporting.

The bench (bench.py) reports model FLOPs utilisation next to pages/sec/chip
so a throughput number is interpretable — without an analytic FLOPs/step
nobody can tell whether a measured rate is 5% or 50% of the hardware peak
(VERDICT round 1, weak #7). Counts follow the standard convention: one
multiply-accumulate = 2 FLOPs; embedding gathers, softmax, layernorm and
other vector ops are excluded (they are bandwidth-, not FLOP-, bound and
conventionally left out of MFU math).
"""
from __future__ import annotations

from typing import Optional

from dnn_page_vectors_tpu.config import Config, ModelConfig


def encoder_flops_per_example(m: ModelConfig, seq_len: int) -> float:
    """Forward-pass matmul FLOPs for ONE sequence through one tower."""
    if m.encoder in ("bert", "t5"):
        d, ff, L = m.model_dim, m.mlp_dim, seq_len
        # per token per layer: QKV+output projections (8 d^2), attention
        # score+apply (4 L d), MLP (bert: two matmuls = 4 d ff; t5 gated
        # GELU: three matmuls = 6 d ff)
        mlp = 6 * d * ff if m.encoder == "t5" else 4 * d * ff
        per_tok_layer = 8 * d * d + 4 * L * d + mlp
        proj = 2 * d * m.out_dim          # pooled vector -> out_dim
        return float(L * m.num_layers * per_tok_layer + proj)
    if m.encoder == "cdssm":
        E, C = m.embed_dim, m.conv_channels
        conv = sum(2 * w * E * C for w in m.conv_widths) * seq_len
        return float(conv + 2 * C * m.out_dim)
    if m.encoder == "kim_cnn":
        E, C = m.embed_dim, m.conv_channels
        conv = sum(2 * w * E * C for w in m.conv_widths) * seq_len
        return float(conv + 2 * len(m.conv_widths) * C * m.out_dim)
    if m.encoder == "lstm":
        # per direction per token: input proj 2*E_in*4H + recurrent 2*H*4H;
        # layer 1 reads the embedding (E), deeper layers read [B, L, 2H]
        H = m.model_dim
        per_dir = 0.0
        e_in = m.embed_dim
        for _ in range(m.num_layers):
            per_dir += 2 * e_in * 4 * H + 2 * H * 4 * H
            e_in = 2 * H
        return float(seq_len * 2 * per_dir + 2 * (2 * H) * m.out_dim)
    raise ValueError(f"no FLOP model for encoder {m.encoder!r}")


def train_flops_per_pair(cfg: Config, batch_size: int,
                         pack: Optional[int] = None) -> float:
    """Matmul FLOPs per (query, page) pair for one optimizer step.

    fwd for both towers (+ hard-negative pages), in-batch logits matmul,
    then the usual 3x multiplier for fwd+bwd (bwd of a matmul costs 2 fwds).

    `pack` (default cfg.train.pack_pages) — sequence packing: the page
    tower runs one [data.page_len] ROW carrying `pack` pages, so the
    per-page page-tower cost is the row cost / pack. This is the row the
    device actually computes (segment masking zeroes scores, it does not
    skip tiles), so MFU stays an honest achieved-FLOPs ratio; the
    packing WIN shows up as pages/sec — and in useful-FLOPs terms via
    bench.py's long_pack phase (docs/MFU.md "packing accounting")."""
    m, d = cfg.model, cfg.data
    H = cfg.train.hard_negatives
    pack = max(1, cfg.train.pack_pages if pack is None else pack)
    # mined negatives ride UNPACKED [B*H, page_len] rows either way
    fwd = (encoder_flops_per_example(m, d.query_len)
           + encoder_flops_per_example(m, d.page_len) / pack
           + H * encoder_flops_per_example(m, d.page_len))
    # logits: q [B, D] @ pages [(1+H) B, D]^T, per pair:
    fwd += 2.0 * batch_size * (1 + H) * m.out_dim
    return 3.0 * fwd


def embed_flops_per_page(cfg: Config) -> float:
    """Matmul FLOPs to embed one page (forward only)."""
    return encoder_flops_per_example(cfg.model, cfg.data.page_len)


# ---------------------------------------------------------------------------
# Roofline accounting (round 11, docs/MFU.md "roofline methodology"):
# MFU against the bf16 matmul peak is the wrong lens for encoders that
# barely matmul — kim_cnn/lstm spend their step in the [vocab, E]
# embedding gather/scatter and short convolutions/recurrences, so 3% "MFU"
# reads as a bug when it is the workload. The meaningful utilization
# number is achieved rate vs the ANALYTIC ROOFLINE: the lower of the
# compute ceiling (peak_flops / flops_per_pair) and the memory ceiling
# (peak_hbm_bw / bytes_per_pair). The bench reports <phase>_roofline_util
# plus which wall binds next to every MFU column.
# ---------------------------------------------------------------------------

def encoder_param_count(m: ModelConfig, vocab_size: int) -> float:
    """Approximate parameter count of ONE tower (embedding included)."""
    if m.encoder in ("bert", "t5"):
        d, ff = m.model_dim, m.mlp_dim
        mlp = 3 * d * ff if m.encoder == "t5" else 2 * d * ff
        per_layer = 4 * d * d + mlp
        return float(vocab_size * d + m.num_layers * per_layer
                     + d * m.out_dim)
    if m.encoder in ("cdssm", "kim_cnn"):
        E, C = m.embed_dim, m.conv_channels
        conv = sum(w * E * C for w in m.conv_widths)
        return float(vocab_size * E + conv
                     + len(m.conv_widths) * C * m.out_dim)
    if m.encoder == "lstm":
        H = m.model_dim
        per_dir, e_in = 0.0, m.embed_dim
        for _ in range(m.num_layers):
            per_dir += e_in * 4 * H + H * 4 * H
            e_in = 2 * H
        return float(vocab_size * m.embed_dim + 2 * per_dir
                     + 2 * H * m.out_dim)
    raise ValueError(f"no param model for encoder {m.encoder!r}")


def _act_bytes_per_example(m: ModelConfig, seq_len: int) -> float:
    """Rough activation HBM traffic per sequence, fwd+bwd (2-byte compute
    dtype; passes counted from the fused-op structure, not per-op)."""
    if m.encoder in ("bert", "t5"):
        d, ff = m.model_dim, m.mlp_dim
        # per layer: ~10 passes over [L, d] (attn in/out, residuals, LN,
        # fwd+bwd) + ~6 over the [L, ff] MLP hidden (fwd gelu + bwd)
        per_tok = m.num_layers * (10 * d + 6 * ff) + 4 * d
        return float(seq_len * per_tok * 2)
    if m.encoder in ("cdssm", "kim_cnn"):
        E, C = m.embed_dim, m.conv_channels
        per_tok = 3 * E + 4 * len(m.conv_widths) * C
        return float(seq_len * per_tok * 2)
    if m.encoder == "lstm":
        H = m.model_dim
        # gate math runs f32 (4 bytes); x_proj [L, 4H] both directions
        per_tok = m.embed_dim * 2 + 2 * (4 * H + 2 * H) * 4
        return float(seq_len * per_tok * m.num_layers)
    raise ValueError(f"no activation model for encoder {m.encoder!r}")


def train_bytes_per_pair(cfg: Config, batch_size: int) -> float:
    """Analytic HBM bytes per (query, page) pair for one optimizer step:
    embedding-table gather (fwd) + dense-grad scatter/update (bwd),
    activation traffic for both towers, and the batch-amortized
    parameter + adamw-moment traffic. Deliberately coarse (a roofline
    denominator, not a simulator) — assumptions in docs/MFU.md."""
    m, d = cfg.model, cfg.data
    H = cfg.train.hard_negatives
    vocab = (d.trigram_buckets if d.tokenizer == "trigram" else d.vocab_size)
    embed_width = m.model_dim if m.encoder in ("bert", "t5") else m.embed_dim
    tokens = d.query_len + (1 + H) * d.page_len
    # gather fwd (2B compute dtype) + scatter-add bwd (read+write f32)
    embed_traffic = tokens * embed_width * (2 + 2 * 4)
    acts = (_act_bytes_per_example(m, d.query_len)
            + (1 + H) * _act_bytes_per_example(m, d.page_len))
    # params: read fwd + read bwd + f32 grad write + adamw update
    # (p, m, v read+write) ≈ 10 f32-equivalent accesses, amortized over
    # the batch; two towers unless shared
    towers = 1 if m.shared_towers else 2
    params = towers * encoder_param_count(m, vocab)
    opt = params * 4 * 10 / max(1, batch_size)
    return float(embed_traffic + acts + opt)


# Per-chip peak HBM bandwidth (bytes/s) by device_kind substring.
# (Public figures: v4 1228, v5e 819, v5p 2765, v6e/Trillium 1640 GB/s;
# v2/v3 per-core devices: 350 / 450 GB/s.)
_PEAK_HBM = [
    ("v6", 1640e9),
    ("v5 lite", 819e9),
    ("v5e", 819e9),
    ("v5litepod", 819e9),
    ("v5p", 2765e9),
    ("v5", 2765e9),
    ("v4", 1228e9),
    ("v3", 450e9),
    ("v2", 350e9),
]


def device_peak_hbm_bps(device) -> Optional[float]:
    """Per-device peak HBM bandwidth in bytes/s, or None when unknown."""
    kind = getattr(device, "device_kind", "").lower()
    if "tpu" not in kind and getattr(device, "platform", "") != "tpu":
        return None
    for sub, bw in _PEAK_HBM:
        if sub in kind:
            return bw
    return None


def roofline(flops_per_pair: float, bytes_per_pair: float,
             peak_flops: Optional[float], peak_bw: Optional[float]):
    """(ceiling pairs/sec, binding wall) — the lower of the compute and
    memory ceilings; None when the device peaks are unknown (CPU)."""
    if not peak_flops or not peak_bw:
        return None, None
    compute = peak_flops / max(flops_per_pair, 1.0)
    memory = peak_bw / max(bytes_per_pair, 1.0)
    return (min(compute, memory),
            "compute" if compute <= memory else "bandwidth")


# Per-chip peak dense bf16 FLOP/s by `jax.Device.device_kind` substring.
# (Public figures: v4 275, v5e 197, v5p 459, v6e/Trillium 918 TFLOP/s.
# v2/v3 report per-core devices: 23 / 61.5 TFLOP/s per device.)
_PEAK_BF16 = [
    ("v6", 918e12),
    ("v5 lite", 197e12),
    ("v5e", 197e12),
    ("v5litepod", 197e12),
    ("v5p", 459e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 61.5e12),
    ("v2", 23e12),
]


def device_peak_flops(device) -> Optional[float]:
    """Per-device peak bf16 FLOP/s, or None when unknown (e.g. CPU)."""
    kind = getattr(device, "device_kind", "").lower()
    if "tpu" not in kind and getattr(device, "platform", "") != "tpu":
        return None
    for sub, peak in _PEAK_BF16:
        if sub in kind:
            return peak
    return None
