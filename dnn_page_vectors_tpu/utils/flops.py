"""Analytic FLOP counts + per-chip peak-FLOPs table, for MFU reporting.

The bench (bench.py) reports model FLOPs utilisation next to pages/sec/chip
so a throughput number is interpretable — without an analytic FLOPs/step
nobody can tell whether a measured rate is 5% or 50% of the hardware peak
(VERDICT round 1, weak #7). Counts follow the standard convention: one
multiply-accumulate = 2 FLOPs; embedding gathers, softmax, layernorm and
other vector ops are excluded (they are bandwidth-, not FLOP-, bound and
conventionally left out of MFU math).
"""
from __future__ import annotations

from typing import Optional

from dnn_page_vectors_tpu.config import Config, ModelConfig


def encoder_flops_per_example(m: ModelConfig, seq_len: int) -> float:
    """Forward-pass matmul FLOPs for ONE sequence through one tower."""
    if m.encoder in ("bert", "t5"):
        d, ff, L = m.model_dim, m.mlp_dim, seq_len
        # per token per layer: QKV+output projections (8 d^2), attention
        # score+apply (4 L d), MLP (bert: two matmuls = 4 d ff; t5 gated
        # GELU: three matmuls = 6 d ff)
        mlp = 6 * d * ff if m.encoder == "t5" else 4 * d * ff
        per_tok_layer = 8 * d * d + 4 * L * d + mlp
        proj = 2 * d * m.out_dim          # pooled vector -> out_dim
        return float(L * m.num_layers * per_tok_layer + proj)
    if m.encoder == "cdssm":
        E, C = m.embed_dim, m.conv_channels
        conv = sum(2 * w * E * C for w in m.conv_widths) * seq_len
        return float(conv + 2 * C * m.out_dim)
    if m.encoder == "kim_cnn":
        E, C = m.embed_dim, m.conv_channels
        conv = sum(2 * w * E * C for w in m.conv_widths) * seq_len
        return float(conv + 2 * len(m.conv_widths) * C * m.out_dim)
    if m.encoder == "lstm":
        # per direction per token: input proj 2*E_in*4H + recurrent 2*H*4H;
        # layer 1 reads the embedding (E), deeper layers read [B, L, 2H]
        H = m.model_dim
        per_dir = 0.0
        e_in = m.embed_dim
        for _ in range(m.num_layers):
            per_dir += 2 * e_in * 4 * H + 2 * H * 4 * H
            e_in = 2 * H
        return float(seq_len * 2 * per_dir + 2 * (2 * H) * m.out_dim)
    raise ValueError(f"no FLOP model for encoder {m.encoder!r}")


def train_flops_per_pair(cfg: Config, batch_size: int) -> float:
    """Matmul FLOPs per (query, page) pair for one optimizer step.

    fwd for both towers (+ hard-negative pages), in-batch logits matmul,
    then the usual 3x multiplier for fwd+bwd (bwd of a matmul costs 2 fwds).
    """
    m, d = cfg.model, cfg.data
    H = cfg.train.hard_negatives
    fwd = (encoder_flops_per_example(m, d.query_len)
           + (1 + H) * encoder_flops_per_example(m, d.page_len))
    # logits: q [B, D] @ pages [(1+H) B, D]^T, per pair:
    fwd += 2.0 * batch_size * (1 + H) * m.out_dim
    return 3.0 * fwd


def embed_flops_per_page(cfg: Config) -> float:
    """Matmul FLOPs to embed one page (forward only)."""
    return encoder_flops_per_example(cfg.model, cfg.data.page_len)


# Per-chip peak dense bf16 FLOP/s by `jax.Device.device_kind` substring.
# (Public figures: v4 275, v5e 197, v5p 459, v6e/Trillium 918 TFLOP/s.
# v2/v3 report per-core devices: 23 / 61.5 TFLOP/s per device.)
_PEAK_BF16 = [
    ("v6", 918e12),
    ("v5 lite", 197e12),
    ("v5e", 197e12),
    ("v5litepod", 197e12),
    ("v5p", 459e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 61.5e12),
    ("v2", 23e12),
]


def device_peak_flops(device) -> Optional[float]:
    """Per-device peak bf16 FLOP/s, or None when unknown (e.g. CPU)."""
    kind = getattr(device, "device_kind", "").lower()
    if "tpu" not in kind and getattr(device, "platform", "") != "tpu":
        return None
    for sub, peak in _PEAK_BF16:
        if sub in kind:
            return peak
    return None
