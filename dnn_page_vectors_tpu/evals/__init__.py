"""Eval layer: Recall@K query->page retrieval (SURVEY.md §2 layer 6)."""
from dnn_page_vectors_tpu.evals.recall import recall_at_k, evaluate_recall

__all__ = ["recall_at_k", "evaluate_recall"]
