"""Retrieval eval: Recall@10 query->page (SURVEY.md §3 #22; BASELINE.json:2).

Shares the top-k substrate with the ANN miner (call stack §4.3): the store
streams shard-by-shard through `ops.topk.topk_over_store`, each shard
row-sharded over the mesh 'data' axis, scored on the MXU, per-shard top-k
all-gathered over ICI, running merge on host — so eval memory stays
O(one store shard) no matter the corpus size (the 1B-page requirement,
BASELINE.md:16; VERDICT r1 #2).
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np

from dnn_page_vectors_tpu.infer.bulk_embed import BulkEmbedder
from dnn_page_vectors_tpu.infer.vector_store import VectorStore
from dnn_page_vectors_tpu.data.toy import ToyCorpus
from dnn_page_vectors_tpu.ops.topk import chunked_topk, topk_over_store


def recall_at_k(query_vecs: np.ndarray, page_ids: np.ndarray,
                page_vecs: np.ndarray, gold_ids: np.ndarray,
                k: int = 10, query_batch: int = 1024,
                chunk: int = 8192) -> float:
    """Fraction of queries whose gold page id is in the top-k, for
    in-memory page vectors (single device). The store-scale path is
    `recall_from_store`.

    query_vecs [Nq, D] and page_vecs [N, D] must be L2-normalized (the
    store's invariant); page_ids maps store rows -> page ids.
    """
    hits = 0
    nq = query_vecs.shape[0]
    pages = jnp.asarray(page_vecs, jnp.float32)
    for s in range(0, nq, query_batch):
        q = jnp.asarray(query_vecs[s: s + query_batch], jnp.float32)
        _, idx = chunked_topk(q, pages, k=k, chunk=chunk)
        idx = np.asarray(idx)
        # -1 padding (store smaller than k) must not wrap to the last row
        retrieved = np.where(idx >= 0, page_ids[np.clip(idx, 0, None)], -1)
        gold = gold_ids[s: s + query_batch, None]
        hits += int((retrieved == gold).any(axis=1).sum())
    return hits / max(nq, 1)


def hits_from_store(query_vecs: np.ndarray, store: VectorStore,
                    gold_ids: np.ndarray, mesh, k: int = 10,
                    query_batch: int = 1024, chunk: int = 8192,
                    index=None, nprobe: Optional[int] = None) -> int:
    """Number of queries whose gold id lands in the store-streamed top-k.
    With `index` (index.ivf.IVFIndex), retrieval goes through the
    sublinear ANN path instead of the full-store sweep (docs/ANN.md) —
    the reported recall then measures model AND index quality together."""
    if query_vecs.shape[0] == 0:
        return 0
    if index is not None:
        _, retrieved, _ = index.search(
            np.asarray(query_vecs, np.float32), k=k, nprobe=nprobe)
    else:
        _, retrieved = topk_over_store(
            np.asarray(query_vecs, np.float32), store, mesh, k=k,
            chunk=chunk, query_batch=query_batch)
    return int((retrieved == gold_ids[:, None]).any(axis=1).sum())


def recall_from_store(query_vecs: np.ndarray, store: VectorStore,
                      gold_ids: np.ndarray, mesh, k: int = 10,
                      query_batch: int = 1024, chunk: int = 8192,
                      index=None, nprobe: Optional[int] = None) -> float:
    """Recall@k streaming the store through the sharded cross-shard merge —
    never materializes more than one store shard. `index`/`nprobe` route
    retrieval through the IVF ANN path instead (hits_from_store)."""
    hits = hits_from_store(query_vecs, store, gold_ids, mesh, k=k,
                           query_batch=query_batch, chunk=chunk,
                           index=index, nprobe=nprobe)
    return float(hits) / max(query_vecs.shape[0], 1)


def recall_vs_exact(index, store: VectorStore, query_vecs: np.ndarray,
                    mesh, k: int = 10, nprobe: Optional[int] = None,
                    query_batch: int = 1024, chunk: int = 8192) -> float:
    """ANN recall@k against the EXACT ground truth: the mean fraction of
    each query's exact top-k (topk_over_store) that the IVF index also
    returns at this `nprobe`. This is the index-quality contract
    (docs/ANN.md) — independent of model quality, unlike gold-id recall —
    and lands in the bench record as `ann_recall_at_10`."""
    qv = np.asarray(query_vecs, np.float32)
    if qv.shape[0] == 0:
        return 0.0
    _, exact_ids = topk_over_store(qv, store, mesh, k=k, chunk=chunk,
                                   query_batch=query_batch)
    _, ann_ids, _ = index.search(qv, k=k, nprobe=nprobe)
    total = 0.0
    for row_exact, row_ann in zip(exact_ids, ann_ids):
        truth = set(int(i) for i in row_exact if i >= 0)
        if not truth:
            total += 1.0
            continue
        got = set(int(i) for i in row_ann if i >= 0)
        total += len(truth & got) / len(truth)
    return total / qv.shape[0]


def evaluate_recall(embedder: BulkEmbedder, corpus: ToyCorpus,
                    store: VectorStore, num_queries: Optional[int] = None,
                    k: int = 10, index=None,
                    nprobe: Optional[int] = None) -> Tuple[float, int]:
    """Embed eval queries, search the store, return (recall@k, num_queries).
    Gold label for query i is page i (ToyCorpus invariant).

    Multi-host: each process embeds + searches a contiguous slice of the
    query range on its (local) mesh — every host still streams the full
    store, since any page can be a nearest neighbour of any query — and
    only the integer hit counts cross processes (call stack §4.3)."""
    from dnn_page_vectors_tpu.parallel.multihost import (
        allgather_hosts, process_info)
    nq = min(num_queries or embedder.cfg.eval.eval_queries, corpus.num_pages)
    pi, pc = process_info()
    lo, hi = pi * nq // pc, (pi + 1) * nq // pc
    query_vecs = embedder.embed_texts(
        [corpus.query_text(i) for i in range(lo, hi)], tower="query")
    gold = np.arange(lo, hi, dtype=np.int64)
    hits = hits_from_store(query_vecs, store, gold, embedder.mesh, k=k,
                           index=index, nprobe=nprobe)
    if pc > 1:
        counts = allgather_hosts(np.array([hits, hi - lo], np.int64)).sum(0)
        return float(counts[0]) / max(int(counts[1]), 1), nq
    return float(hits) / max(nq, 1), nq
