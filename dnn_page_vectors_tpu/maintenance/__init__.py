"""Background maintenance for a live page-vector store
(docs/MAINTENANCE.md): the subsystem that keeps a continuously-updated
corpus healthy WHILE it serves, with zero reader-visible pauses.

  * `compact` — online generation compaction: fold the gen-NNNN chain +
    base into a fresh compacted base (dead rows dropped, ids preserved,
    byte-deterministic), swapped in with one atomic manifest flip;
  * `lease` — per-writer append leases on the id cursor, so concurrent
    `cli append` processes queue or fail fast instead of double-assigning
    page ids;
  * `migrate` — rolling model migration: re-embed a live store to a new
    model step unit-by-unit (base, then each generation) with an atomic
    per-unit manifest flip, while serving runs dual-stamp
    (docs/MAINTENANCE.md "Rolling model migration");
  * `service` — the supervised `MaintenanceService` worker pool (one
    worker per pillar: compactor, off-path index rebuilder, janitor,
    autoscaler, migrator), driven by `cli maintain [--once]` or attached
    in-process via `SearchService.start_maintenance()`.
"""
from dnn_page_vectors_tpu.maintenance.compact import (
    compact_store, purge_stale)
from dnn_page_vectors_tpu.maintenance.lease import (
    AppendLease, LeaseHeld, LeaseLost, expire_stale_lease)
from dnn_page_vectors_tpu.maintenance.migrate import (
    MigrationPlan, migrate_store)
from dnn_page_vectors_tpu.maintenance.service import MaintenanceService

__all__ = [
    "AppendLease", "LeaseHeld", "LeaseLost", "MaintenanceService",
    "MigrationPlan", "compact_store", "expire_stale_lease",
    "migrate_store", "purge_stale",
]
