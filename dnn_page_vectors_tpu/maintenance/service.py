"""The background maintenance service (docs/MAINTENANCE.md).

`MaintenanceService` supervises one worker thread per pillar, each polling
its trigger every `maintenance.interval_s` seconds and running its job
under one shared mutation lock (two pillars must never interleave manifest
flips):

  * **compactor** — when the chain's tombstone density crosses
    `maintenance.compact_tombstone_density`, fold the generation chain
    into a fresh compacted base (maintenance/compact.py), rebuild the IVF
    index over it when one exists, hot-swap the serving view, then purge
    the old chain's bytes;
  * **rebuilder** — when a drift rebuild was deferred off the refresh()
    path (`serve.index_rebuild_pending`, docs/UPDATES.md) or the live
    index degraded to exact, build the next index generation BESIDE the
    live one (`IVFIndex.build(dirname=...)` reusing the recorded
    pq/balance config), flip the store's index-dir pointer atomically,
    and hot-swap via the existing `_ServeView` refresh — a drift rebuild
    never again blocks an append or a query;
  * **janitor** — sweep expired append leases, stale index generations
    (dirs the pointer moved off), and compaction debris a crashed run
    left behind. Old artifacts are deleted one full cycle after they go
    stale, so in-flight readers on the previous view never lose a file
    mid-query;
  * **migrator** (docs/MAINTENANCE.md "Rolling model migration") — once
    armed via `request_migration`, re-embed the live store to a new model
    step one unit per pass (base, then each generation, oldest first)
    through `MigrationPlan`, hot-swapping the serving view between units
    so the fleet walks through the stamp flip with no restarts; on
    completion rebuild the index over the new stamp and let the serving
    refresh retire the old tower;
  * **autoscaler** (docs/SCALING.md "Scale-out tier") — ladder the
    worker-fleet size off the serving telemetry: windowed queue-wait p99
    or deadline-shed rate over the up-thresholds spawns the next tail
    worker, sustained calm drains the highest one — acting through
    operator-attached hooks (`attach_scaler`), observable-only without
    them, and rate-limited by `maintenance.autoscale_cooldown_s` so a
    resize's own dip never reads as fresh pressure.

Every mutation goes through the manifest writers (`_write_shard_files`,
`_atomic_dump`, `set_index_dir`); worker exceptions are counted
(`maintenance_<pillar>_errors`), logged, and never kill the worker. The
service is driven by `cli maintain [--once]`, or attached in-process to a
`SearchService` via `start_maintenance()` — which also moves drift
rebuilds off the refresh path (`maintenance.bg_rebuild`).

API: `start()` (spawn the workers, idempotent), `pause()`/`resume()`
(freeze/unfreeze trigger checks), `drain()` (block until in-flight jobs
finish), `run_once()` (one synchronous pass of all three pillars — works
with or without the threads), `close()` (stop + join).
"""
from __future__ import annotations

import glob
import json
import os
import re
import shutil
import threading
import time
from typing import Callable, Dict, Optional

from dnn_page_vectors_tpu.infer.vector_store import VectorStore
from dnn_page_vectors_tpu.maintenance.compact import (
    compact_store, purge_stale)
from dnn_page_vectors_tpu.maintenance.lease import expire_stale_lease
from dnn_page_vectors_tpu.maintenance.migrate import MigrationPlan
from dnn_page_vectors_tpu.utils import faults, telemetry

_INDEX_DIR_RE = re.compile(r"^ivf(-\d+)?$")


def _next_index_dirname(current: str) -> str:
    """ivf -> ivf-0001 -> ivf-0002 ... (the next index generation's home,
    built beside the live one and pointer-flipped in)."""
    m = re.match(r"^ivf-(\d+)$", current)
    return f"ivf-{(int(m.group(1)) if m else 0) + 1:04d}"


class MaintenanceService:
    """Supervised pillar workers over one store (docs/MAINTENANCE.md).

    `svc` (optional) attaches a live `SearchService`: its registry carries
    the maintenance instruments, completed swaps hot-swap the serving view
    through `svc.refresh()`, and background rebuilds count into the
    service's `full_rebuilds` — the acceptance pin that rebuilds happen
    ONLY here, never on the refresh caller."""

    PILLARS = ("compaction", "rebuild", "janitor", "autoscale", "migrate")

    def __init__(self, cfg, store_dir: str, mesh, svc=None, registry=None):
        self._cfg = cfg
        self._store_dir = store_dir
        self._mesh = mesh
        self._svc = svc
        self.registry = registry or (
            svc.registry if svc is not None
            else telemetry.default_registry())
        m = getattr(cfg, "maintenance", None)
        self._density = (getattr(m, "compact_tombstone_density", 0.2)
                         if m is not None else 0.2)
        self._interval_s = (getattr(m, "interval_s", 5.0)
                            if m is not None else 5.0)
        # autoscale pillar knobs (docs/SCALING.md "Scale-out tier")
        self._as_on = bool(getattr(m, "autoscale", False)
                           if m is not None else False)
        self._as_min = int(getattr(m, "autoscale_min_workers", 1)
                           if m is not None else 1)
        self._as_max = int(getattr(m, "autoscale_max_workers", 4)
                           if m is not None else 4)
        self._as_up_queue = float(
            getattr(m, "autoscale_up_queue_p99_ms", 50.0)
            if m is not None else 50.0)
        self._as_up_shed = float(
            getattr(m, "autoscale_up_shed_rate", 0.5)
            if m is not None else 0.5)
        self._as_down_queue = float(
            getattr(m, "autoscale_down_queue_p99_ms", 5.0)
            if m is not None else 5.0)
        self._as_cooldown_s = float(
            getattr(m, "autoscale_cooldown_s", 30.0)
            if m is not None else 30.0)
        # scaling acts only through operator-attached hooks; without
        # them the pillar still evaluates and emits events (the policy
        # is observable before it is trusted). All three are touched
        # only under the mutation lock (the pillar job) or before
        # start() — attach_scaler is a wiring call, not a hot path.
        self._spawn_hook: Optional[Callable[[int], None]] = None
        self._drain_hook: Optional[Callable[[int], None]] = None
        self._size_hook: Optional[Callable[[], int]] = None
        self._last_scale_t: Optional[float] = None
        # migrate pillar knobs (docs/MAINTENANCE.md "Rolling model
        # migration")
        mg = getattr(cfg, "migrate", None)
        self._mig_batch_rows = int(getattr(mg, "batch_rows", 4096)
                                   if mg is not None else 4096)
        self._mig_units = int(getattr(mg, "units_per_pass", 1)
                              if mg is not None else 1)
        self._mig_purge = bool(getattr(mg, "purge", True)
                               if mg is not None else True)
        self._migrate_req: Optional[Dict] = None   # guarded-by: _mlock
        # injectable for the fake-clock pillar-ladder tests
        self._clock: Callable[[], float] = time.monotonic
        self._lock = threading.Lock()
        # one mutation at a time across pillars AND run_once (re-entrant:
        # run_once drives all three jobs under one hold). The mutation
        # lock is the OUTER layer of the hierarchy — stats/fault counters
        # nest under it, never the reverse (graftcheck lock-order):
        # lock-order: MaintenanceService._mlock < MaintenanceService._lock
        # lock-order: MaintenanceService._mlock < faults._COUNTER_LOCK
        self._mlock = threading.RLock()
        self._stop = threading.Event()
        self._threads: list = []
        self._paused = False                  # guarded-by: _lock
        self._busy = 0                        # guarded-by: _lock
        self._stats: Dict[str, int] = {}      # guarded-by: _lock
        self._last: Dict[str, Dict] = {}      # guarded-by: _lock

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "MaintenanceService":
        """Spawn one worker per pillar (idempotent)."""
        if self._threads:
            return self
        for name, job in (("compaction", self._compact_once),
                          ("rebuild", self._rebuild_once),
                          ("janitor", self._janitor_once),
                          ("autoscale", self._autoscale_once),
                          ("migrate", self._migrate_once)):
            t = threading.Thread(target=self._run_worker, args=(name, job),
                                 daemon=True, name=f"maint-{name}")
            self._threads.append(t)
            t.start()
        return self

    def attach_scaler(self, spawn: Callable[[int], None],
                      drain: Callable[[int], None],
                      size: Optional[Callable[[], int]] = None) -> None:
        """Wire the autoscale pillar's actuators: `spawn(index)` starts
        the worker for the next tail partition index, `drain(index)`
        drains the highest one (the membership-at-the-tail rule,
        docs/SCALING.md), `size()` reports the current fleet size —
        defaulting to the attached service's live-worker count. Call
        before start(); without hooks the pillar only observes."""
        self._spawn_hook = spawn
        self._drain_hook = drain
        self._size_hook = size

    def _run_worker(self, name: str, job: Callable[[], Optional[Dict]]
                    ) -> None:
        while not self._stop.wait(self._interval_s):
            with self._lock:
                paused = self._paused
            if paused:
                continue
            self._guarded_job(name, job)

    def _guarded_job(self, name: str, job: Callable[[], Optional[Dict]]
                     ) -> Optional[Dict]:
        """One supervised pillar pass: mutation lock held, exceptions
        counted and reported, never propagated into the worker loop."""
        with self._lock:
            self._busy += 1
        try:
            with self._mlock:
                res = job()
        except Exception as e:  # noqa: BLE001 — the worker must survive
            res = {"error": f"{type(e).__name__}: {e}"[:300]}
            faults.count(f"maintenance_{name}_errors")
            faults.warn(f"maintenance {name} pass failed "
                        f"({res['error']}); the worker keeps polling")
        finally:
            with self._lock:
                self._busy -= 1
        if res is not None:
            with self._lock:
                self._stats[name] = self._stats.get(name, 0) + 1
                self._last[name] = res
        return res

    def pause(self) -> None:
        """Stop triggering new jobs (in-flight ones finish; see drain)."""
        with self._lock:
            self._paused = True

    def resume(self) -> None:
        with self._lock:
            self._paused = False

    def drain(self, timeout_s: float = 60.0) -> bool:
        """Block until no pillar job is in flight. True when drained."""
        deadline = time.monotonic() + max(0.0, float(timeout_s))
        while True:
            with self._lock:
                if self._busy == 0:
                    return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.02)

    def close(self) -> None:
        """Stop the workers and join them (drains in-flight jobs)."""
        self._stop.set()
        for t in self._threads:
            t.join()
        self._threads = []

    def run_once(self) -> Dict:
        """One synchronous pass of every pillar (janitor first so a
        crashed prior run's debris never confuses the triggers) — the
        `cli maintain --once` / bench / loadgen-mutator entry point.
        Works with or without the background threads running."""
        out: Dict[str, Dict] = {}
        with self._mlock:
            for name, job in (("janitor", self._janitor_once),
                              ("compaction", self._compact_once),
                              ("rebuild", self._rebuild_once),
                              ("migrate", self._migrate_once),
                              ("autoscale", self._autoscale_once)):
                res = self._guarded_job(name, job)
                if res is not None:
                    out[name] = res
        return out

    def stats(self) -> Dict:
        """Pass counts + each pillar's last result (telemetry snapshot)."""
        with self._lock:
            return {"passes": dict(self._stats),
                    "last": {k: dict(v) for k, v in self._last.items()}}

    # -- pillar: generation compaction -------------------------------------
    def _compact_once(self) -> Optional[Dict]:
        # trigger check on an unverified handle (a CRC sweep per poll
        # would re-read every shard's bytes every interval_s); the
        # compaction itself re-opens WITH the verify gate
        store = VectorStore(self._store_dir, verify=False)
        ms = store.maintenance_stats()
        reg = self.registry
        reg.gauge("maintenance.tombstone_density").set(
            ms["tombstone_density"])
        reg.gauge("maintenance.dead_rows").set(ms["dead_rows"])
        reg.gauge("maintenance.reclaimable_bytes").set(
            ms["reclaimable_bytes"])
        if (store.migration is not None
                or store.chain_generation <= store.compacted_through
                or ms["tombstone_density"] < self._density):
            # mid-migration, folding would mix stamps within one shard —
            # the migrate pillar owns the store until the completion flip
            return None
        store = VectorStore(self._store_dir)     # verified handle
        had_index = os.path.exists(os.path.join(
            store.directory, store.index_dirname, "manifest.json"))
        stats = compact_store(store, registry=reg)
        if stats.get("action") != "compacted":
            return stats
        if had_index:
            # rebuild over the compacted base BEFORE the serving refresh:
            # the view swap then lands store + index together, with no
            # degraded-to-exact window in between
            stats["index_rebuild"] = self._swap_index(
                store, reason=f"generation compaction epoch "
                              f"{stats['epoch']}", refresh=False)
        if self._svc is not None:
            info = self._svc.refresh()
            stats["refresh_swap_ms"] = info.get("swap_ms")
            if "partitions" in info:
                # partitioned service (docs/SCALING.md): the compacted
                # base rolled in partition by partition — queries on the
                # other partitions never waited on this one's restage
                stats["partitions_refreshed"] = len(info["partitions"])
        # reclaim only after the serving view moved over — in-flight
        # buckets on the old view finished during the refresh swap
        stats["purged"] = purge_stale(store, stats)
        stats.pop("stale_dirs", None)
        stats.pop("stale_files", None)
        return stats

    # -- pillar: off-path index rebuilds -----------------------------------
    def _rebuild_once(self) -> Optional[Dict]:
        svc = self._svc
        if VectorStore(self._store_dir, verify=False).migration is not None:
            # an index built mid-migration would span two encoders'
            # geometries; serving runs exact on mixed-stamp views and the
            # migrate pillar rebuilds at the completion flip
            return None
        reason = None
        if svc is not None:
            if svc._serve_index != "ivf":
                return None
            pending = svc.registry.gauge(
                "serve.index_rebuild_pending").value > 0
            err = svc._view.index_error
            store0 = VectorStore(self._store_dir, verify=False)
            has_manifest = os.path.exists(os.path.join(
                store0.directory, store0.index_dirname, "manifest.json"))
            if pending:
                reason = "drift rebuild deferred off the refresh path"
            elif err is not None and has_manifest:
                reason = f"live index degraded ({err[:120]})"
        else:
            store0 = VectorStore(self._store_dir, verify=False)
            mpath = os.path.join(store0.directory, store0.index_dirname,
                                 "manifest.json")
            if os.path.exists(mpath):
                reason = self._standalone_rebuild_reason(store0, mpath)
        if reason is None:
            return None
        store = VectorStore(self._store_dir)
        if store.num_vectors == 0:
            return None
        return self._swap_index(store, reason=reason)

    def _standalone_rebuild_reason(self, store,
                                   mpath: str) -> Optional[str]:
        """Without a live service, decide from the on-disk index: drift
        past updates.rebuild_drift, or structural staleness open() would
        reject (compaction, quarantine, re-stamp)."""
        from dnn_page_vectors_tpu.index.ivf import (
            IndexUnavailable, IVFIndex)
        try:
            with open(mpath) as f:
                man = json.load(f)
        except (OSError, ValueError):
            return "torn index manifest"
        drift = (int(man.get("appended_since_build", 0))
                 / max(store.num_vectors, 1))
        limit = getattr(getattr(self._cfg, "updates", None),
                        "rebuild_drift", 0.25)
        if drift > limit:
            return f"drift {drift:.3f} > rebuild_drift {limit}"
        try:
            IVFIndex.open(store, verify=True)
        except IndexUnavailable as e:
            return f"index unavailable ({str(e)[:120]})"
        except Exception as e:  # noqa: BLE001 — unreadable = rebuild
            return f"index unreadable ({type(e).__name__})"
        return None

    def _swap_index(self, store, reason: str,
                    refresh: bool = True) -> Dict:
        """Build the next index generation beside the live one, flip the
        store's index-dir pointer atomically, and (with a service
        attached) hot-swap the serving view. The old index directory is
        left on disk for the janitor — a reader on the previous view may
        still be mmap-ing its code files."""
        from dnn_page_vectors_tpu.index.ivf import IVFIndex
        faults.active().check("bg_rebuild")
        old_name = store.index_dirname
        old_man: Dict = {}
        mpath = os.path.join(store.directory, old_name, "manifest.json")
        if os.path.exists(mpath):
            try:
                with open(mpath) as f:
                    old_man = json.load(f)
            except (OSError, ValueError):
                old_man = {}
        next_name = _next_index_dirname(old_name)
        serve = self._cfg.serve
        pq_cfg = old_man.get("pq") or {}
        t0 = time.perf_counter()
        idx = IVFIndex.build(
            store, self._mesh, nlist=getattr(serve, "nlist", 0),
            iters=getattr(serve, "kmeans_iters", 8),
            seed=self._cfg.data.seed,
            init=getattr(serve, "kmeans_init", "kmeans++"),
            balance=old_man.get("balance",
                                getattr(serve, "kmeans_balance", 0.0)),
            pq_m=pq_cfg.get("m", 0), pq_iters=pq_cfg.get("iters", 8),
            opq_iters=pq_cfg.get("opq_iters", 3), dirname=next_name)
        build_s = time.perf_counter() - t0
        t1 = time.perf_counter()
        store.set_index_dir(next_name)       # THE pointer flip
        rb = {"reason": reason[:200], "dirname": next_name,
              "nlist": idx.nlist, "build_seconds": round(build_s, 3)}
        if refresh and self._svc is not None:
            rinfo = self._svc.refresh()
            if "partitions" in rinfo:
                # each partition re-opened its restricted view of the new
                # index generation in turn (rolling swap, docs/SCALING.md)
                rb["partitions_refreshed"] = len(rinfo["partitions"])
        if self._svc is not None:
            self._svc._m_rebuilds.inc()
            self._svc.registry.gauge("serve.index_rebuild_pending").set(0.0)
        rb["swap_ms"] = round((time.perf_counter() - t1) * 1000.0, 3)
        self.registry.counter("maintenance.bg_rebuilds").inc()
        self.registry.gauge("maintenance.bg_rebuild_swap_ms").set(
            rb["swap_ms"])
        self.registry.event("index_rebuild_bg", rb)
        faults.count("index_bg_rebuilds")
        return rb

    # -- pillar: rolling model migration -----------------------------------
    def request_migration(self, to_step: int, corpus, embedder) -> None:
        """Arm the migrate pillar: re-embed the store to `to_step` with
        `embedder` (built over the NEW model's params) reading page text
        from `corpus`. The pillar then sweeps one unit per pass, hot-
        swapping the serving view between units; with a service attached
        its query path goes dual-stamp immediately (begin_migration) so
        queries route per shard stamp mid-sweep."""
        with self._mlock:
            self._migrate_req = {"to_step": int(to_step), "corpus": corpus,
                                 "embedder": embedder}
        if self._svc is not None:
            self._svc.begin_migration(embedder.params, int(to_step))

    def _migrate_once(self) -> Optional[Dict]:   # holds-lock: _mlock
        req = self._migrate_req
        if req is None:
            return None
        store = VectorStore(self._store_dir)      # verified handle
        plan = MigrationPlan(store, req["corpus"], req["embedder"],
                             req["to_step"], registry=self.registry,
                             batch_rows=self._mig_batch_rows)
        begun = plan.begin()
        if begun.get("action") == "noop":
            self._migrate_req = None
            return begun
        units = plan.pending_units()
        if units:
            out: Dict = {**begun, "action": "migrating"}
            out["units"], out["rows"], stale = [], 0, []
            for unit in units[: self._mig_units]:
                st = plan.migrate_unit(unit)
                out["units"].append(int(unit))
                out["rows"] += int(st.get("rows", 0))
                stale += st.get("stale_files", [])
            if self._svc is not None:
                # the fleet walks onto the re-embedded unit now — the
                # epoch bump rides the same refresh generation gate every
                # other manifest flip uses
                info = self._svc.refresh()
                out["refresh_swap_ms"] = info.get("swap_ms")
            if self._mig_purge:
                # superseded old-stamp bytes, reclaimed only after the
                # serving view moved over (same rule as compaction)
                out["purged"] = purge_stale(store, {"stale_files": stale})
            return out
        fin = plan.complete()
        if fin is None:
            return None
        had_index = os.path.exists(os.path.join(
            store.directory, store.index_dirname, "manifest.json"))
        if had_index:
            # rebuild over the NEW stamp before the final refresh: ANN ran
            # degraded-to-exact through the dual-stamp window, and the
            # completion swap lands stamp + index together
            fin["index_rebuild"] = self._swap_index(
                store, reason=f"model migration to step {req['to_step']}",
                refresh=False)
        if self._svc is not None:
            # this refresh adopts the new query tower and unloads the old
            # one (SearchService.refresh, docs/SERVING.md)
            info = self._svc.refresh()
            fin["refresh_swap_ms"] = info.get("swap_ms")
        self._migrate_req = None
        return fin

    # -- pillar: autoscale (docs/SCALING.md "Scale-out tier") --------------
    def _autoscale_once(self) -> Optional[Dict]:
        """One policy evaluation: read the windowed pressure signals off
        the attached service, ladder them against the thresholds, and —
        inside the fleet-size bounds, outside the cooldown — act through
        the attached hooks. Spawn targets the next tail partition index,
        drain the highest (membership changes at the TAIL, so the
        gateway's contiguity rule re-cuts the split); both emit their
        event whether or not a hook is attached."""
        if not self._as_on:
            return None
        svc = self._svc
        if svc is None:
            return None
        sig = svc.autoscale_signals()
        reg = self.registry
        reg.gauge("maintenance.autoscale_queue_p99_ms").set(
            sig["queue_wait_p99_ms"])
        reg.gauge("maintenance.autoscale_shed_rate").set(sig["shed_rate"])
        if self._size_hook is not None:
            size = int(self._size_hook())
        elif getattr(svc, "_fanout", None) is not None:
            size = len(svc._fanout.live_workers())
        else:
            return None       # no fleet to size
        # the queue-p99 trigger needs a populated window (the same >= 4
        # floor the admission door uses before trusting the percentile);
        # the shed-rate trigger is already evidence by itself
        queue_hot = (sig["queue_wait_samples"] >= 4
                     and sig["queue_wait_p99_ms"] >= self._as_up_queue)
        shed_hot = sig["shed_rate"] >= self._as_up_shed
        calm = (sig["queue_wait_p99_ms"] <= self._as_down_queue
                and sig["shed_rate"] == 0.0)
        decision = None
        if (queue_hot or shed_hot) and size < self._as_max:
            decision = "up"
        elif calm and size > self._as_min:
            decision = "down"
        if decision is None:
            return None
        now = self._clock()
        if (self._last_scale_t is not None
                and now - self._last_scale_t < self._as_cooldown_s):
            return None       # cooling down: the last resize must settle
        attrs = {"workers": size,
                 "queue_wait_p99_ms": sig["queue_wait_p99_ms"],
                 "shed_rate": sig["shed_rate"]}
        if decision == "up":
            acted = self._spawn_hook is not None
            if acted:
                self._spawn_hook(size)        # the next tail index
            reg.event("autoscale_up", dict(
                attrs, to_workers=size + 1, acted=acted,
                trigger="queue_wait" if queue_hot else "shed_rate"))
        else:
            acted = self._drain_hook is not None
            if acted:
                self._drain_hook(size - 1)    # the highest index drains
            reg.event("autoscale_down", dict(
                attrs, to_workers=size - 1, acted=acted))
        reg.counter("maintenance.autoscale_decisions").inc()
        self._last_scale_t = now
        return {"decision": decision, "workers": size, "acted": acted,
                **{k: sig[k] for k in ("queue_wait_p99_ms", "shed_rate")}}

    # -- pillar: janitor ---------------------------------------------------
    def _janitor_once(self) -> Optional[Dict]:
        store = VectorStore(self._store_dir, verify=False)
        out = {"lease_expired": False, "index_dirs_removed": 0,
               "migrate_dirs_removed": 0, "purged_dirs": 0,
               "purged_files": 0}
        if expire_stale_lease(store, registry=self.registry):
            out["lease_expired"] = True
            self.registry.counter("maintenance.leases_expired").inc()
        cur = store.index_dirname
        live_idx = os.path.join(store.directory, cur)
        for path in sorted(glob.glob(os.path.join(store.directory,
                                                  "ivf*"))):
            name = os.path.basename(path)
            if (path == live_idx or not os.path.isdir(path)
                    or not _INDEX_DIR_RE.match(name)):
                continue
            shutil.rmtree(path, ignore_errors=True)
            out["index_dirs_removed"] += 1
        # migration unit dirs no manifest references any more: a crashed
        # attempt's torn unit, or a unit a later migration/compaction
        # superseded (docs/MAINTENANCE.md "Rolling model migration")
        ref_dirs = {os.path.dirname(e[k]) for e in store.shards()
                    for k in ("vec", "ids", "scl") if k in e}
        for path in sorted(glob.glob(os.path.join(store.directory,
                                                  "migrate-*"))):
            if (os.path.isdir(path)
                    and os.path.basename(path) not in ref_dirs):
                shutil.rmtree(path, ignore_errors=True)
                out["migrate_dirs_removed"] += 1
        epoch = store.compacted_through
        if epoch:
            referenced = {os.path.dirname(e[k]) for e in store.shards()
                          for k in ("vec", "ids", "scl") if k in e}
            ref_files = {e[k] for e in store.shards()
                         for k in ("vec", "ids", "scl")
                         if k in e and os.path.dirname(e[k]) == ""}
            stale = {"stale_dirs": [], "stale_files": []}
            for path in glob.glob(os.path.join(store.directory, "gen-*")):
                m = re.match(r"^gen-(\d+)$", os.path.basename(path))
                if m and int(m.group(1)) <= epoch and os.path.isdir(path):
                    stale["stale_dirs"].append(path)
            for path in glob.glob(os.path.join(store.directory,
                                               "compact-*")):
                if (os.path.isdir(path)
                        and os.path.basename(path) not in referenced):
                    stale["stale_dirs"].append(path)
            for path in glob.glob(os.path.join(store.directory,
                                               "shard_*.npy")):
                if os.path.basename(path) not in ref_files:
                    stale["stale_files"].append(path)
            purged = purge_stale(store, stale)
            out["purged_dirs"] = purged["purged_dirs"]
            out["purged_files"] = purged["purged_files"]
        return out if any(out.values()) else None
