"""Per-writer append leases on the store's id cursor (docs/MAINTENANCE.md).

Until this module, "one generation writer at a time" was a convention, not
a mechanism: two concurrent `cli append` processes would both read the same
append cursor (`next_page_id`), both open generation G+1, and the second
`GenerationWriter` would even wipe the first one's half-written directory —
double-assigned page ids and torn bytes. The lease makes the cursor a
leased resource:

  * the lease record is ONE json file under the store manifest dir
    (`append.lease.json`), written through the store's atomic fault-aware
    dump (`lease_dump`/`lease_file` ops) — manifest-mediated like every
    other durable byte in the store's blast radius;
  * the check-then-write critical section is serialized by a short-lived
    `O_CREAT|O_EXCL` lock file (`append.lease.json.lock`) so two acquirers
    can never interleave read-and-claim; a crashed holder's lock file goes
    stale and is broken after `_LOCK_STALE_S`;
  * leases EXPIRE (`updates.writer_lease_s`): a writer that died mid-append
    blocks its successors for at most one ttl, after which the next
    acquirer STEALS the lease (`lease_stolen` event) — the dead writer's
    uncommitted generation was never visible, so stealing is safe;
  * a second live writer either QUEUES on the lease (polling until
    `updates.lease_wait_s` runs out) or fails fast with `LeaseHeld` when
    the wait budget is 0.

`append_corpus` (updates/append.py) wraps its whole cursor-read → embed →
commit window in a lease and renews it per shard, so long appends never
outlive their own ttl. Expiry uses the wall clock on purpose: leases
coordinate real concurrent processes, and the lease file is coordination
state, not byte-pinned output (the appended generation bytes stay
deterministic — the lease never touches them).
"""
from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Dict, Optional

from dnn_page_vectors_tpu.utils import faults, telemetry

LEASE_NAME = "append.lease.json"
# a lock FILE (the O_EXCL critical section, held for one read+write) older
# than this is a crashed acquirer's leftover and is broken
_LOCK_STALE_S = 5.0
_POLL_S = 0.05

_TOKEN_LOCK = threading.Lock()
_TOKEN_SEQ = 0


def _next_token(owner: str) -> str:
    """Process-unique lease token: owner + pid + a monotone sequence (no
    entropy needed — uniqueness per process is what the verify-after-write
    step compares)."""
    global _TOKEN_SEQ
    with _TOKEN_LOCK:
        _TOKEN_SEQ += 1
        return f"{owner}:{os.getpid()}:{_TOKEN_SEQ}"


class LeaseHeld(RuntimeError):
    """The append lease is held by another live writer and the wait budget
    ran out — fail fast instead of double-assigning ids."""


class LeaseLost(RuntimeError):
    """This writer's lease expired and was taken over mid-append (renew
    came too late). The append must abort: its cursor is no longer owned."""


class AppendLease:
    """One writer's claim on a store's append cursor (context manager).

    >>> with AppendLease(store, ttl_s=30.0, wait_s=5.0):
    ...     cursor = store.next_page_id()   # safe: no other leased writer
    """

    def __init__(self, store, owner: Optional[str] = None,
                 ttl_s: float = 30.0, wait_s: float = 5.0,
                 registry=None):
        self.store = store
        self.path = os.path.join(store.directory, LEASE_NAME)
        self.owner = owner or f"pid-{os.getpid()}"
        self.token = _next_token(self.owner)
        self.ttl_s = max(0.1, float(ttl_s))
        self.wait_s = max(0.0, float(wait_s))
        self.registry = registry or telemetry.default_registry()
        self.held = False
        self.stole_from: Optional[str] = None

    # -- the O_EXCL critical section ---------------------------------------
    @contextlib.contextmanager
    def _flock(self):
        """Serialize check-then-write against every other acquirer (same
        host or another process on the shared filesystem). Held for one
        lease-file read + write only; a stale lock file (crashed holder)
        is broken after _LOCK_STALE_S."""
        lock = self.path + ".lock"
        while True:
            try:
                fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                break
            except FileExistsError:
                try:
                    if time.time() - os.path.getmtime(lock) > _LOCK_STALE_S:
                        os.remove(lock)
                        faults.count("lease_lock_broken")
                        continue
                except OSError:
                    continue
                time.sleep(_POLL_S)
        try:
            yield
        finally:
            os.close(fd)
            try:
                os.remove(lock)
            except OSError:
                pass

    def _read(self) -> Optional[Dict]:
        try:
            import json
            with open(self.path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _write(self, now: float) -> None:
        self.store._atomic_dump({
            "token": self.token, "owner": self.owner,
            "acquired": round(now, 3),
            "expires": round(now + self.ttl_s, 3),
            "cursor": self.store.next_page_id(),
        }, self.path, op="lease")

    # -- protocol ----------------------------------------------------------
    def acquire(self) -> "AppendLease":
        """Claim the cursor: free/expired leases are taken immediately
        (expired-but-present ones count as STOLEN), a live foreign lease is
        polled until `wait_s` runs out, then LeaseHeld."""
        deadline = time.monotonic() + self.wait_s
        while True:
            with self._flock():
                cur = self._read()
                now = time.time()
                expired = cur is not None and float(
                    cur.get("expires", 0)) <= now
                if cur is None or expired or cur.get("token") == self.token:
                    self.stole_from = (cur.get("owner")
                                       if cur is not None and expired
                                       else None)
                    self._write(now)
                    self.held = True
                    faults.count("lease_acquired")
                    self.registry.event("lease_acquired", {
                        "owner": self.owner,
                        "stolen_from": self.stole_from})
                    if self.stole_from is not None:
                        faults.count("lease_stolen")
                        self.registry.event("lease_stolen", {
                            "owner": self.owner,
                            "from": self.stole_from})
                    return self
                holder = cur.get("owner", "?")
            if time.monotonic() >= deadline:
                raise LeaseHeld(
                    f"append lease on {self.store.directory} is held by "
                    f"{holder} (expires in "
                    f"{float(cur.get('expires', 0)) - now:.1f}s); "
                    "queue longer (updates.lease_wait_s) or retry")
            time.sleep(_POLL_S)

    def renew(self) -> None:
        """Extend the ttl mid-append (called per shard by append_corpus) —
        a long append never outlives its own lease. Raises LeaseLost when
        another writer took over (this append must abort)."""
        if not self.held:
            raise RuntimeError("renew() before acquire()")
        with self._flock():
            cur = self._read()
            if cur is None or cur.get("token") != self.token:
                self.held = False
                raise LeaseLost(
                    f"append lease on {self.store.directory} was taken by "
                    f"{(cur or {}).get('owner', '?')} — this writer's ttl "
                    "expired mid-append; raise updates.writer_lease_s")
            self._write(time.time())

    def release(self) -> None:
        """Drop the lease (idempotent; never removes a foreign lease)."""
        if not self.held:
            return
        with self._flock():
            cur = self._read()
            if cur is not None and cur.get("token") == self.token:
                try:
                    os.remove(self.path)
                except OSError:
                    pass
        self.held = False

    def __enter__(self) -> "AppendLease":
        return self.acquire() if not self.held else self

    def __exit__(self, *exc) -> None:
        self.release()


def expire_stale_lease(store, registry=None) -> bool:
    """Janitor sweep (maintenance/service.py): remove an EXPIRED lease file
    so the next acquirer starts clean instead of paying the steal path.
    Returns True when one was removed."""
    lease = AppendLease(store, owner="janitor", registry=registry)
    with lease._flock():
        cur = lease._read()
        if cur is None or float(cur.get("expires", 0)) > time.time():
            return False
        try:
            os.remove(lease.path)
        except OSError:
            return False
    faults.count("lease_expired")
    return True
