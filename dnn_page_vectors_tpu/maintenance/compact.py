"""Online generation compaction (docs/MAINTENANCE.md).

Tombstones mask dead rows at read time (docs/UPDATES.md) — nothing ever
reclaims their bytes: a year of appends and deletions leaves the store
carrying every row it ever wrote, every generation manifest it ever
committed, and posting lists full of dead candidates. `compact_store`
folds the whole chain back down:

  * every LIVE row (id not tombstoned) across the base plus the intact
    generation chain is gathered at STORED width (int8 codes + scales, or
    fp16 rows — no requantization, so compaction is lossless and
    byte-deterministic given the same inputs), globally sorted by page id,
    and re-sharded into fresh shards under `<store>/compact-EEEE/`
    through the existing CRC-recording writer (`_write_shard_files`:
    bytes + fsync + size/CRC32 into the entry);
  * the swap is ONE atomic manifest dump (`compact_swap_dump` /
    `compact_swap_file` fault ops): the main manifest's shard table is
    replaced by the compacted entries, `compacted_through` records the
    folded epoch, and `append_cursor` pins the id high-water mark (a
    tombstoned top id must never be re-issued). Readers move from
    old-chain to new-base with that single pointer flip — a crash at any
    earlier point leaves the old chain fully intact (the compact dir is
    invisible until the flip), a crash after leaves the new base;
  * ids are PRESERVED — compaction moves rows, never renames them — and
    the generation counter stays monotonic: the next append opens
    generation `compacted_through + 1`.

Old files are not deleted at swap time: a live `_ServeView` may still be
streaming them. `purge_stale(store, stats)` reclaims them once the caller
knows no reader holds the old view (the MaintenanceService purges after
the serving refresh; `cli maintain --once` purges immediately).

The shard table change structurally invalidates any IVF index (its
recorded table no longer matches — docs/ANN.md), which is the designed
hand-off: the background rebuilder (maintenance/service.py) builds the
next index generation over the compacted base and pointer-flips it in.
"""
from __future__ import annotations

import os
import shutil
import time
from typing import Dict, Optional

import numpy as np

from dnn_page_vectors_tpu.utils import faults, telemetry


def _entry_bytes(entry: Dict) -> int:
    return sum(int(b) for b in entry.get("bytes", {}).values())


def compact_store(store, registry=None) -> Dict:
    """Fold the generation chain + base into a fresh compacted base and
    atomically swap it in. Returns the compaction stats dict (action,
    epoch, rows, dead rows dropped, byte accounting, and the stale
    dirs/files `purge_stale` reclaims). A store with no generations —
    nothing to fold — returns {"action": "noop"}."""
    t0 = time.perf_counter()
    if store._writer_files():
        raise ValueError(
            f"store at {store.directory} has live writer manifests (an "
            "embed fleet is mid-flight); compact after merge_writers()")
    if store.migration is not None:
        # folding would merge shards that carry DIFFERENT model stamps into
        # one output shard, breaking the one-stamp-per-shard routing pin —
        # the migrate pillar re-runs compaction after the completion flip
        return {"action": "noop", "reason": "migration in flight",
                "generation": store.generation}
    prev_epoch = store.compacted_through
    epoch = store.chain_generation
    if epoch <= prev_epoch:
        return {"action": "noop", "reason": "no generations to fold",
                "generation": epoch}
    old_entries = store.shards()
    old_bytes = sum(_entry_bytes(e) for e in old_entries)
    cursor_before = store.next_page_id()

    # pass 1 — source coordinates: (page id, source entry, source row) for
    # every stored row, tombstone-masked through load_ids (the one choke
    # point every reader uses, docs/UPDATES.md)
    ids_parts, src_parts, row_parts = [], [], []
    for pos, entry in enumerate(old_entries):
        ids = np.asarray(store.load_ids(entry), np.int64)
        ids_parts.append(ids)
        src_parts.append(np.full(ids.shape, pos, np.int32))
        row_parts.append(np.arange(ids.shape[0], dtype=np.int64))
    all_ids = (np.concatenate(ids_parts) if ids_parts
               else np.zeros((0,), np.int64))
    src = (np.concatenate(src_parts) if src_parts
           else np.zeros((0,), np.int32))
    rows = (np.concatenate(row_parts) if row_parts
            else np.zeros((0,), np.int64))
    live = all_ids >= 0
    dead_rows = int((~live).sum())
    ids_l, src_l, row_l = all_ids[live], src[live], rows[live]
    order = np.argsort(ids_l, kind="stable")     # global id order: the
    ids_l, src_l, row_l = ids_l[order], src_l[order], row_l[order]
    if ids_l.size and (np.diff(ids_l) == 0).any():
        raise RuntimeError(
            "duplicate live page id found while compacting — the store's "
            "update invariant (old rows tombstoned) is broken; refusing "
            "to fold")

    # pass 2 — gather + rewrite, one output shard at a time (host memory
    # stays O(shard) regardless of store size; sources are mmap'd)
    subdir = f"compact-{epoch:04d}"
    d = os.path.join(store.directory, subdir)
    if os.path.isdir(d):
        # a torn previous attempt never flipped the manifest, so its
        # directory is invisible garbage — clear it, same as a reused
        # quarantined generation number (docs/UPDATES.md)
        shutil.rmtree(d, ignore_errors=True)
    os.makedirs(d, exist_ok=True)
    is_int8 = store.manifest["dtype"] == "int8"
    raw_cache: Dict[int, tuple] = {}

    def _raw(pos: int):
        got = raw_cache.get(pos)
        if got is None:
            _, vecs, scl = store._load_entry(old_entries[pos], raw=True)
            got = raw_cache[pos] = (vecs, scl)
        return got

    # attribute words ride the fold untouched (docs/ANN.md "Filtered
    # retrieval"): compaction moves rows, never re-derives attributes —
    # pre-attrs shards contribute their all-zero default words
    has_attrs = store.attrs_enabled
    attrs_cache: Dict[int, np.ndarray] = {}

    def _attr_words(pos: int) -> np.ndarray:
        got = attrs_cache.get(pos)
        if got is None:
            got = attrs_cache[pos] = store.load_attrs(old_entries[pos])
        return got

    plan = faults.active()
    new_entries = []
    next_idx = store._next_shard_index()
    ss = store.manifest["shard_size"]
    for s0 in range(0, ids_l.size, ss):
        ids_c = ids_l[s0: s0 + ss]
        src_c = src_l[s0: s0 + ss]
        row_c = row_l[s0: s0 + ss]
        n = int(ids_c.size)
        data = np.empty((n, store.dim), np.int8 if is_int8 else np.float16)
        scl_c = np.empty((n,), np.float16) if is_int8 else None
        atr_c = np.empty((n,), np.uint32) if has_attrs else None
        for pos in np.unique(src_c):
            m = src_c == pos
            vecs, scl = _raw(int(pos))
            data[m] = np.asarray(vecs[row_c[m]])
            if scl_c is not None:
                scl_c[m] = np.asarray(scl[row_c[m]])
            if atr_c is not None:
                atr_c[m] = _attr_words(int(pos))[row_c[m]]
        plan.check("compact_write")
        if is_int8:
            entry = store._write_shard_files(subdir, next_idx, ids_c,
                                             None, data, scl_c,
                                             attrs=atr_c)
        else:
            entry = store._write_shard_files(subdir, next_idx, ids_c,
                                             data, None, None,
                                             attrs=atr_c)
        entry["gen"] = epoch         # masked only by LATER tombstones
        entry["id_lo"] = int(ids_c.min())
        entry["id_hi"] = int(ids_c.max()) + 1
        new_entries.append(entry)
        next_idx += 1

    # THE swap: one atomic manifest dump moves every reader from the old
    # chain to the new base; a crash before this line costs nothing but
    # an invisible compact dir
    man = dict(store.manifest)
    man["shards"] = new_entries
    man["compacted_through"] = epoch
    # every generation 1..epoch is folded, so any migrated-entry overrides
    # for them are folded too (docs/MAINTENANCE.md "Rolling model
    # migration")
    man.pop("gen_overrides", None)
    man["append_cursor"] = max(int(man.get("append_cursor", 0)),
                               cursor_before)
    store._atomic_dump(man, store._manifest_path, op="compact_swap")
    store.manifest = man
    store._load_generations()        # chain now resumes past the epoch

    # stale artifacts (reclaimed by purge_stale AFTER readers move over):
    # folded generation dirs, previous compact dirs, and root-level base
    # shard files the new manifest no longer references
    stale_dirs = [store._gen_path(g) for g in range(prev_epoch + 1,
                                                    epoch + 1)]
    old_subdirs = {os.path.dirname(e[k]) for e in old_entries
                   for k in ("vec", "ids", "scl", "atr") if k in e}
    stale_dirs += [os.path.join(store.directory, sd)
                   for sd in sorted(old_subdirs - {"", subdir})
                   if sd.startswith(("compact-", "migrate-"))]
    stale_files = [os.path.join(store.directory, e[k])
                   for e in old_entries
                   for k in ("vec", "ids", "scl", "atr")
                   if k in e and os.path.dirname(e[k]) == ""]
    new_bytes = sum(_entry_bytes(e) for e in new_entries)
    seconds = time.perf_counter() - t0
    stats = {
        "action": "compacted",
        "epoch": epoch,
        "rows": int(ids_l.size),
        "dead_rows_dropped": dead_rows,
        "generations_folded": epoch - prev_epoch,
        "shards": len(new_entries),
        "store_bytes_before": old_bytes,
        "store_bytes_after": new_bytes,
        "bytes_reclaimed": max(0, old_bytes - new_bytes),
        "seconds": round(seconds, 3),
        "compact_docs_per_s": round(ids_l.size / max(seconds, 1e-9), 2),
        "stale_dirs": stale_dirs,
        "stale_files": stale_files,
    }
    reg = registry or telemetry.default_registry()
    reg.counter("maintenance.compactions").inc()
    reg.counter("maintenance.compact_bytes_reclaimed").inc(
        stats["bytes_reclaimed"])
    reg.gauge("maintenance.compact_docs_per_s").set(
        stats["compact_docs_per_s"])
    reg.event("compaction", {
        "epoch": epoch, "rows": stats["rows"],
        "dead_rows_dropped": dead_rows,
        "bytes_reclaimed": stats["bytes_reclaimed"],
        "seconds": stats["seconds"]})
    faults.count("store_compactions")
    return stats


def purge_stale(store, stats: Dict) -> Dict:
    """Reclaim the old chain's bytes after a compaction, once no reader
    still holds the pre-swap view (the MaintenanceService calls this after
    the serving refresh; a crashed run's leftovers are swept by the
    janitor on the next cycle). Never touches a path the CURRENT manifest
    references, and never leaves the store directory."""
    referenced = {os.path.normpath(os.path.join(store.directory, e[k]))
                  for e in store.shards()
                  for k in ("vec", "ids", "scl", "atr") if k in e}
    removed_dirs, removed_files = 0, 0
    root = os.path.normpath(store.directory)
    for path in stats.get("stale_dirs", []):
        p = os.path.normpath(path)
        if not p.startswith(root + os.sep) or any(
                r.startswith(p + os.sep) for r in referenced):
            continue
        if os.path.isdir(p):
            shutil.rmtree(p, ignore_errors=True)
            removed_dirs += 1
    for path in stats.get("stale_files", []):
        p = os.path.normpath(path)
        if not p.startswith(root + os.sep) or p in referenced:
            continue
        try:
            os.remove(p)
            removed_files += 1
        except OSError:
            pass
    return {"purged_dirs": removed_dirs, "purged_files": removed_files}
