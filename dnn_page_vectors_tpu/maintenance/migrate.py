"""Rolling model migration (docs/MAINTENANCE.md "Rolling model migration").

Re-embed a LIVE store to a new model step unit-by-unit while it serves:
the base shard table first (the oldest vectors), then each appended
generation in chain order. Every commit point is ONE `_atomic_dump` of the
MAIN manifest (`op="migrate_swap"`), so a crash anywhere — including the
injected `migrate_write` / `migrate_swap_dump` / `migrate_swap_file`
faults — leaves a serveable store on exactly one side of the flip:

  * `begin()` records `{"migration": {from_step, to_step}}` and bumps
    `migration_epoch` (folded into `store.generation`, so every flip moves
    the number the refresh broadcast, the worker eligibility gate, and the
    result-cache key already gate on);
  * each unit's re-embedded shards land under
    `migrate-<to_step>-<unit>/` (data files + fsync first), then commit
    atomically — the base unit by replacing its `shards` entries, a
    generation unit as a `gen_overrides` record (CRC-matched against the
    gen manifest on disk, see `VectorStore._gen_override`) so the
    two-manifest crash window never exists;
  * `complete()` drops the migration record and flips the store stamp
    once NO unit still carries the old stamp — appends that landed
    mid-sweep (stamped from_step by the GenerationWriter) simply become
    new pending units, so the sweep loops until the store drains.

A shard is re-embedded whole, so the serving invariant is one stamp per
shard, never mixed within one (`entry_step`); mid-sweep the store
legitimately serves BOTH stamps and infer/serve.py routes each shard's
queries through the matching tower (dual-stamp serving).
"""
from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional

import numpy as np

from dnn_page_vectors_tpu.utils import faults, telemetry


def _entry_paths(store, entry: Dict) -> List[str]:
    return [os.path.join(store.directory, entry[k])
            for k in ("vec", "ids", "scl", "atr") if k in entry]


class MigrationPlan:
    """One rolling migration of `store` to `to_step` (docs/MAINTENANCE.md).

    `corpus` supplies the page text, `embedder` the NEW model's page tower
    (`embed_texts(..., tower="page")`); `batch_rows` bounds the host-side
    text batch per embed call. Drive it with `run()` (the cli path: sweep
    to completion) or unit-at-a-time via `begin()` / `pending_units()` /
    `migrate_unit()` / `complete()` (the maintenance pillar path, which
    hot-swaps the serving view between units)."""

    def __init__(self, store, corpus, embedder, to_step: int,
                 registry=None, batch_rows: int = 4096):
        self.store = store
        self.corpus = corpus
        self.embedder = embedder
        self.to_step = int(to_step)
        self.registry = registry or telemetry.default_registry()
        self.batch_rows = max(1, int(batch_rows))

    # -- lifecycle ---------------------------------------------------------
    def begin(self) -> Dict:
        """Record the migration in the main manifest (idempotent: resuming
        an in-flight migration to the same step is a no-op flip-wise). A
        store already at `to_step` returns {"action": "noop"}."""
        store = self.store
        if store._writer_files():
            raise ValueError(
                f"store at {store.directory} has live writer manifests (an "
                "embed fleet is mid-flight); migrate after merge_writers()")
        mig = store.migration
        if mig is not None:
            if int(mig.get("to_step", -1)) != self.to_step:
                raise ValueError(
                    f"a migration to step {mig.get('to_step')} is already "
                    f"in flight; finish it before migrating to "
                    f"{self.to_step}")
            return {"action": "resumed",
                    "from_step": int(mig.get("from_step", -1)),
                    "to_step": self.to_step}
        if store.model_step is None:
            raise ValueError(
                "store is unstamped (no model_step); run the base 'embed' "
                "before migrating")
        from_step = int(store.model_step)
        if from_step == self.to_step:
            return {"action": "noop", "reason": "store already at to_step",
                    "to_step": self.to_step}
        man = dict(store.manifest)
        man["migration"] = {"from_step": from_step, "to_step": self.to_step}
        man["migration_epoch"] = int(man.get("migration_epoch", 0)) + 1
        self._commit(man)
        self.registry.event("migration_started", {
            "from_step": from_step, "to_step": self.to_step,
            "units": len(self.pending_units()),
            "rows": store.num_vectors})
        return {"action": "started", "from_step": from_step,
                "to_step": self.to_step}

    def pending_units(self) -> List[int]:
        """Units still carrying a non-target stamp, oldest first: 0 is the
        base shard table, g > 0 is generation g."""
        store = self.store
        units: List[int] = []
        if any(store.entry_step(e) != self.to_step
               for e in store.manifest.get("shards", [])):
            units.append(0)
        for man in store.generations():
            if any(store.entry_step(e) != self.to_step
                   for e in man.get("shards", [])):
                units.append(int(man["gen"]))
        return units

    def migrate_unit(self, unit: int) -> Dict:
        """Re-embed every non-target-stamp shard of one unit and commit it
        with one atomic main-manifest flip. Returns the unit stats, with
        the superseded files listed for `purge_stale` (reclaim AFTER the
        serving view moved over — a reader on the previous view may still
        be mmap-ing them)."""
        store = self.store
        t0 = time.perf_counter()
        plan = faults.active()
        if unit == 0:
            src_entries = list(store.manifest.get("shards", []))
        else:
            mans = [m for m in store.generations()
                    if int(m["gen"]) == int(unit)]
            if not mans:
                raise ValueError(
                    f"generation {unit} is not in the live chain")
            src_entries = list(mans[0].get("shards", []))
        todo = [e for e in src_entries
                if store.entry_step(e) != self.to_step]
        if not todo:
            return {"action": "noop", "unit": int(unit), "rows": 0,
                    "stale_files": [], "stale_dirs": []}
        subdir = f"migrate-{self.to_step}-{int(unit):04d}"
        d = os.path.join(store.directory, subdir)
        self._clear_torn(d)
        os.makedirs(d, exist_ok=True)

        rows = 0
        new_by_index: Dict[int, Dict] = {}
        for e in todo:
            # RAW on-disk ids (never through load_ids): row positions must
            # survive byte-for-byte so the rewritten shard keeps its index,
            # count, and id-range — tombstones keep masking at read time
            ids = np.load(os.path.join(store.directory, e["ids"]))
            vecs = self._embed_ids(ids)
            # attributes are invariant under re-embedding (they describe
            # the PAGE, not the vector): copy the source shard's words
            # verbatim — pre-attrs shards carry their all-zero default
            words = (store.load_attrs(e) if store.attrs_enabled else None)
            plan.check("migrate_write")
            entry = store._write_shard_files(subdir, int(e["index"]), ids,
                                             vecs, None, None, attrs=words)
            for k in ("gen", "id_lo", "id_hi"):
                if k in e:
                    entry[k] = e[k]
            entry["model_step"] = self.to_step
            new_by_index[int(e["index"])] = entry
            rows += int(entry["count"])

        # THE per-unit flip: one atomic main-manifest dump moves every
        # reader from the old-stamp shards to the re-embedded ones, and
        # bumps migration_epoch in the SAME write so stale caches keyed on
        # the pre-flip generation can never satisfy a post-flip query
        man = dict(store.manifest)
        man["migration_epoch"] = int(man.get("migration_epoch", 0)) + 1
        if unit == 0:
            man["shards"] = [new_by_index.get(int(e["index"]), e)
                             for e in src_entries]
        else:
            gpath = os.path.join(store._gen_path(int(unit)), "manifest.json")
            with open(gpath) as f:
                disk_man = json.load(f)
            ovs = dict(man.get("gen_overrides") or {})
            ovs[str(int(unit))] = {
                "src_vec_crc": [s.get("crc", {}).get("vec")
                                for s in disk_man.get("shards", [])],
                "shards": [dict(new_by_index.get(int(e["index"]), e))
                           for e in src_entries]}
            man["gen_overrides"] = ovs
        self._commit(man)

        dt = time.perf_counter() - t0
        pps = round(rows / max(dt, 1e-9), 2)
        total = 1 + len(store.generations())
        done = total - len(self.pending_units())
        reg = self.registry
        reg.gauge("migrate.generations_done").set(done)
        reg.gauge("migrate.pages_per_s").set(pps)
        reg.event("migration_generation_done", {
            "generation": int(unit), "shards": len(todo), "rows": rows,
            "seconds": round(dt, 3)})
        faults.count("store_migrate_units")
        return {"action": "migrated_unit", "unit": int(unit),
                "shards": len(todo), "rows": rows,
                "seconds": round(dt, 3), "migrate_pages_per_s": pps,
                "stale_files": [p for e in todo
                                for p in _entry_paths(store, e)],
                # gen-NNNN dirs keep their manifest.json (the chain walk
                # needs it), so only individual files ever go stale here
                "stale_dirs": []}

    def complete(self) -> Optional[Dict]:
        """Drop the migration record and flip the store stamp — the LAST
        atomic flip, legal only once nothing still carries the old stamp.
        Returns None while units are still pending (or no migration is in
        flight)."""
        store = self.store
        mig = store.migration
        if mig is None or self.pending_units():
            return None
        man = dict(store.manifest)
        man.pop("migration", None)
        man["model_step"] = self.to_step
        man["migration_epoch"] = int(man.get("migration_epoch", 0)) + 1
        self._commit(man)
        self.registry.event("migration_complete", {
            "from_step": int(mig.get("from_step", -1)),
            "to_step": self.to_step, "rows": store.num_vectors})
        self.registry.counter("maintenance.migrations").inc()
        faults.count("store_migrations")
        return {"action": "completed",
                "from_step": int(mig.get("from_step", -1)),
                "to_step": self.to_step}

    def run(self) -> Dict:
        """Sweep to completion (the `cli migrate` path): begin, migrate
        every pending unit oldest-first — re-listing between units so
        appends that land mid-sweep are picked up — then complete. Returns
        the migration stats with the superseded files for purge_stale."""
        t0 = time.perf_counter()
        begun = self.begin()
        if begun.get("action") == "noop":
            return begun
        units_done, rows = 0, 0
        stale_files: List[str] = []
        while True:
            units = self.pending_units()
            if not units:
                break
            st = self.migrate_unit(units[0])
            units_done += 1
            rows += st["rows"]
            stale_files += st["stale_files"]
        fin = self.complete() or {}
        dt = time.perf_counter() - t0
        return {"action": "migrated",
                "from_step": int(begun.get("from_step", -1)),
                "to_step": self.to_step, "units": units_done,
                "rows": rows, "seconds": round(dt, 3),
                "migrate_pages_per_s": round(rows / max(dt, 1e-9), 2),
                "completed": fin.get("action") == "completed",
                "stale_dirs": [], "stale_files": stale_files}

    # -- internals ---------------------------------------------------------
    def _commit(self, man: Dict) -> None:
        store = self.store
        store._atomic_dump(man, store._manifest_path, op="migrate_swap")
        store.manifest = man
        store._load_generations()

    def _embed_ids(self, ids: np.ndarray) -> np.ndarray:
        parts = []
        for s in range(0, int(ids.shape[0]), self.batch_rows):
            texts = [self.corpus.page_text(int(i))
                     for i in ids[s: s + self.batch_rows]]
            parts.append(self.embedder.embed_texts(texts, tower="page"))
        if not parts:
            return np.zeros((0, self.store.dim), np.float16)
        return np.concatenate(parts)

    def _clear_torn(self, d: str) -> None:
        """A crashed previous attempt's files in this unit dir never made a
        manifest — clear them so stale bytes can't satisfy a fresh CRC
        record. Files the CURRENT manifest references (a committed earlier
        pass over this unit dir) are kept."""
        if not os.path.isdir(d):
            return
        store = self.store
        referenced = {os.path.normpath(os.path.join(store.directory, e[k]))
                      for e in store.shards()
                      for k in ("vec", "ids", "scl", "atr") if k in e}
        for name in os.listdir(d):
            p = os.path.normpath(os.path.join(d, name))
            if p not in referenced:
                try:
                    os.remove(p)
                except OSError:
                    pass


def migrate_store(store, corpus, embedder, to_step: int, registry=None,
                  batch_rows: int = 4096) -> Dict:
    """One-shot rolling migration of `store` to `to_step` (see
    MigrationPlan.run)."""
    return MigrationPlan(store, corpus, embedder, to_step,
                         registry=registry, batch_rows=batch_rows).run()
