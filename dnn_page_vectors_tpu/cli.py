"""CLI: one entry point per workflow (SURVEY.md §3 #25; call stacks §4.1-4.4).

  python -m dnn_page_vectors_tpu.cli train --config cdssm_toy
  python -m dnn_page_vectors_tpu.cli embed --config cdssm_toy
  python -m dnn_page_vectors_tpu.cli eval  --config cdssm_toy
  python -m dnn_page_vectors_tpu.cli mine  --config hardneg_v5p64
  python -m dnn_page_vectors_tpu.cli search --config cdssm_toy --query "..."
  python -m dnn_page_vectors_tpu.cli search --config cdssm_toy --queries q.txt
  python -m dnn_page_vectors_tpu.cli index --config cdssm_toy
  python -m dnn_page_vectors_tpu.cli index --config cdssm_toy --pq
  python -m dnn_page_vectors_tpu.cli search --config cdssm_toy --nprobe 8 ...
  python -m dnn_page_vectors_tpu.cli pipeline --config hardneg_v5p64 --rounds 4
  python -m dnn_page_vectors_tpu.cli append --config cdssm_toy \
      --set data.num_pages=12000 --tombstone 17,42
  python -m dnn_page_vectors_tpu.cli refresh --config cdssm_toy
  python -m dnn_page_vectors_tpu.cli migrate --config cdssm_toy
  python -m dnn_page_vectors_tpu.cli maintain --config cdssm_toy --once
  python -m dnn_page_vectors_tpu.cli trace --config cdssm_toy --query "..."
  python -m dnn_page_vectors_tpu.cli serve-metrics --config cdssm_toy
  python -m dnn_page_vectors_tpu.cli serve-metrics --config cdssm_toy --watch 2
  python -m dnn_page_vectors_tpu.cli loadtest --config cdssm_toy \
      --shape poisson --p99-ms 50 --seed 0
  python -m dnn_page_vectors_tpu.cli loadtest --config cdssm_toy \
      --transport socket --partitions 2
  python -m dnn_page_vectors_tpu.cli partition-worker --config cdssm_toy \
      --connect 127.0.0.1:9410 --partition 0 --partitions 2
  python -m dnn_page_vectors_tpu.cli lint
  python -m dnn_page_vectors_tpu.cli lint --write-baseline

Any config field is overridable with --set section.field=value; every flag
round-trips through the Config dataclasses (SURVEY.md §5.6).
"""
from __future__ import annotations

import argparse
import json
import os
from typing import Dict

from dnn_page_vectors_tpu.config import CONFIGS, get_config
from dnn_page_vectors_tpu.utils.platform import honor_jax_platforms_env

honor_jax_platforms_env()


def _parse_overrides(pairs) -> Dict[str, object]:
    out: Dict[str, object] = {}
    for pair in pairs or []:
        key, _, value = pair.partition("=")
        out[key] = value
    return out


def _prepare_store(store_dir, cfg, model_step):
    """Stale-safe store open with the configured geometry (ADVICE r4; see
    infer/vector_store.py:prepare_store)."""
    from dnn_page_vectors_tpu.infer.vector_store import prepare_store
    return prepare_store(store_dir, cfg.model.out_dim,
                         cfg.eval.store_shard_size, cfg.eval.store_dtype,
                         model_step)


def _open_index(cfg, store):
    """The IVF index for eval/mine when serve.index=ivf, or None (exact
    path) — unavailability warns and falls back rather than failing the
    command (docs/ANN.md)."""
    if cfg.serve.index != "ivf":
        return None
    from dnn_page_vectors_tpu.index.ivf import IndexUnavailable, IVFIndex
    from dnn_page_vectors_tpu.utils import faults as _faults
    try:
        return IVFIndex.open(store)
    except IndexUnavailable as e:
        _faults.warn(f"IVF index unavailable ({e}); using exact retrieval")
        return None


def _trainer(cfg):
    from dnn_page_vectors_tpu.train.loop import Trainer
    lookup = None
    if cfg.train.hard_negatives > 0:
        negs_path = os.path.join(cfg.workdir, "hard_negatives.npy")
        if os.path.exists(negs_path):
            # close the mine -> train loop (config 4): feed mined negatives
            from dnn_page_vectors_tpu.mine.ann import HardNegatives
            lookup = HardNegatives.load(negs_path)
        else:
            import sys
            print(f"WARNING: train.hard_negatives="
                  f"{cfg.train.hard_negatives} but {negs_path} does not "
                  "exist — training with in-batch negatives ONLY; run "
                  "'mine' first (or check --workdir)", file=sys.stderr)
    return Trainer(cfg, hard_negative_lookup=lookup)


def _embedder(cfg, trainer, state):
    from dnn_page_vectors_tpu.infer.bulk_embed import BulkEmbedder
    from dnn_page_vectors_tpu.parallel.multihost import inference_mesh
    # single-process: the trainer's mesh; multi-process: a process-local
    # mesh — embed/eval/mine run per-host independent (parallel/multihost.py)
    mesh = inference_mesh(cfg.mesh, trainer.mesh)
    return BulkEmbedder(cfg, trainer.model, state.params, trainer.page_tok,
                        mesh, query_tok=trainer.query_tok)


def _restore_or_init(cfg, trainer):
    """Returns (state, ckpt_manager); state is restored from the latest
    checkpoint when one exists."""
    from dnn_page_vectors_tpu.train.checkpoint import CheckpointManager
    state = trainer.init_state()
    mgr = CheckpointManager(os.path.join(cfg.workdir, "ckpt"))
    if mgr.latest_step() is not None:
        state = mgr.restore(state)
    return state, mgr


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(prog="dnn_page_vectors_tpu")
    ap.add_argument("command", choices=["train", "embed", "eval", "mine",
                                        "search", "pipeline", "configs",
                                        "init-store", "merge-store",
                                        "reset-store", "index", "append",
                                        "migrate", "refresh", "maintain",
                                        "trace",
                                        "serve-metrics", "loadtest",
                                        "partition-worker", "lint"])
    ap.add_argument("--once", action="store_true",
                    help="maintain: run ONE synchronous pass of every "
                         "pillar (janitor, compaction, rebuild) and exit "
                         "instead of looping every maintenance.interval_s")
    # -- lint (graftcheck, docs/ANALYSIS.md) -------------------------------
    ap.add_argument("--root", default=None, metavar="DIR",
                    help="lint: project root to analyze (default: this "
                         "checkout) — used by fixture tests")
    ap.add_argument("--baseline", default=None, metavar="FILE",
                    help="lint: baseline file (default: "
                         "<root>/.graftcheck-baseline.json)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="lint: accept every current finding into the "
                         "baseline file and exit 0")
    ap.add_argument("--changed", nargs="?", const="HEAD", default=None,
                    metavar="REF",
                    help="lint: fast mode — restrict file-scoped rules "
                         "to files changed vs REF (default HEAD: the "
                         "working tree) plus untracked files; "
                         "project-level drift/protocol rules still run "
                         "whole-repo (docs/ANALYSIS.md)")
    ap.add_argument("--tombstone", default=None, metavar="IDS",
                    help="append: comma-separated page ids to DELETE (their "
                         "vectors mask out of every retrieval path)")
    ap.add_argument("--update-ids", default=None, metavar="IDS",
                    help="append: comma-separated existing page ids to "
                         "RE-EMBED into the new generation (old rows "
                         "tombstoned automatically)")
    ap.add_argument("--attrs", nargs="+", default=None, metavar="K=V",
                    help="append: stamp every appended/updated row with "
                         "these attributes — lang=<0-255>, site=<string "
                         "or bucket 0-65535>, recency=<band 0-15> — "
                         "packed into one per-row attribute word "
                         "(docs/ANN.md 'Filtered retrieval'). Refuses on "
                         "a store with no attribute table unless "
                         "--init-attrs is also given")
    ap.add_argument("--init-attrs", dest="init_attrs", action="store_true",
                    help="append: initialize the store's attribute table "
                         "first (records the versioned bit-field layout "
                         "in the manifest; shards written before it read "
                         "as all-zero words)")
    ap.add_argument("--query", default=None,
                    help="search: free-text query to embed and retrieve for")
    ap.add_argument("--filter", dest="filter_expr", default=None,
                    metavar="EXPR",
                    help="search: attribute predicate every result must "
                         "match — 'lang==X', 'site in {a,b}', "
                         "'recency>=band', '&'-joined conjunctions "
                         "(docs/ANN.md 'Filtered retrieval'); applies to "
                         "--query, --queries, and --interactive")
    ap.add_argument("--queries", default=None, metavar="FILE",
                    help="search: batch mode — one query per line, routed "
                         "through search_many (bucket-filling vectorized "
                         "dispatch), one JSON result line per query")
    ap.add_argument("--interactive", action="store_true",
                    help="search: serve queries from stdin, one JSON result "
                         "line each (model + store loaded once)")
    ap.add_argument("--topk", type=int, default=None,
                    help="search: results to return (default eval.recall_k)")
    ap.add_argument("--nprobe", type=int, default=None,
                    help="search/eval/mine: IVF lists probed per query — "
                         "implies serve.index=ivf (docs/ANN.md; shorthand "
                         "for --set serve.index=ivf --set serve.nprobe=N)")
    ap.add_argument("--pq", action="store_true",
                    help="index: train OPQ+PQ compressed posting payloads "
                         "alongside the inverted file (docs/ANN.md) — "
                         "serve.pq_m subspaces, or an automatic ~dim/8 "
                         "when the knob is 0; search then runs on-device "
                         "ADC over m-byte codes with an exact re-rank")
    ap.add_argument("--rounds", type=int, default=2,
                    help="pipeline: train->embed->mine->train rounds")
    ap.add_argument("--config", default="cdssm_toy", choices=sorted(CONFIGS))
    ap.add_argument("--set", dest="overrides", action="append",
                    metavar="section.field=value")
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--steps", type=int, default=None)
    ap.add_argument("--start", type=int, default=0,
                    help="embed: first page id (store-shard aligned) — for "
                         "manual fleet sharding, one corpus slice per process")
    ap.add_argument("--stop", type=int, default=None,
                    help="embed: one-past-last page id (shard aligned)")
    ap.add_argument("--profile", action="store_true",
                    help="dump a jax.profiler trace under workdir/trace")
    ap.add_argument("--json", action="store_true",
                    help="serve-metrics: emit the JSON registry snapshot "
                         "instead of the Prometheus text exposition")
    ap.add_argument("--watch", type=float, default=None, metavar="N",
                    help="serve-metrics: re-print the live SLO snapshot "
                         "every N seconds (single-line JSON per tick) "
                         "instead of one-shot; Ctrl-C stops")
    # -- loadtest (docs/SERVING.md "SLO methodology") ----------------------
    ap.add_argument("--shape", default="poisson",
                    choices=["poisson", "burst", "closed"],
                    help="loadtest: arrival process — open-loop poisson, "
                         "open-loop on/off burst, or closed-loop workers")
    ap.add_argument("--p99-ms", dest="p99_ms", type=float, default=50.0,
                    help="loadtest: the SLO target — find the max "
                         "sustained QPS with windowed p99 under this")
    ap.add_argument("--seed", type=int, default=0,
                    help="loadtest: workload seed; the same seed replays "
                         "the identical offered-load schedule")
    ap.add_argument("--distinct", type=int, default=64,
                    help="loadtest: distinct queries under the Zipfian "
                         "repeat distribution")
    ap.add_argument("--trial-s", dest="trial_s", type=float, default=None,
                    help="loadtest: measured seconds per trial (default "
                         "obs.window_s, so the rolling window exactly "
                         "turns over)")
    ap.add_argument("--warmup-s", dest="warmup_s", type=float, default=1.0,
                    help="loadtest: per-trial warmup seconds the rolling "
                         "window ages out before the measurement")
    ap.add_argument("--start-qps", dest="start_qps", type=float, default=8.0,
                    help="loadtest: first offered load probed (workers "
                         "for --shape closed)")
    ap.add_argument("--iters", type=int, default=4,
                    help="loadtest: bisection steps after the doubling "
                         "phase brackets the p99 cliff")
    ap.add_argument("--partitions", type=int, default=None, metavar="P",
                    help="loadtest/search: serve.partitions override — "
                         "split the store into P contiguous partitions "
                         "behind the scatter-gather (docs/SCALING.md "
                         "'Partitioned serving'); the report gains a "
                         "per-partition qps/p99/shed block")
    ap.add_argument("--replicas", type=int, default=None, metavar="R",
                    help="loadtest/search: serve.replicas override — R "
                         "health-routed copies of every partition "
                         "(shorthand for --set serve.replicas=R)")
    ap.add_argument("--result-cache", dest="result_cache", default=None,
                    choices=["on", "off"],
                    help="loadtest: generation-keyed result cache A/B "
                         "switch — 'on' enables serve.result_cache (and, "
                         "with --transport socket, the fleet-shared "
                         "CACHE_LOOKUP/CACHE_PUT hop) so the report gains "
                         "a result_cache block (hits, misses, hit_rate, "
                         "bytes; docs/SERVING.md 'Result cache'); 'off' "
                         "forces it off regardless of --set overrides")
    ap.add_argument("--transport", default="inproc",
                    choices=["inproc", "socket"],
                    help="loadtest: 'socket' runs the asyncio front end "
                         "(infer/server.py) over loopback — with "
                         "partitions > 1 it also spawns one "
                         "`partition-worker` SUBPROCESS per replica — and "
                         "points the driver's issue path at the socket "
                         "client, so qps@p99 covers the full network path "
                         "(docs/SERVING.md 'Network front end')")
    ap.add_argument("--front-ends", dest="front_ends", type=int, default=1,
                    metavar="N",
                    help="loadtest: run N socket front ends over ONE "
                         "shared worker fleet (docs/SCALING.md 'Scale-out "
                         "tier') — each gets its own WorkerGateway and "
                         "listener, every worker registers with all N, "
                         "and the driver spreads load across them with a "
                         "seeded client-side balancer; the report gains a "
                         "per-front-end qps/p99 block. Requires "
                         "--transport socket when N > 1")
    ap.add_argument("--balance", default="round_robin",
                    choices=["round_robin", "least_loaded"],
                    help="loadtest: client-side balancing policy across "
                         "--front-ends (seeded by --seed so runs replay)")
    ap.add_argument("--filters", dest="lt_filters", action="store_true",
                    help="loadtest: mix seeded filtered queries into the "
                         "workload (per-scenario predicate profiles over "
                         "the Zipf repeat distribution, docs/ANN.md "
                         "'Filtered retrieval'); the report gains a "
                         "per-scenario qps/p99 block")
    # -- partition-worker (docs/SERVING.md "Network front end") ------------
    ap.add_argument("--connect", default=None, metavar="HOST:PORT",
                    help="partition-worker: the front end's WorkerGateway "
                         "address to register with — comma-separated "
                         "HOST:PORT,... registers this worker with EVERY "
                         "listed gateway (multi-front-end tier)")
    ap.add_argument("--partition", type=int, default=0, metavar="I",
                    help="partition-worker: which partition of the "
                         "--partitions-way balanced split this process "
                         "serves")
    ap.add_argument("--replica", type=int, default=0, metavar="R",
                    help="partition-worker: this process's replica id "
                         "within its partition")
    ap.add_argument("--mutate-every", dest="mutate_every", type=float,
                    default=None, metavar="S",
                    help="loadtest: hot-swap refresh() every S seconds of "
                         "trial time — measures serving UNDER live "
                         "updates (docs/UPDATES.md)")
    ap.add_argument("--mutate-mode", dest="mutate_mode", default="refresh",
                    choices=["refresh", "maintain"],
                    help="loadtest: what --mutate-every fires — 'refresh' "
                         "(the plain hot-swap) or 'maintain' (alternate "
                         "tombstones+refresh with a full maintenance pass: "
                         "compaction + background index rebuilds under "
                         "fire, docs/MAINTENANCE.md)")
    ap.add_argument("--faults", default=None, metavar="PLAN",
                    help="fault-injection plan 'op:kind:at[:count],...' "
                         "(utils/faults.py; shorthand for --set "
                         "faults.plan=...). Off by default.")
    ap.add_argument("--chaos", default=None, metavar="PLAN",
                    help="loadtest: seeded network-chaos schedule armed "
                         "under the query hammer (same grammar as "
                         "--faults, over the wire ops wire_send / "
                         "wire_recv / worker_dial / gateway_accept / "
                         "cache_peer_send and kinds conn_drop / "
                         "frame_delay / frame_trunc / frame_dup). "
                         "Installed AFTER fleet start so setup never "
                         "eats the schedule; the report gains a `chaos` "
                         "block with availability/errors/injected "
                         "counts (docs/ROBUSTNESS.md).")
    args = ap.parse_args(argv)

    if args.command == "configs":
        for name in sorted(CONFIGS):
            print(name)
        return

    if args.command == "lint":
        # graftcheck static analysis (docs/ANALYSIS.md). Dispatches before
        # any model/device/jax import on purpose: the analyzer is
        # stdlib-only and must run on a jax-less box. JSON report on
        # stdout, `file:line` diagnostics on stderr, exit 1 on any
        # non-baselined finding.
        import sys

        from dnn_page_vectors_tpu.tools import analyze as graftcheck
        root = args.root or graftcheck.REPO_ROOT
        baseline = args.baseline or os.path.join(root,
                                                 graftcheck.BASELINE_NAME)
        paths = None
        if args.changed is not None:
            # the pre-commit fast path: file rules only touch what the
            # diff touches; project rules still see the whole repo
            import subprocess as _sp
            try:
                diff = _sp.run(
                    ["git", "diff", "--name-only", args.changed, "--"],
                    capture_output=True, text=True, cwd=root, check=True)
                untracked = _sp.run(
                    ["git", "ls-files", "--others", "--exclude-standard"],
                    capture_output=True, text=True, cwd=root, check=True)
            except (OSError, _sp.CalledProcessError) as e:
                detail = getattr(e, "stderr", "") or str(e)
                print(f"lint --changed: git diff against "
                      f"{args.changed!r} failed: {detail.strip()}",
                      file=sys.stderr)
                raise SystemExit(2)
            paths = sorted(
                p for p in (diff.stdout + untracked.stdout).splitlines()
                if p.endswith(".py"))
        report = graftcheck.analyze(root=root, baseline_path=baseline,
                                    paths=paths)
        if args.write_baseline:
            graftcheck.write_baseline(
                baseline, report.findings + report.baselined)
            print(json.dumps({"baseline": baseline,
                              "entries": len(report.findings)
                              + len(report.baselined)}))
            return
        if paths is not None:
            print(f"lint --changed {args.changed}: file rules over "
                  f"{report.files_scanned} changed file(s); project "
                  "rules whole-repo", file=sys.stderr)
        for f in report.findings:
            print(f.human(), file=sys.stderr)
        for key in report.stale_baseline:
            print(f"stale baseline entry (fixed? remove it): {key}",
                  file=sys.stderr)
        print(json.dumps(report.to_dict(), sort_keys=True))
        if report.exit_code:
            raise SystemExit(report.exit_code)
        return
    if args.command == "search" and not (args.query or args.queries
                                         or args.interactive):
        ap.error("search requires --query TEXT, --queries FILE, "
                 "or --interactive")
    if args.command == "trace" and not (args.query or args.queries):
        ap.error("trace requires --query TEXT or --queries FILE")

    cfg = get_config(args.config, _parse_overrides(args.overrides))
    if args.workdir:
        cfg = cfg.replace(workdir=args.workdir)
    if args.faults is not None:
        import dataclasses as _dc
        cfg = cfg.replace(faults=_dc.replace(cfg.faults, plan=args.faults))
    if args.nprobe is not None:
        import dataclasses as _dc
        cfg = cfg.replace(serve=_dc.replace(cfg.serve, index="ivf",
                                            nprobe=args.nprobe))
    if args.partitions is not None or args.replicas is not None:
        import dataclasses as _dc
        over = {}
        if args.partitions is not None:
            over["partitions"] = max(1, args.partitions)
        if args.replicas is not None:
            over["replicas"] = max(1, args.replicas)
        cfg = cfg.replace(serve=_dc.replace(cfg.serve, **over))
    if getattr(args, "result_cache", None) is not None:
        # --result-cache on/off: the A/B switch over serve.result_cache;
        # 'on' over a socket transport also enables the fleet-shared hop
        # (FLAG_RESULT_CACHE, docs/SERVING.md "Result cache")
        import dataclasses as _dc
        rc_on = args.result_cache == "on"
        cfg = cfg.replace(serve=_dc.replace(
            cfg.serve, result_cache=rc_on,
            result_cache_fleet=bool(rc_on and args.transport == "socket")))

    # fault injection (only when a plan is configured) + the always-on
    # transient-I/O retry policy — every command goes through this
    from dnn_page_vectors_tpu.utils import faults
    faults.install_from_config(cfg)

    from dnn_page_vectors_tpu.parallel.mesh import multihost_init
    multihost_init()

    from dnn_page_vectors_tpu.infer.vector_store import VectorStore
    from dnn_page_vectors_tpu.utils.profiling import maybe_profile

    store_dir = os.path.join(cfg.workdir, "store")

    # Store-admin commands dispatch BEFORE the trainer build: they need no
    # model, tokenizer, or device — just the store directory and (for
    # init-store) the latest checkpoint step.
    if args.command == "reset-store":
        # Explicit administrative drop of all shards — the CLI escape hatch
        # for the populated-store geometry guard ("cannot switch dtype ...
        # reset() first"), so switching store_dtype/shard_size on a CURRENT
        # (non-stale) store never requires Python. Deliberately its own
        # command: init-store must not silently destroy non-stale vectors.
        store = VectorStore(store_dir)
        n = store.num_vectors
        store.reset()
        print(json.dumps({"store": store_dir, "dropped_vectors": n}))
        return

    if args.command == "merge-store":
        # Manual-fleet step 3: fold writer manifests into the main one once
        # every slice finished. (The jax.distributed path does this itself
        # behind a barrier; readers work without it either way — shards()
        # always sees the union view.)
        store = VectorStore(store_dir)
        store.merge_writers()
        print(json.dumps({"store": store_dir,
                          "shards": len(store.manifest["shards"]),
                          "vectors": store.num_vectors}))
        return

    if args.command == "index":
        # Build/rebuild the IVF ANN index over an embedded store
        # (docs/ANN.md). Needs no model or tokenizer — just the store and
        # a device mesh for the MXU k-means; an existing index is
        # overwritten (build is deterministic for a given store + seed).
        import time as _time

        from dnn_page_vectors_tpu.index.ivf import IVFIndex
        from dnn_page_vectors_tpu.index.pq import auto_pq_m
        from dnn_page_vectors_tpu.parallel.multihost import local_mesh
        store = VectorStore(store_dir)
        # --pq (or a non-zero serve.pq_m knob) turns on compressed
        # posting payloads; the flag alone picks an automatic ~dim/8
        # subspace count for the store's geometry
        pq_m = cfg.serve.pq_m
        if args.pq and not pq_m:
            pq_m = auto_pq_m(store.dim)
        t0 = _time.perf_counter()
        idx = IVFIndex.build(store, local_mesh(cfg.mesh),
                             nlist=cfg.serve.nlist,
                             iters=cfg.serve.kmeans_iters,
                             seed=cfg.data.seed,
                             init=cfg.serve.kmeans_init,
                             balance=cfg.serve.kmeans_balance,
                             pq_m=pq_m, pq_iters=cfg.serve.pq_iters,
                             opq_iters=cfg.serve.pq_opq_iters)
        # init->final imbalance delta: what the seeding bought (k-means++
        # vs the random draw it replaced; docs/ANN.md)
        init_imb = float(idx.manifest.get("init_imbalance", 0.0))
        # raw->balanced delta: what the assignment cap bought (the
        # balanced-init ROADMAP item; 0 when serve.kmeans_balance is off)
        raw_imb = float(idx.manifest.get("imbalance_raw", idx.imbalance))
        pq_sec = idx.manifest.get("pq") or {}
        print(json.dumps({
            "store": store_dir, "vectors": store.num_vectors,
            "nlist": idx.nlist, "imbalance": idx.imbalance,
            "kmeans_init": idx.manifest.get("init"),
            "imbalance_init": init_imb,
            "imbalance_delta": round(init_imb - idx.imbalance, 4),
            "balance_cap": idx.manifest.get("balance_cap", 0),
            "imbalance_raw": raw_imb,
            "imbalance_balance_delta": round(raw_imb - idx.imbalance, 4),
            "pq_m": idx.pq_m,
            "codebook_build_seconds": pq_sec.get("train_seconds"),
            "model_step": idx.model_step,
            "build_seconds": round(_time.perf_counter() - t0, 3),
            "fault_counters": faults.counters()}, sort_keys=True))
        return

    if args.command == "refresh":
        # Bring the IVF index up to date with an appended store
        # (docs/UPDATES.md): incremental posting append in O(new shards),
        # or a drift-triggered full rebuild. Needs no model — just the
        # store and a device mesh for the assignment pass. A serving
        # process picks the result up on its next SearchService.refresh()
        # (or `:refresh` in `search --interactive`).
        from dnn_page_vectors_tpu.index.ivf import IVFIndex
        from dnn_page_vectors_tpu.parallel.multihost import local_mesh
        store = VectorStore(store_dir)
        idx, info = IVFIndex.update(store, local_mesh(cfg.mesh),
                                    rebuild_drift=cfg.updates.rebuild_drift,
                                    nlist=cfg.serve.nlist,
                                    iters=cfg.serve.kmeans_iters,
                                    init=cfg.serve.kmeans_init)
        print(json.dumps({
            "store": store_dir, "vectors": store.num_vectors,
            "store_generation": store.generation,
            "nlist": idx.nlist, "imbalance": idx.imbalance,
            "index_generation": idx.index_generation,
            **info, "fault_counters": faults.counters()}, sort_keys=True))
        return

    if args.command == "maintain":
        # Background maintenance (docs/MAINTENANCE.md): generation
        # compaction once tombstone density crosses the threshold,
        # off-path IVF rebuilds (drift or structural staleness), and the
        # stale-artifact janitor. Needs no model — just the store and a
        # device mesh for the rebuild's k-means. --once runs a single
        # synchronous pass; without it the supervised workers poll every
        # maintenance.interval_s until Ctrl-C, one JSON line per pass
        # that did work.
        import sys
        import time as _time

        from dnn_page_vectors_tpu.maintenance import MaintenanceService
        from dnn_page_vectors_tpu.parallel.multihost import local_mesh
        try:
            store = VectorStore(store_dir)
        except FileNotFoundError:
            raise SystemExit(f"no store at {store_dir}; run 'embed' "
                             "before maintaining")
        ms = MaintenanceService(cfg, store.directory, local_mesh(cfg.mesh))
        if args.once:
            out = ms.run_once()
            print(json.dumps({"store": store_dir, **out,
                              "fault_counters": faults.counters()},
                             sort_keys=True))
            return
        print(json.dumps({"maintaining": store_dir,
                          "interval_s": cfg.maintenance.interval_s}),
              file=sys.stderr, flush=True)
        ms.start()     # the supervised worker pool: one thread per pillar
        seen = {}
        try:
            while True:
                _time.sleep(cfg.maintenance.interval_s)
                snap = ms.stats()
                for pillar, n in snap["passes"].items():
                    if n != seen.get(pillar):
                        seen[pillar] = n
                        print(json.dumps(
                            {pillar: snap["last"].get(pillar), "passes": n},
                            sort_keys=True), flush=True)
        except KeyboardInterrupt:
            ms.close()
        return

    if args.command == "partition-worker":
        # One partition replica as a real process (docs/SERVING.md
        # "Network front end"): opens the store, builds its restricted
        # view over the --partitions-way balanced split, registers with
        # the front end's WorkerGateway at --connect, heartbeats, and
        # answers vector RPCs over its slice until the gateway hangs up.
        # Needs NO model or checkpoint — just the store and a device mesh
        # for staging + the compiled top-k.
        if not args.connect:
            ap.error("partition-worker requires --connect HOST:PORT")
        from dnn_page_vectors_tpu.infer.partition_host import (
            run_partition_worker)
        partitions = max(1, args.partitions or 1)
        run_partition_worker(cfg, store_dir, args.connect,
                             partition=args.partition,
                             partitions=partitions, replica=args.replica)
        return

    if args.command == "init-store":
        # Manual-fleet step 1 (docs/SCALING.md): ONE invocation prepares and
        # stamps the store before N uncoordinated `embed --start/--stop`
        # processes write into it — those processes have no barrier between
        # them, so the reset-if-stale decision must happen exactly once here.
        from dnn_page_vectors_tpu.train.checkpoint import CheckpointManager
        mgr = CheckpointManager(os.path.join(cfg.workdir, "ckpt"))
        model_step = mgr.latest_step() or 0
        mgr.close()
        _prepare_store(store_dir, cfg, model_step)
        print(json.dumps({"store": store_dir, "model_step": model_step}))
        return

    trainer = _trainer(cfg)

    if args.command == "pipeline":
        # train -> embed -> mine -> continue-train rounds (SURVEY.md §4.4)
        from dnn_page_vectors_tpu.train.pipeline import run_pipeline
        state, mgr = _restore_or_init(cfg, trainer)
        steps_per_round = (args.steps if args.steps is not None
                           else max(1, cfg.train.steps // args.rounds))
        with maybe_profile(args.profile, cfg.workdir):
            out = run_pipeline(cfg, rounds=args.rounds,
                               steps_per_round=steps_per_round,
                               trainer=trainer, state=state,
                               ckpt_manager=mgr)
        mgr.save(int(out["state"].step), out["state"], wait=True)
        mgr.close()
        print(json.dumps({"rounds": args.rounds,
                          "recalls": out["recalls"]}, sort_keys=True))
        return

    if args.command == "train":
        state, mgr = _restore_or_init(cfg, trainer)
        # bare re-run after a crash completes to the CONFIGURED total (resume
        # equivalence, §5.4); --steps N explicitly means "N more steps".
        steps = (max(0, cfg.train.steps - int(state.step))
                 if args.steps is None else args.steps)
        with maybe_profile(args.profile, cfg.workdir):
            state, metrics = trainer.train(steps=steps, state=state,
                                           ckpt_manager=mgr)
        mgr.save(int(state.step), state, wait=True)
        mgr.close()
        print(json.dumps({"final": metrics}, sort_keys=True))
        return

    state, mgr = _restore_or_init(cfg, trainer)
    if mgr.latest_step() is None:
        import sys
        print(f"WARNING: no checkpoint under {cfg.workdir}/ckpt — "
              f"'{args.command}' is running with RANDOM params; "
              "run 'train' first (or check --workdir)", file=sys.stderr)
    mgr.close()
    embedder = _embedder(cfg, trainer, state)

    from dnn_page_vectors_tpu.parallel.multihost import barrier, process_info
    pi, pc = process_info()
    model_step = int(state.step)
    fleet = args.start != 0 or args.stop is not None

    if args.command == "migrate":
        # Rolling model migration (docs/MAINTENANCE.md "Rolling model
        # migration"): re-embed the EXISTING store to this checkpoint's
        # model step unit-by-unit — base shard table first, then each
        # appended generation — every unit committed with one atomic
        # manifest flip. The store stays serveable the whole sweep: a
        # SearchService over it serves dual-stamp mid-sweep and picks
        # each flip up on its next refresh(). Contrast `embed`, which
        # RESETS a stale-stamped store and starts over.
        from dnn_page_vectors_tpu.maintenance import (
            migrate_store, purge_stale)
        try:
            store = VectorStore(store_dir)
        except FileNotFoundError:
            raise SystemExit(f"no store at {store_dir}; run 'embed' "
                             "before migrating")
        out = migrate_store(store, trainer.corpus, embedder, model_step,
                            batch_rows=cfg.migrate.batch_rows)
        purged = {}
        if cfg.migrate.purge and out.get("action") == "migrated":
            purged = purge_stale(store, out)
        print(json.dumps({
            "store": store_dir,
            **{k: v for k, v in out.items()
               if k not in ("stale_files", "stale_dirs")},
            **purged, "store_generation": store.generation,
            "fault_counters": faults.counters()}, sort_keys=True))
        return

    if args.command == "embed":
        # vectors from an older checkpoint are stale, not resumable work: a
        # finished shard only counts if it came from the same model step.
        # An unstamped store with shards is ambiguous -> reset (fresh stores
        # have no shards, so resetting them is free). Under multi-process,
        # process 0 prepares/stamps the store before anyone writes. Manual
        # --start/--stop fleet slices must NOT each make that decision (no
        # barrier between them -> a late starter could reset a sibling's
        # fresh shards), so they require a prior `init-store` run instead —
        # and read the store's stamped geometry rather than their own
        # eval.store_shard_size (a slice launched with a divergent override
        # must not silently re-shape the shared store).
        writer = None
        if fleet:
            try:
                store = VectorStore(store_dir)
            except FileNotFoundError:
                raise SystemExit(
                    f"no store at {store_dir}; run 'init-store' once before "
                    "launching --start/--stop embed slices")
            if store.manifest.get("model_step") != model_step:
                raise SystemExit(
                    f"store at {store_dir} is stamped for model step "
                    f"{store.manifest.get('model_step')} but the checkpoint "
                    f"is at {model_step}; run 'init-store' once before "
                    "launching --start/--stop embed slices")
            # writer id: the slice's first shard index (disjoint ranges ->
            # disjoint writer manifests; see VectorStore multi-writer notes)
            writer = args.start // store.manifest["shard_size"]
        elif pi == 0:
            _prepare_store(store_dir, cfg, model_step)
        barrier("store_ready")
        if pc > 1:
            writer = pi          # the jax.distributed multi-writer path
        store = VectorStore(store_dir, dim=cfg.model.out_dim,
                            writer_id=writer)
        # per-stage pipeline breakdown (produce_wait/read/tokenize/h2d/
        # compute/d2h/write) in the final JSON: the operator sees WHICH
        # stage binds the sweep, not just the end-to-end rate
        from dnn_page_vectors_tpu.utils.profiling import PipelineProfiler
        prof = PipelineProfiler()
        with maybe_profile(args.profile, cfg.workdir):
            embedder.embed_corpus(trainer.corpus, store,
                                  start=args.start, stop=args.stop,
                                  profiler=prof)
        if pi == 0:
            print(json.dumps({"embedded": store.num_vectors,
                              "model_step": model_step,
                              "tokenize_workers": cfg.data.tokenize_workers,
                              "stages": prof.summary(),
                              "fault_counters": faults.counters()}))
    elif args.command == "append":
        # Live corpus update (docs/UPDATES.md): embed everything past the
        # store's append cursor — grow the corpus first, e.g.
        # --set data.num_pages=<new total> — into a fresh generation, with
        # optional deletions (--tombstone) and in-place page updates
        # (--update-ids), then bring the IVF index up to date when one
        # exists. Serving processes pick the generation up via refresh().
        if pc > 1:
            raise SystemExit("append is a single-process job (one "
                             "generation writer); run it on one host")
        from dnn_page_vectors_tpu.updates import append_corpus
        from dnn_page_vectors_tpu.utils import telemetry
        from dnn_page_vectors_tpu.utils.logging import MetricsLogger
        try:
            store = VectorStore(store_dir)
        except FileNotFoundError:
            raise SystemExit(f"no store at {store_dir}; run 'embed' before "
                             "appending")
        if store.manifest.get("model_step") != model_step:
            raise SystemExit(
                f"store at {store_dir} is stamped for model step "
                f"{store.manifest.get('model_step')} but the checkpoint is "
                f"at {model_step}; appended vectors must share the base "
                "params — re-run 'embed' (full re-embed) instead")
        tomb = [int(x) for x in (args.tombstone or "").split(",")
                if x.strip()]
        upd = [int(x) for x in (args.update_ids or "").split(",")
               if x.strip()]
        attr_word = None
        if args.init_attrs:
            store.init_attrs()
        if args.attrs:
            from dnn_page_vectors_tpu.index import attrs as attrs_mod
            try:
                attr_word = attrs_mod.parse_attr_assignments(args.attrs)
            except attrs_mod.FilterError as e:
                raise SystemExit(f"bad --attrs: {e}")
            if not store.attrs_enabled:
                raise SystemExit(
                    f"store at {store_dir} has no attribute table; pass "
                    "--init-attrs once to create it (older shards then "
                    "read as all-zero attribute words), or drop --attrs")
        with maybe_profile(args.profile, cfg.workdir):
            stats = append_corpus(
                embedder, trainer.corpus, store, tombstone=tomb,
                update_ids=upd, attrs=attr_word,
                log=MetricsLogger(cfg.workdir, echo=False,
                                  registry=telemetry.default_registry()))
        index_info = None
        from dnn_page_vectors_tpu.index.ivf import (
            MANIFEST as _IVF_MANIFEST, IVFIndex, index_dir)
        if cfg.updates.auto_update_index and os.path.exists(
                os.path.join(index_dir(store), _IVF_MANIFEST)):
            try:
                _, index_info = IVFIndex.update(
                    store, embedder.mesh,
                    rebuild_drift=cfg.updates.rebuild_drift,
                    nlist=cfg.serve.nlist, iters=cfg.serve.kmeans_iters,
                    init=cfg.serve.kmeans_init)
            except Exception as e:  # append succeeded; index refresh didn't
                index_info = {"error": f"{type(e).__name__}: {e}"}
        print(json.dumps({"store": store_dir,
                          "store_generation": store.generation,
                          "store_vectors": store.num_vectors, **stats,
                          "index_update": index_info,
                          "fault_counters": faults.counters()},
                         sort_keys=True))
    elif args.command == "eval":
        from dnn_page_vectors_tpu.evals.recall import evaluate_recall
        store = VectorStore(store_dir)
        index = _open_index(cfg, store)
        recall, nq = evaluate_recall(embedder, trainer.corpus, store,
                                     k=cfg.eval.recall_k, index=index,
                                     nprobe=cfg.serve.nprobe)
        if pi == 0:
            print(json.dumps({f"recall@{cfg.eval.recall_k}": recall,
                              "num_queries": nq,
                              "index": ("ivf" if index is not None
                                        else "exact")}, sort_keys=True))
    elif args.command == "search":
        # query-time retrieval over the embedded store (the serving half of
        # call stack §4.3): SearchService loads everything once — params on
        # device, store pre-staged in HBM when it fits — so --interactive
        # answers a stream of queries at per-query encode+top-k cost
        # (VERDICT r3 Weak #6: the old per-invocation cold start is now
        # only paid once).
        if pi != 0:
            # a query service is one host's job; the inference mesh is
            # process-local (no cross-process collectives), so other
            # processes simply exit instead of idling on stdin
            return
        from dnn_page_vectors_tpu.infer.serve import SearchService
        store = VectorStore(store_dir)
        store_step = store.manifest.get("model_step")
        if store_step != int(state.step):
            import sys
            print(f"WARNING: store embedded at model step {store_step} but "
                  f"the restored checkpoint is at step {int(state.step)} — "
                  "query and page vectors come from DIFFERENT params; "
                  "re-run 'embed' for meaningful rankings", file=sys.stderr)
        k = args.topk or cfg.eval.recall_k
        # one-shot queries stream shard-at-a-time (a full HBM preload for a
        # single answer is waste); --interactive / --queries pre-stage the
        # store (a batch file or a stdin session amortizes the staging)
        from dnn_page_vectors_tpu.utils import telemetry
        from dnn_page_vectors_tpu.utils.logging import MetricsLogger
        preload = 4.0 if (args.interactive or args.queries) else 0.0
        svc = SearchService(
            cfg, embedder, trainer.corpus, store, preload_hbm_gb=preload,
            log=MetricsLogger(cfg.workdir, echo=False,
                              registry=telemetry.default_registry()))
        if args.queries:
            # batch mode: every line is a query; the whole file goes through
            # ONE search_many (bucket-filling tiled dispatch), one JSON
            # result line per query in input order
            with open(args.queries) as f:
                queries = [ln.strip() for ln in f if ln.strip()]
            results = svc.search_many(queries, k=k,
                                      filters=args.filter_expr)
            for query, res in zip(queries, results):
                print(json.dumps({"query": query, "results": res}),
                      flush=True)
            svc.close()     # flushes cache/stage counters to the metrics log
        elif args.interactive:
            import sys
            svc.warmup(k=k)
            print(json.dumps({"ready": True, "vectors": store.num_vectors,
                              "hbm_resident": svc.preloaded,
                              "degraded": svc.degraded,
                              "fault_counters": faults.counters(),
                              "latency_ms": round(svc.warm_latency_ms, 3)}),
                  flush=True)
            for line in sys.stdin:
                query = line.strip()
                if not query:
                    continue
                if query == ":refresh":
                    # zero-downtime hot-swap to the store's current
                    # generation (after an out-of-process `append`):
                    # in-flight queries finish on the old view
                    print(json.dumps({"refreshed": svc.refresh()},
                                     sort_keys=True), flush=True)
                    continue
                if query == ":metrics":
                    # live JSON snapshot of the serving registry (docs/
                    # OBSERVABILITY.md): flat metrics + typed instruments
                    # with windowed qps/p99 + the lifecycle event ring
                    print(json.dumps(svc.metrics_snapshot(),
                                     sort_keys=True), flush=True)
                    continue
                print(json.dumps({"query": query,
                                  "results": svc.search(
                                      query, k=k,
                                      filters=args.filter_expr)}),
                      flush=True)
            svc.close()
        else:
            print(json.dumps({"query": args.query,
                              "degraded": svc.degraded,
                              "results": svc.search(
                                  args.query, k=k,
                                  filters=args.filter_expr)}))
    elif args.command == "loadtest":
        # SLO harness (docs/SERVING.md "SLO methodology"): replay a seeded
        # traffic shape against a live micro-batched service and
        # binary-search offered load for the max sustained QPS meeting the
        # windowed-p99 target. Every reported number is read from the
        # telemetry registry; trial progress streams to stderr as
        # single-line JSON (the serve-metrics --watch format), the final
        # report is ONE JSON line on stdout.
        if pi != 0:
            return
        import sys

        from dnn_page_vectors_tpu.infer.serve import SearchService
        from dnn_page_vectors_tpu.loadgen import (
            Mutator, find_qps_at_p99, make_workload)
        store = VectorStore(store_dir)
        svc = SearchService(cfg, embedder, trainer.corpus, store,
                            preload_hbm_gb=4.0)
        k = args.topk or cfg.eval.recall_k
        svc.warmup(k=k)
        svc.start_batcher()
        n_fe = max(1, int(args.front_ends))
        if n_fe > 1 and args.transport != "socket":
            raise SystemExit("--front-ends N > 1 requires --transport "
                             "socket (the balancer spreads load across N "
                             "listeners; an in-process service has none)")
        client = None
        fe_svcs = [svc]
        net_servers = []
        gateways = []
        clients = []
        worker_procs = []
        if args.transport == "socket":
            # the over-the-wire path (docs/SERVING.md "Network front
            # end"): asyncio front end over loopback; with partitions a
            # WorkerGateway + one partition-worker SUBPROCESS per
            # replica, so the measured qps@p99 crosses real process
            # boundaries and the RPC fan-out (hedging, liveness routing)
            import subprocess
            import sys as _sys

            from dnn_page_vectors_tpu.infer.partition_host import (
                WorkerGateway)
            from dnn_page_vectors_tpu.infer.server import (
                serve_in_background)
            from dnn_page_vectors_tpu.infer.transport import (
                SocketSearchClient)
            from dnn_page_vectors_tpu.loadgen import BalancedClient
            for _fe in range(1, n_fe):
                # extra front ends (docs/SCALING.md "Scale-out tier"):
                # each is a full SearchService over the SAME store with
                # its own gateway + listener; the shared worker fleet
                # below registers with every one of them
                fe = SearchService(cfg, embedder, trainer.corpus, store,
                                   preload_hbm_gb=4.0)
                fe.warmup(k=k)
                fe.start_batcher()
                fe_svcs.append(fe)
            if svc.partition_set is not None:
                for fe in fe_svcs:
                    gw = WorkerGateway(fe)
                    fe.attach_gateway(gw)
                    gateways.append(gw)
                P = svc.partition_set.partitions
                R = svc.partition_set.replicas
                connect = ",".join(f"{gw.host}:{gw.port}"
                                   for gw in gateways)
                base_cmd = [_sys.executable, "-m",
                            "dnn_page_vectors_tpu.cli", "partition-worker",
                            "--config", args.config,
                            "--workdir", cfg.workdir,
                            "--connect", connect,
                            "--partitions", str(P)]
                for pair in args.overrides or []:
                    base_cmd += ["--set", pair]
                if args.result_cache is not None:
                    # the --result-cache A/B must reach the worker
                    # subprocesses too — they advertise
                    # FLAG_RESULT_CACHE at REGISTER off their own config
                    base_cmd += [
                        "--set",
                        f"serve.result_cache={cfg.serve.result_cache}",
                        "--set", "serve.result_cache_fleet="
                                 f"{cfg.serve.result_cache_fleet}"]
                for wp in range(P):
                    for wr in range(R):
                        worker_procs.append(subprocess.Popen(
                            base_cmd + ["--partition", str(wp),
                                        "--replica", str(wr)],
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL))
                for fe_i, gw in enumerate(gateways):
                    if not gw.wait_for_workers(P * R, timeout_s=120.0):
                        print(json.dumps({
                            "warning": "not every partition worker "
                                       "registered in time; unserved "
                                       "partitions fall back to local "
                                       "views",
                            "front_end": fe_i,
                            "workers_live": len(gw.live_workers()),
                            "expected": P * R}), file=sys.stderr,
                            flush=True)
            for fe_i, fe in enumerate(fe_svcs):
                net_servers.append(serve_in_background(fe,
                                                       front_end=fe_i))
            for ns in net_servers:
                clients.append(SocketSearchClient(
                    ns.host, ns.port,
                    deadline_ms=cfg.serve.deadline_ms,
                    compress=cfg.serve.wire_compress,
                    result_cache=bool(cfg.serve.result_cache
                                      and cfg.serve.result_cache_fleet)))
            client = (clients[0] if n_fe == 1 else
                      BalancedClient(clients, policy=args.balance,
                                     seed=args.seed))
        distinct = max(1, args.distinct)
        queries = [trainer.corpus.query_text(i) for i in range(distinct)]
        scen = None
        if args.lt_filters:
            # seeded filtered-query mix (docs/ANN.md "Filtered
            # retrieval"): the default scenario predicates all match the
            # all-zero attribute word, so the filtered path exercises
            # even on a store whose shards predate init_attrs()
            from dnn_page_vectors_tpu.loadgen.workload import (
                DEFAULT_FILTER_SCENARIOS)
            scen = DEFAULT_FILTER_SCENARIOS
        wl = make_workload(args.shape, seed=args.seed, distinct=distinct,
                           profile=((k, None, 1.0),),
                           filter_scenarios=scen)
        maint = None
        if args.mutate_every and args.mutate_mode == "maintain":
            # maintenance under fire (docs/MAINTENANCE.md): alternate a
            # tombstone burst + hot-swap refresh with a full maintenance
            # pass, so the measured p99 covers compaction and background
            # index rebuilds actually running — lower
            # maintenance.compact_tombstone_density via --set to make
            # compaction fire within a short test
            from dnn_page_vectors_tpu.updates import append_corpus
            maint = svc.start_maintenance(threads=False)
            n_base = max(store.num_vectors, 1)
            tomb_state = {"next": 0}
            tomb_chunk = max(16, n_base // 64)

            def _tombstone_refresh():
                ids = sorted({(tomb_state["next"] + i) % n_base
                              for i in range(tomb_chunk)})
                tomb_state["next"] = (tomb_state["next"]
                                      + tomb_chunk) % n_base
                append_corpus(embedder, trainer.corpus, svc.store,
                              tombstone=ids)
                svc.refresh()

            mut = Mutator(ops=[("tombstone_refresh", _tombstone_refresh),
                               ("maintain", maint.run_once)],
                          period_s=args.mutate_every)
        elif args.mutate_every:
            mut = Mutator(svc.refresh, period_s=args.mutate_every)
        else:
            mut = None
        trial_s = (args.trial_s if args.trial_s is not None
                   else cfg.obs.window_s)
        if args.chaos:
            # arm the seeded chaos schedule only NOW — store build, fleet
            # start, and registration must not eat the plan's scheduled
            # calls (docs/ROBUSTNESS.md "Availability drills")
            faults.install(faults.FaultPlan.parse(args.chaos,
                                                  seed=cfg.faults.seed))
        report = find_qps_at_p99(
            svc, wl, queries, p99_target_ms=args.p99_ms,
            start=args.start_qps, iters=args.iters, duration_s=trial_s,
            warmup_s=args.warmup_s, mutator=mut, client=client,
            progress=lambda line: print(line, file=sys.stderr, flush=True),
            progress_every_s=max(1.0, trial_s / 2.0),
            front_ends=fe_svcs if n_fe > 1 else None)
        if args.transport == "socket":
            final_met = svc.metrics()
            report.update({
                "transport": "socket",
                "listen": ",".join(f"{ns.host}:{ns.port}"
                                   for ns in net_servers),
                **({"transport_totals": final_met["transport"]}
                   if "transport" in final_met else {}),
            })
            if n_fe > 1:
                report["front_ends"] = n_fe
                report["balance_policy"] = args.balance
        if args.lt_filters:
            # per-scenario qps/p99 rides every trial record
            # (loadgen/driver.py "filter_scenarios"); the headline marker
            # here just says the mix was armed
            report["filters"] = [
                {"scenario": name, "predicate": pred, "weight": w}
                for name, pred, w in scen]
        if cfg.serve.result_cache:
            # result-cache block (docs/SERVING.md "Result cache"): run
            # totals straight off the registry — per-trial deltas ride
            # each trial record (loadgen/driver.py)
            rc_met = svc.metrics()
            if "result_cache" in rc_met:
                report["result_cache"] = rc_met["result_cache"]
        if maint is not None:
            final_met = svc.metrics()
            report.update({
                "mutate_mode": args.mutate_mode,
                "maintenance": maint.stats(),
                "full_rebuilds": final_met["full_rebuilds"],
                "tombstone_density": final_met["tombstone_density"],
                "reclaimable_bytes": final_met["reclaimable_bytes"],
            })
        if svc.partition_set is not None:
            # partitioned topology + routing health (docs/SCALING.md):
            # per-partition qps/p99/shed/degraded-serve counts, plus the
            # service-level routing counters
            part_met = svc.metrics()
            report.update({
                "serve_partitions": part_met["serve_partitions"],
                "serve_replicas": part_met["serve_replicas"],
                "replica_shed": part_met["replica_shed"],
                "partition_degraded": part_met["partition_degraded"],
                "partitions": part_met["partitions"],
            })
        if args.chaos:
            # the availability drill's verdict: fraction of offered
            # queries ANSWERED (sheds excluded both sides — a shed is
            # deliberate backpressure, not lost availability)
            trials = report.get("trials", [])
            sent = sum(t.get("requests_sent", 0) for t in trials)
            errs = sum(t.get("errors", 0) for t in trials)
            sheds = sum(t.get("transport", {}).get("client_sheds", 0)
                        for t in trials)
            offered = max(sent - sheds, 1)
            report["chaos"] = {
                "plan": args.chaos,
                "offered": sent,
                "sheds": sheds,
                "errors": errs,
                "availability": round(
                    max(sent - sheds - errs, 0) / offered, 6),
                "injected": {key: v for key, v in faults.counters().items()
                             if key.startswith("injected_")
                             or key == "worker_reconnect"},
            }
        for c in clients:
            c.close()
        for ns in net_servers:
            ns.close()
        for proc in worker_procs:
            proc.terminate()
        for proc in worker_procs:
            try:
                proc.wait(timeout=10)
            except Exception:  # noqa: BLE001 — a stuck worker gets killed
                proc.kill()
        for gw in gateways:
            gw.close()
        for fe in fe_svcs[1:]:
            fe.close()
        svc.close()
        report.update({
            "store_vectors": store.num_vectors,
            "query_batch": svc.query_batch,
            "k": k,
            "serve_index": cfg.serve.index,
            "batch_window_adaptive": cfg.serve.batch_window_adaptive,
            "batch_window_ms": round(svc.batch_window_ms, 3),
            "recompiles": svc.recompiles,
            "warm_latency_ms": round(svc.warm_latency_ms, 3),
            "fault_counters": faults.counters(),
        })
        print(json.dumps(report))
    elif args.command in ("trace", "serve-metrics"):
        # Observability endpoints (docs/OBSERVABILITY.md). `trace` runs the
        # given queries under request-scoped tracing and exports the span
        # trees as Chrome/Perfetto trace_event JSON; `serve-metrics` probes
        # the service once and prints the Prometheus text exposition (or
        # the JSON registry snapshot with --json).
        if pi != 0:
            return
        from dnn_page_vectors_tpu.infer.serve import SearchService
        store = VectorStore(store_dir)
        svc = SearchService(cfg, embedder, trainer.corpus, store,
                            preload_hbm_gb=0.0)
        k = args.topk or cfg.eval.recall_k
        if args.command == "serve-metrics":
            # one probe query so rate/latency instruments expose live
            # numbers, not an all-zero registry
            svc.search_many([trainer.corpus.query_text(0)], k=k)
            if args.watch:
                # live mode: one single-line JSON tick of the windowed SLO
                # view every N seconds (the same line format the loadtest
                # driver emits as trial progress); Ctrl-C exits clean
                import time as _time

                from dnn_page_vectors_tpu.loadgen import snapshot_line
                try:
                    while True:
                        print(snapshot_line(svc), flush=True)
                        _time.sleep(args.watch)
                except KeyboardInterrupt:
                    pass
                return
            if args.json:
                print(json.dumps(svc.metrics_snapshot(), sort_keys=True))
            else:
                print(svc.prometheus_text(), end="")
            return
        if args.queries:
            with open(args.queries) as f:
                queries = [ln.strip() for ln in f if ln.strip()]
        else:
            queries = [args.query]
        for query in queries:       # one trace (and one span tree) each
            svc.search_many([query], k=k)
        out_path = os.path.join(cfg.workdir, "trace_events.json")
        with open(out_path, "w") as f:
            json.dump(svc.tracer.chrome_trace(), f)
        print(json.dumps({
            "trace_file": out_path,
            "traces": len(svc.tracer.traces()),
            "spans": len(svc.tracer.chrome_trace()["traceEvents"]),
            "slow_queries": len(svc.tracer.slow_queries()),
            "slow_ms": cfg.obs.slow_ms}, sort_keys=True))
    elif args.command == "mine":
        from dnn_page_vectors_tpu.mine.ann import mine_hard_negatives
        store = VectorStore(store_dir)
        index = _open_index(cfg, store)
        out = os.path.join(cfg.workdir, "hard_negatives.npy")
        # out_path at any process count: the miner's writer-slice protocol
        # keeps peak host memory O(query_block) and barriers internally
        negs = mine_hard_negatives(embedder, trainer.corpus, store,
                                   num_negatives=cfg.train.hard_negatives or 7,
                                   out_path=out, index=index,
                                   nprobe=cfg.serve.nprobe)
        if pi == 0:
            print(json.dumps({"mined": list(negs.table.shape), "path": out,
                              "index": ("ivf" if index is not None
                                        else "exact")}))


if __name__ == "__main__":
    main()
