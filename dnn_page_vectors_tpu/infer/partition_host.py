"""Multi-process partition serving: workers, registration, hedged fan-out
(docs/SERVING.md "Network front end", docs/SCALING.md "Partitioned
serving").

PR 12 made partitions an abstraction (`infer/partition.py`): P x R
host-simulated worker THREADS, each owning a `_ServeView` over its
`PartitionSpec` slice. This module puts each replica behind a real
process and socket boundary:

  * `PartitionWorker` — one partition replica as its own process (or, in
    tests, a thread with its own service instance): opens the store,
    builds ONE restricted view over its spec's contiguous shard range
    (the same `SearchService._build_view` the in-process replicas use, so
    results are byte-identical by construction), connects to the front
    end's `WorkerGateway`, REGISTERs, heartbeats, and answers `T_VQUERY`
    frames with `_topk_view` over its slice. `cli partition-worker` is
    the process entry point.
  * `WorkerGateway` — the front-end side: a plain-socket listener where
    workers register, one reader thread per worker demultiplexing
    responses by request id, and the scatter itself — `topk()` fans the
    coalesced query block out to one routed worker per partition (routing
    still goes through `PartitionSet._route`, which now sees worker
    LIVENESS: a dead worker's replica sheds with reason "liveness"
    exactly like a restaging one sheds in-process).

Tail-latency control:

  * **per-partition deadlines** — the fan-out budgets each RPC against
    the coalesced batch's tightest deadline (relative remaining ms on the
    wire; the worker re-anchors on its own clock).
  * **hedged requests** — when a partition's answer has not arrived
    within the `serve.hedge_quantile` quantile of that partition's
    observed RPC latency, the SAME request fires at a sibling replica's
    worker and the first answer wins (`serve.hedge_fired` counter,
    `hedge_fired` event). Hedging needs a latency history (>= 8 samples)
    — a cold gateway never hedges on guesses.
  * **local fallback** — a worker that is dead, times out, or tears its
    response degrades EXACTLY like the in-process shed path: the gateway
    computes that partition's slice on the front end's own view
    (`_topk_view` over the identical shard range), so a kill -9 or a
    truncated frame can change latency but never bytes — the result-set
    identity pin extends over the wire.

Liveness: a worker is alive while its registration connection is open
and its last heartbeat is younger than 2 x `serve.heartbeat_s`.
Connection EOF / torn frames mark it lost immediately (`worker_lost`
event) and fail its in-flight RPCs over to the fallback path — recovery
is bounded by one heartbeat interval even for a silently hung peer.

Self-healing (docs/ROBUSTNESS.md "Network failure model"): a lost
worker is no longer gone for good — `PartitionWorker.run` is a
supervised loop that re-dials with exponential backoff + jitter
(`serve.reconnect_base_s` / `serve.reconnect_max_s`) and re-REGISTERs
with its current generation; the gateway re-admits it (`worker_rejoined`
event) and nudges a generation-lagging rejoiner with T_REFRESH so it
serves nothing stale. Gateway-side, each replica slot carries a
persistent circuit breaker (`serve.breaker_*`): K consecutive wire
failures open it and routing skips the replica (straight to local
fallback, no per-request timeout) until a half-open probe succeeds.
"""
from __future__ import annotations

import dataclasses
import json
import os
import random
import socket
import threading
import time
from collections import OrderedDict
from concurrent.futures import FIRST_COMPLETED, Future
from concurrent.futures import wait as futures_wait
from typing import Dict, List, Optional, Tuple

import numpy as np

from dnn_page_vectors_tpu.infer import transport
from dnn_page_vectors_tpu.utils import faults
from dnn_page_vectors_tpu.infer.transport import (
    DeadlineExceeded, FrameError, FLAG_FILTERS, FLAG_RESULT_CACHE,
    FLAG_WIRE_COMPRESS, FrameSender, InternTable, RemoteError, T_BYE,
    T_DRAIN, T_HEARTBEAT, T_HELLO, T_REFRESH, T_REGISTER, T_RESULT,
    T_RESULT_C, T_SHED, T_ERROR, T_VQUERY, T_VQUERY_PUT, T_VQUERY_REF)
from dnn_page_vectors_tpu.ops.topk import merge_partition_topk
from dnn_page_vectors_tpu.utils.profiling import LatencyStats


class MeshEmbedder:
    """The model-free embedder stub a partition worker serves with: the
    serving top-k only needs the device mesh (staging + compiled top-k);
    tokenize/encode never run on the vector RPC path."""

    def __init__(self, mesh):
        self.mesh = mesh
        self.query_tok = None
        self.page_tok = None


class _WorkerConn:
    """Front-end-side record of one registered partition worker."""

    def __init__(self, sock: socket.socket, addr, partition: int,
                 replica: int, pid: int, flags: int = 0,
                 generation: int = 0):
        self.sock = sock
        self.addr = addr
        self.partition = int(partition)
        self.replica = int(replica)
        self.pid = int(pid)
        self.flags = int(flags)            # negotiated caps, set once
        self.wlock = threading.Lock()      # serializes frame writes
        # send-path state shared with the writer: the reused encode
        # buffer and the query-block intern ring both live under wlock
        self.sender = FrameSender(sock)    # guarded-by: wlock
        self.intern = InternTable()        # guarded-by: wlock
        self._lock = threading.Lock()
        self._last_beat = time.perf_counter()   # guarded-by: _lock
        self._dead = False                       # guarded-by: _lock
        self._lost_reason: Optional[str] = None  # guarded-by: _lock
        self._generation = int(generation)       # guarded-by: _lock
        # the partition-split width this worker's REFRESH ack says its
        # view was built over; None until the first ack lands (a
        # pre-elastic worker never reports one). Elastic routing gates
        # on it exactly like it gates on generation — a worker on the
        # wrong split serves NOTHING until it re-splits, so one result
        # set can never mix splits across the wire.
        self._split: Optional[int] = None        # guarded-by: _lock
        # a draining worker announced T_DRAIN: routing stops sending it
        # new work (its slice falls back to the local view) and the
        # elastic fleet width no longer counts it
        self._draining = False                   # guarded-by: _lock

    def beat(self) -> None:
        with self._lock:
            self._last_beat = time.perf_counter()

    def alive(self, max_age_s: float) -> bool:
        with self._lock:
            if self._dead:
                return False
            return (time.perf_counter() - self._last_beat) <= max_age_s

    def mark_dead(self, reason: str) -> bool:
        """-> True exactly once (the caller that transitions it emits the
        worker_lost event)."""
        with self._lock:
            if self._dead:
                return False
            self._dead = True
            self._lost_reason = reason
            return True

    @property
    def dead(self) -> bool:
        with self._lock:
            return self._dead

    @property
    def generation(self) -> int:
        with self._lock:
            return self._generation

    def set_generation(self, gen: int,
                       split: Optional[int] = None) -> None:
        with self._lock:
            self._generation = int(gen)
            if split is not None and split > 0:
                self._split = int(split)

    @property
    def split(self) -> Optional[int]:
        with self._lock:
            return self._split

    def set_draining(self) -> bool:
        """-> True exactly once (the transitioning caller emits the
        worker_draining event and triggers the elastic shrink)."""
        with self._lock:
            if self._draining:
                return False
            self._draining = True
            return True

    @property
    def draining(self) -> bool:
        with self._lock:
            return self._draining


class WorkerGateway:
    """The front end's worker registry + RPC fan-out (one per service).

    Workers connect to `port` and REGISTER; the gateway reads heartbeats
    and responses off each connection on a dedicated reader thread and
    exposes `topk()` — the over-the-wire scatter `SearchService` routes
    through when attached (`svc.attach_gateway(gw)`)."""

    def __init__(self, svc, pset=None, host: str = "127.0.0.1",
                 port: int = 0, heartbeat_s: Optional[float] = None,
                 hedge_quantile: Optional[float] = None,
                 rpc_timeout_s: float = 10.0):
        self._svc = svc
        serve_cfg = getattr(svc.cfg, "serve", None)
        self.heartbeat_s = (heartbeat_s if heartbeat_s is not None
                            else getattr(serve_cfg, "heartbeat_s", 0.5)
                            if serve_cfg is not None else 0.5)
        self.hedge_quantile = (
            hedge_quantile if hedge_quantile is not None
            else getattr(serve_cfg, "hedge_quantile", 0.95)
            if serve_cfg is not None else 0.95)
        # serve.wire_compress: what THIS end confirms when a worker
        # advertises compression at REGISTER; off = the whole fleet
        # talks raw frames regardless of worker capability
        self._compress = bool(getattr(serve_cfg, "wire_compress", True)
                              if serve_cfg is not None else True)
        # fleet result cache (docs/SERVING.md "Result cache"): what THIS
        # end confirms when a worker advertises FLAG_RESULT_CACHE — the
        # worker then answers repeated vector blocks from its per-hop
        # block cache instead of re-scanning
        self._rcache = bool(
            serve_cfg is not None
            and getattr(serve_cfg, "result_cache", False)
            and getattr(serve_cfg, "result_cache_fleet", False))
        # filtered retrieval (docs/ANN.md "Filtered retrieval"): what
        # THIS end confirms when a worker advertises FLAG_FILTERS — a
        # filtered scatter only routes a partition to a worker that
        # negotiated the flag; everyone else's slice serves locally
        self._filters = bool(getattr(serve_cfg, "filters", True)
                             if serve_cfg is not None else True)
        # per-replica circuit breakers (docs/ROBUSTNESS.md "Network
        # failure model"): serve.breaker_failures consecutive wire
        # failures open a replica's breaker and routing skips it until a
        # half-open probe succeeds; <= 0 disables breakers entirely
        self._breaker_failures = int(
            getattr(serve_cfg, "breaker_failures", 3)
            if serve_cfg is not None else 3)
        self._breaker_open_s = float(
            getattr(serve_cfg, "breaker_open_s", 0.25)
            if serve_cfg is not None else 0.25)
        self._breaker_max_s = float(
            getattr(serve_cfg, "breaker_max_s", 30.0)
            if serve_cfg is not None else 30.0)
        # serve.elastic (docs/SCALING.md "Scale-out tier"): fleet
        # membership drives the partition split. A worker joining at the
        # next tail index widens the split (deterministic
        # partition_shard_ranges re-cut), a draining tail worker shrinks
        # it — both through the same generation-gated REFRESH handoff a
        # store swap uses, so no result set ever mixes splits. Off (the
        # default), the split is fixed at boot exactly as before.
        self._elastic = bool(getattr(serve_cfg, "elastic", False)
                             if serve_cfg is not None else False)
        self.rpc_timeout_s = float(rpc_timeout_s)
        self._own_pset = None
        if pset is None:
            pset = svc.partition_set
        if pset is None:
            # single-view service: fan out through a 1-partition set the
            # gateway owns (routing/health state lives there) — the P=1
            # over-the-wire topology is a worker, not a special case
            from dnn_page_vectors_tpu.infer.partition import PartitionSet
            self._own_pset = pset = PartitionSet(svc, svc.store,
                                                 partitions=1, replicas=1)
        self.partition_set = pset
        self._lock = threading.Lock()
        # registry lock over per-worker connection state: stats() reads
        # worker liveness (the _WorkerConn._lock property) while holding
        # the registry lock, never the reverse (graftcheck lock-order)
        # lock-order: WorkerGateway._lock < _WorkerConn._lock
        self._workers: Dict[Tuple[int, int], _WorkerConn] = {}  # guarded-by: _lock
        # breakers OUTLIVE their _WorkerConn: keyed by replica slot, so
        # trip history spans re-registrations (the breaker itself locks
        # its own state; only the dict is registry state)
        self._breakers: Dict[Tuple[int, int], faults.CircuitBreaker] = {}  # guarded-by: _lock
        self._pending: Dict[int, Tuple[Future, _WorkerConn]] = {}  # guarded-by: _lock
        self._lat: Dict[int, LatencyStats] = {}   # guarded-by: _lock
        self._registered = 0                      # guarded-by: _lock
        self._rpcs = 0                            # guarded-by: _lock
        self._rpc_fallbacks = 0                   # guarded-by: _lock
        self._resplits = 0                        # guarded-by: _lock
        self._wait_timeouts = 0                   # guarded-by: _lock
        self._closed = False                      # guarded-by: _lock
        self._threads: List[threading.Thread] = []   # guarded-by: _lock
        # serializes elastic re-splits (a join and a drain landing
        # together must re-cut once, not interleave two resizes). Held
        # OUTSIDE the registry lock and the service's refresh lock: the
        # membership snapshot is taken under _lock and released before
        # the resize starts, and the resize itself runs under the same
        # svc._refresh_lock a store refresh uses, so a refresh and a
        # re-split can never interleave their view swaps.
        # lock-order: WorkerGateway._resplit_lock < SearchService._refresh_lock
        # lock-order: SearchService._refresh_lock < WorkerGateway._lock
        self._resplit_lock = threading.Lock()
        # the listener socket and the accept-thread handle are OWNER
        # state: bound here, closed/joined only by close() — reader
        # threads never touch them
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, int(port)))
        self._sock.listen(64)
        self.host, self.port = self._sock.getsockname()[:2]
        self._accept_t = threading.Thread(target=self._accept_loop,
                                          daemon=True,
                                          name="worker-gateway-accept")
        self._accept_t.start()

    # -- registry ----------------------------------------------------------
    def _accept_loop(self) -> None:
        while True:
            try:
                conn, addr = self._sock.accept()
            except OSError:
                return            # listener closed
            spec = faults.active().wire("gateway_accept")
            if spec is not None:
                # an injected accept fault: the worker's dial lands and
                # immediately dies (or stalls) — its retry_wire/reconnect
                # path is what's under test
                if spec.kind in ("delay", "frame_delay"):
                    time.sleep(faults.active().wire_delay_s())
                else:
                    conn.close()
                    continue
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = threading.Thread(target=self._conn_loop, args=(conn, addr),
                                 daemon=True, name="worker-gateway-reader")
            with self._lock:
                if self._closed:
                    conn.close()
                    return
                self._threads.append(t)
            t.start()

    def _conn_loop(self, conn: socket.socket, addr) -> None:
        """One registered worker's reader: REGISTER first, then
        heartbeats and RPC responses until EOF/torn frame."""
        svc = self._svc
        worker: Optional[_WorkerConn] = None
        reason = "connection closed"
        try:
            frame = transport.read_frame(conn)
            if frame is None or frame[0] != T_REGISTER:
                conn.close()
                return
            self._account(transport.HEADER.size + len(frame[1]))
            pid_, rid, wpid, wflags, wgen = transport.decode_register(
                frame[1])
            agreed = wflags & ((FLAG_WIRE_COMPRESS if self._compress else 0)
                               | (FLAG_RESULT_CACHE if self._rcache else 0)
                               | (FLAG_FILTERS if self._filters else 0))
            worker = _WorkerConn(conn, addr, pid_, rid, wpid,
                                 flags=agreed, generation=wgen)
            with self._lock:
                old = self._workers.get((pid_, rid))
                self._workers[(pid_, rid)] = worker
                self._registered += 1
            rejoined = False
            if old is not None:
                if old.mark_dead("replaced"):
                    self._fail_inflight(old, "replaced by a new "
                                             "registration")
                else:
                    # the slot's previous connection was already LOST:
                    # this registration is the self-healing worker's
                    # re-dial landing (docs/ROBUSTNESS.md)
                    rejoined = True
            if wflags:
                # confirm the negotiated capability set on the same
                # ordered stream — the ack lands before any VQUERY, so
                # the worker knows the agreed flags by its first answer
                with worker.wlock:
                    worker.sender.send(T_HELLO, transport.encode_hello(
                        agreed), counter=svc._m_wire_bytes,
                        raw_counter=svc._m_wire_raw)
            svc.registry.event("worker_registered", {
                "partition": pid_, "replica": rid, "pid": wpid,
                "addr": f"{addr[0]}:{addr[1]}",
                "wire_compress": bool(agreed & FLAG_WIRE_COMPRESS),
                "result_cache": bool(agreed & FLAG_RESULT_CACHE),
                "filters": bool(agreed & FLAG_FILTERS),
                "generation": wgen})
            if rejoined:
                # liveness restored: the fresh connection wipes the
                # breaker's consecutive-failure history (the in-flight
                # RPCs the loss failed already counted against it)
                self._breaker_result(pid_, rid, ok=True)
                svc.registry.event("worker_rejoined", {
                    "partition": pid_, "replica": rid, "pid": wpid,
                    "generation": wgen})
            # a (re)joining worker whose view lags the routed generation
            # serves NOTHING until REFRESH catches it up (generation
            # gating in _pick_worker) — nudge it immediately instead of
            # leaving it stale until the next broadcast_refresh. In
            # elastic mode the nudge ALWAYS fires and carries the routed
            # split width too: a joiner's split is unknown until its ack
            # lands (split gating), so the nudge is also how it becomes
            # routable at all.
            cur_gen = self._routed_generation(pid_)
            cur_split = (len(self.partition_set._view_table)
                         if self._elastic else 0)
            if cur_gen is not None and (wgen != cur_gen or self._elastic):
                try:
                    with worker.wlock:
                        worker.sender.send(
                            T_REFRESH,
                            transport.encode_refresh(cur_gen, cur_split),
                            counter=svc._m_wire_bytes,
                            raw_counter=svc._m_wire_raw)
                except OSError:
                    pass          # a dying worker re-registers fresh
            # a join at the next tail index widens the elastic fleet:
            # re-cut the split over the new width and broadcast the
            # handoff (no-op unless serve.elastic and the live set is
            # contiguous at a new width)
            self._maybe_resplit(trigger="join")
            while True:
                frame = transport.read_frame(conn)
                if frame is None:
                    break
                ftype, payload = frame
                actual = transport.HEADER.size + len(payload)
                if ftype == T_HEARTBEAT:
                    self._account(actual)
                    worker.beat()
                elif ftype in (T_RESULT, T_RESULT_C, T_SHED, T_ERROR):
                    worker.beat()     # any traffic proves liveness
                    self._resolve(ftype, payload, actual)
                elif ftype == T_REFRESH:
                    # the worker's view-rebuild ack: it now serves this
                    # store generation (and, extended form, this split
                    # width) and is routable again
                    self._account(actual)
                    gen, wsplit = transport.decode_refresh(payload)
                    worker.set_generation(gen, split=wsplit)
                    worker.beat()
                    svc.registry.event("worker_refreshed", {
                        "partition": worker.partition,
                        "replica": worker.replica, "generation": gen,
                        "partitions": wsplit})
                elif ftype == T_DRAIN:
                    # the worker announced a graceful exit: stop routing
                    # it new work NOW (its slice serves from the local
                    # view), and let the elastic fleet shrink around it
                    self._account(actual)
                    worker.beat()
                    if worker.set_draining():
                        svc.registry.event("worker_draining", {
                            "partition": worker.partition,
                            "replica": worker.replica, "pid": worker.pid})
                        self._maybe_resplit(trigger="drain")
                elif ftype == T_BYE:
                    self._account(actual)
                    reason = "deregistered"
                    break
                else:
                    self._account(actual)
                    reason = f"unexpected frame type {ftype}"
                    break
        except FrameError as e:
            # torn response / garbage: indistinguishable from a crashed
            # peer — treated exactly like one
            reason = f"torn frame: {e}"
        except OSError as e:
            reason = f"socket error: {e}"
        finally:
            try:
                conn.close()
            except OSError:
                pass
            if worker is not None and worker.mark_dead(reason):
                self._fail_inflight(worker, reason)
                svc.registry.event("worker_lost", {
                    "partition": worker.partition,
                    "replica": worker.replica,
                    "reason": reason[:200]})

    def _account(self, actual: int, raw: Optional[int] = None) -> None:
        """Wire-byte accounting: actual bytes moved, plus the raw-frame
        equivalent (what the same traffic would have cost uncompressed)
        feeding the wire-compression ratio."""
        self._svc._m_wire_bytes.inc(actual)
        self._svc._m_wire_raw.inc(actual if raw is None else raw)

    def _resolve(self, ftype: int, payload: bytes, actual: int) -> None:
        if ftype in (T_RESULT, T_RESULT_C):
            req_id, scores, ids, scan = transport.decode_result_any(
                ftype, payload)
            self._account(actual,
                          raw=transport.result_raw_bytes(*scores.shape)
                          if ftype == T_RESULT_C else actual)
            ok: Optional[Tuple] = (scores, ids, scan)
            exc: Optional[Exception] = None
        elif ftype == T_SHED:
            self._account(actual)
            req_id, code, why = transport.decode_shed(payload)
            ok, exc = None, DeadlineExceeded(why or f"shed code {code}")
        else:
            self._account(actual)
            req_id, msg = transport.decode_error(payload)
            ok, exc = None, RemoteError(msg)
        with self._lock:
            entry = self._pending.pop(req_id, None)
        if entry is None:
            return                # a hedged loser landing late: discard
        fut, _ = entry
        if exc is None:
            fut.set_result(ok)
        else:
            fut.set_exception(exc)

    def _fail_inflight(self, worker: _WorkerConn, reason: str) -> None:
        with self._lock:
            doomed = [rid for rid, (_, w) in self._pending.items()
                      if w is worker]
            entries = [self._pending.pop(rid) for rid in doomed]
        for fut, _ in entries:
            fut.set_exception(RemoteError(f"worker lost: {reason}"))

    def _routed_generation(self, pid: int) -> Optional[int]:
        """The store generation the front end currently routes for
        partition `pid` — what a worker must serve to be eligible."""
        try:
            views = self.partition_set._view_table[pid]
        except IndexError:
            return None
        return views[0].generation if views else None

    # -- circuit breakers (docs/ROBUSTNESS.md "Network failure model") -----
    def _breaker(self, pid: int, rid: int) -> faults.CircuitBreaker:
        """Replica (pid, rid)'s persistent breaker, created on first
        use. The open/close callbacks run OUTSIDE the breaker's lock
        (CircuitBreaker contract), so taking the registry lock in
        _breaker_event keeps the gateway's lock order intact."""
        with self._lock:
            br = self._breakers.get((pid, rid))
            if br is None:
                br = self._breakers[(pid, rid)] = faults.CircuitBreaker(
                    failures=self._breaker_failures,
                    open_s=self._breaker_open_s,
                    max_open_s=self._breaker_max_s,
                    on_open=lambda b, p=pid, r=rid: self._breaker_event(
                        "breaker_open", p, r, b),
                    on_close=lambda b, p=pid, r=rid: self._breaker_event(
                        "breaker_close", p, r, b))
            return br

    def _breaker_allow(self, pid: int, rid: int) -> bool:
        if self._breaker_failures <= 0:
            return True
        return self._breaker(pid, rid).allow()

    def _breaker_result(self, pid: int, rid: int, ok: bool) -> None:
        """Feed a wire outcome for replica (pid, rid) into its breaker.
        Only WIRE failures count (send errors, lost workers, remote
        errors) — a deadline shed is deliberate backpressure from a
        healthy worker and never opens a breaker."""
        if self._breaker_failures <= 0:
            return
        br = self._breaker(pid, rid)
        if ok:
            br.record_success()
        else:
            br.record_failure()

    def _breaker_event(self, name: str, pid: int, rid: int,
                       br: faults.CircuitBreaker) -> None:
        with self._lock:
            n_open = sum(1 for b in self._breakers.values()
                         if b.state == "open")
        reg = self._svc.registry
        reg.gauge("serve.breakers_open").set(n_open)
        attrs = {"partition": pid, "replica": rid,
                 "trips": br.trips, "open": n_open}
        if name == "breaker_open":
            reg.event("breaker_open", attrs)
        else:
            reg.event("breaker_close", attrs)

    # -- liveness (PartitionSet routing + availability tests) --------------
    def _alive_age_s(self) -> float:
        """Max heartbeat age before a CONNECTED worker counts as hung:
        two missed beats, plus a floor for host scheduling jitter (a
        loaded 1-core box can delay an idle worker's heartbeat thread
        past a bare 2x multiple). Crashes never wait for this — a dead
        connection reads EOF and marks the worker lost immediately."""
        return 2.0 * self.heartbeat_s + 0.25

    def worker_alive(self, pid: int, rid: int) -> bool:
        with self._lock:
            w = self._workers.get((pid, rid))
        return w is not None and w.alive(self._alive_age_s())

    def active(self) -> bool:
        """Any live worker at all? False = the in-process scatter serves
        (zero per-request overhead when no fleet is attached)."""
        with self._lock:
            workers = list(self._workers.values())
        age = self._alive_age_s()
        return any(w.alive(age) for w in workers)

    def live_workers(self) -> List[Tuple[int, int]]:
        with self._lock:
            keys = list(self._workers)
        return [key for key in keys if self.worker_alive(*key)]

    def wait_for_workers(self, n: int, timeout_s: float = 30.0) -> bool:
        """Block until `n` workers are live (fleet-start barrier for
        cli/bench) — False on timeout, after recording WHAT the barrier
        waited for and the fleet state it saw (`gateway_wait_timeout`
        event + the stats() wait_timeouts counter): a silent False is
        undebuggable once re-splits make barriers routine."""
        t0 = time.perf_counter()
        t_end = t0 + timeout_s
        while time.perf_counter() < t_end:
            if len(self.live_workers()) >= n:
                return True
            time.sleep(0.01)
        live = len(self.live_workers())
        if live >= n:
            return True
        with self._lock:
            registered = self._registered
        self._note_wait_timeout(
            "workers", time.perf_counter() - t0, timeout_s,
            wanted=int(n), live=live, registered=registered)
        return False

    def _note_wait_timeout(self, barrier: str, waited_s: float,
                           timeout_s: float, **state) -> None:
        with self._lock:
            self._wait_timeouts += 1
        self._svc.registry.event("gateway_wait_timeout", dict(
            {"barrier": barrier, "waited_s": round(waited_s, 3),
             "timeout_s": round(float(timeout_s), 3)}, **state))

    def _pick_worker(self, pid: int, prefer_rid: int,
                     exclude: Tuple[int, ...] = (),
                     generation: Optional[int] = None,
                     split: Optional[int] = None,
                     require_flags: int = 0
                     ) -> Optional[_WorkerConn]:
        """The live worker that should answer partition `pid`: the routed
        replica's own worker when live, else the lowest-rid live sibling
        not in `exclude`. With `generation` set, a worker whose view
        serves a DIFFERENT store generation is ineligible — after a
        refresh the fan-out serves that slice locally (on the already-
        swapped front-end view) until the worker's T_REFRESH ack lands,
        so one result set can never mix generations across the wire.
        `split` gates identically on the partition-split width the
        worker last ACKED (elastic mode): a worker cut over a different
        width — or one that never reported — serves nothing, so one
        result set can never mix splits either. A draining worker is
        skipped unconditionally (its slice falls back to the local
        view). A replica whose circuit breaker is open is skipped the
        same way — the breaker check runs LAST because a half-open
        breaker's allow() consumes its single probe slot. `require_flags`
        restricts to workers whose NEGOTIATED capability set covers the
        mask — a filtered scatter passes FLAG_FILTERS here, so a legacy
        worker is simply unroutable for that request (its slice serves
        from the local filtered view: never wrong results)."""
        with self._lock:
            cands = [(rid, w) for (p, rid), w in self._workers.items()
                     if p == pid and rid not in exclude]
        cands.sort(key=lambda t: (t[0] != prefer_rid, t[0]))
        age = self._alive_age_s()
        for _, w in cands:
            if w.alive(age) and not w.draining \
                    and (w.flags & require_flags) == require_flags \
                    and (generation is None
                         or w.generation == generation) \
                    and (split is None or w.split == split) \
                    and self._breaker_allow(pid, w.replica):
                return w
        return None

    # -- the RPC fan-out ---------------------------------------------------
    def _prepare(self, qv: np.ndarray, n: int) -> Tuple[bytes, int, int]:
        """The shared fan-out encode: the query block's wire bytes are
        built ONCE per coalesced bucket and shared across every
        partition send (and every hedge/failover resend) — each RPC adds
        only its per-request head. -> (block bytes, n, dim)."""
        block = np.ascontiguousarray(qv[:n], dtype="<f4")
        return block.tobytes(), n, block.shape[1]

    def _send(self, worker: _WorkerConn, prep: Tuple[bytes, int, int],
              k: int, nprobe: Optional[int],
              deadline: Optional[float],
              ftext: Optional[str] = None) -> Future:
        svc = self._svc
        block, n, dim = prep
        req_id = transport.next_request_id()
        rem_ms = 0.0
        if deadline is not None:
            rem_ms = max((deadline - svc._clock()) * 1000.0, 0.001)
        head = transport._VQUERY_HEAD.pack(req_id, rem_ms, int(k),
                                           int(nprobe or 0), n, dim)
        # the optional predicate field is PER REQUEST — it rides after
        # the block on every variant and is never interned (routing
        # guarantees this worker negotiated FLAG_FILTERS when non-empty)
        tail = transport._filters_field(ftext)
        fut: Future = Future()
        with self._lock:
            self._pending[req_id] = (fut, worker)
            self._rpcs += 1
        try:
            with worker.wlock:
                if worker.flags & FLAG_WIRE_COMPRESS:
                    # interned send: the block ships once per connection
                    # slot; repeats cost a 2-byte reference
                    slot, fresh = worker.intern.slot_for(block)
                    slot_b = transport._SLOT.pack(slot)
                    raw = (transport.HEADER.size + len(head) + len(block)
                           + len(tail))
                    if fresh:
                        worker.sender.send(T_VQUERY_PUT, head, slot_b,
                                           block, tail,
                                           counter=svc._m_wire_bytes,
                                           raw_counter=svc._m_wire_raw,
                                           raw_len=raw)
                    else:
                        worker.sender.send(T_VQUERY_REF, head, slot_b,
                                           tail,
                                           counter=svc._m_wire_bytes,
                                           raw_counter=svc._m_wire_raw,
                                           raw_len=raw)
                else:
                    worker.sender.send(T_VQUERY, head, block, tail,
                                       counter=svc._m_wire_bytes,
                                       raw_counter=svc._m_wire_raw)
        except OSError as e:
            # popping the entry claims the right to complete the future:
            # the reader thread races us here (a torn send closes the
            # socket, so its _fail_inflight may fail this req_id first)
            with self._lock:
                claimed = self._pending.pop(req_id, None) is not None
            if worker.mark_dead(f"send failed: {e}"):
                self._fail_inflight(worker, f"send failed: {e}")
                svc.registry.event("worker_lost", {
                    "partition": worker.partition,
                    "replica": worker.replica,
                    "reason": f"send failed: {e}"[:200]})
            # no breaker feed here: the RemoteError future is observed
            # in _await_partition, which records exactly one failure
            if claimed:
                fut.set_exception(RemoteError(f"send failed: {e}"))
        return fut

    def _hedge_delay_s(self, pid: int) -> Optional[float]:
        """The wait before hedging partition `pid`: the hedge-quantile
        point of its observed RPC latency, or None while the history is
        too thin (< 8 samples) to hedge on evidence."""
        q = self.hedge_quantile
        if not 0.0 < q < 1.0:
            return None
        with self._lock:
            lat = self._lat.get(pid)
            if lat is None or len(lat) < 8:
                return None
            return max(lat.percentile_ms(q * 100.0) / 1000.0, 1e-4)

    def _record_latency(self, pid: int, seconds: float) -> None:
        with self._lock:
            lat = self._lat.get(pid)
            if lat is None:
                lat = self._lat[pid] = LatencyStats()
            lat.add(seconds)

    def _await_partition(self, pid: int, prefer_rid: int, first: Future,
                         first_rid: int, prep: Tuple[bytes, int, int],
                         k: int, nprobe: Optional[int],
                         deadline: Optional[float],
                         generation: Optional[int] = None,
                         split: Optional[int] = None,
                         ftext: Optional[str] = None
                         ) -> Optional[Tuple]:
        """Wait for partition `pid`'s RPC answer, hedging to a sibling at
        the latency-quantile point and failing over on worker loss; None
        when every wire route failed (the caller serves locally)."""
        svc = self._svc
        t0 = time.perf_counter()
        budget = self.rpc_timeout_s
        if deadline is not None:
            rem = deadline - svc._clock()
            budget = min(budget, max(rem, 0.0))
        in_flight: Dict[Future, int] = {first: first_rid}
        tried = {first_rid}
        hedged = False
        while True:
            elapsed = time.perf_counter() - t0
            remaining = budget - elapsed
            hedge_s = None if hedged else self._hedge_delay_s(pid)
            if hedge_s is not None and elapsed < hedge_s:
                timeout = min(hedge_s - elapsed, max(remaining, 0.0))
            else:
                timeout = max(remaining, 0.0)
            done, _ = futures_wait(set(in_flight), timeout=timeout,
                                   return_when=FIRST_COMPLETED)
            for fut in done:
                rid = in_flight.pop(fut)
                exc = fut.exception()
                if exc is not None and isinstance(exc, RemoteError):
                    # a wire failure (lost worker / failed send / remote
                    # error) feeds the breaker; a DeadlineExceeded shed
                    # is deliberate backpressure and never counts
                    self._breaker_result(pid, rid, ok=False)
                if exc is None:
                    self._breaker_result(pid, rid, ok=True)
                    if not hedged:
                        # only UNHEDGED completions feed the hedge-delay
                        # history: a hedged call finishes slow by
                        # definition (the hedge only fired because it
                        # crossed the quantile), and recording it would
                        # drag the threshold up until hedging turned
                        # itself off — the healthy-path distribution is
                        # the reference the quantile must track
                        self._record_latency(pid,
                                             time.perf_counter() - t0)
                    return fut.result()
                tried.add(rid)
            elapsed = time.perf_counter() - t0
            if elapsed >= budget and not in_flight:
                return None
            if not in_flight:
                # every issued RPC failed: fail over to an untried live
                # sibling (not a hedge — the first copy is already dead)
                w = self._pick_worker(pid, prefer_rid,
                                      exclude=tuple(tried),
                                      generation=generation, split=split,
                                      require_flags=(FLAG_FILTERS
                                                     if ftext else 0))
                if w is None:
                    return None
                in_flight[self._send(w, prep, k, nprobe, deadline,
                                     ftext)] = w.replica
                tried.add(w.replica)
                continue
            if elapsed >= budget:
                return None
            if (not hedged and hedge_s is not None
                    and elapsed >= hedge_s):
                hedged = True
                w = self._pick_worker(pid, prefer_rid,
                                      exclude=tuple(tried),
                                      generation=generation, split=split,
                                      require_flags=(FLAG_FILTERS
                                                     if ftext else 0))
                if w is not None:
                    svc._m_hedge_fired.inc()
                    cur = svc.tracer.current()
                    svc.registry.event("hedge_fired", {
                        "partition": pid, "from_replica": first_rid,
                        "to_replica": w.replica,
                        "after_ms": round(elapsed * 1000.0, 3),
                    }, trace_id=getattr(cur, "trace_id", None))
                    in_flight[self._send(w, prep, k, nprobe, deadline,
                                         ftext)] = w.replica
                    tried.add(w.replica)

    # graftcheck: hot
    def topk(self, qv: np.ndarray, n: int, k: int,
             nprobe: Optional[int] = None,
             deadline: Optional[float] = None,
             predicate=None) -> Tuple[np.ndarray, np.ndarray]:
        """The over-the-wire scatter-gather: one routed worker RPC per
        partition (hedged, deadline-budgeted), per-partition LOCAL
        fallback on any wire failure, winners folded through the same
        partition merge tree as the in-process scatter — results
        byte-identical to `PartitionSet.topk` by construction.

        With `predicate` (a compiled `index/attrs.Predicate`) the
        canonical text rides each RPC's optional filter field, routing
        restricts to FLAG_FILTERS workers, and every fallback slice runs
        the same filtered `_topk_view` — so the filtered result set is
        byte-identical to the in-process filtered scatter too."""
        svc = self._svc
        pset = self.partition_set
        ftext = predicate.text if predicate is not None else None
        req_flags = FLAG_FILTERS if ftext else 0
        # ONE table snapshot anchors the whole scatter: its length IS
        # the split width every per-partition decision below is gated
        # on, so a concurrent elastic re-split (which publishes a new
        # table in one assignment) can never hand this result set a
        # mixed cut — the same snapshot idiom that pins generations
        table = pset._view_table
        P = len(table)
        split = P if self._elastic else None
        # ONE shared encode for the whole scatter (and its hedges): the
        # block bytes build here and every per-partition send reuses them
        prep = self._prepare(qv, n)
        calls: List[Tuple[int, object, Optional[Future], int]] = []
        with svc._stage("scatter", partitions=P, transport="socket"):
            for pid in range(P):
                rep = pset._route(pid)
                gen = table[pid][rep.rid].generation
                w = self._pick_worker(pid, rep.rid, generation=gen,
                                      split=split,
                                      require_flags=req_flags)
                if w is None:
                    calls.append((pid, rep, None, -1))
                else:
                    calls.append((pid, rep,
                                  self._send(w, prep, k, nprobe, deadline,
                                             ftext),
                                  w.replica))
            parts: List[Optional[Tuple]] = [None] * P
            for pid, rep, fut, rid in calls:
                res = None
                if fut is not None:
                    with svc._stage("rpc", partition=pid, replica=rid):
                        res = self._await_partition(
                            pid, rep.rid, fut, rid, prep, k, nprobe,
                            deadline,
                            generation=table[pid][rep.rid].generation,
                            split=split, ftext=ftext)
                if res is None:
                    # the in-process degrade path, verbatim: this
                    # partition's slice computed on the front end's own
                    # view — a dead/torn/late worker costs latency,
                    # never bytes
                    if fut is not None:
                        with self._lock:
                            self._rpc_fallbacks += 1
                    view = table[pid][rep.rid]
                    res = svc._topk_view(view, qv, n, k, nprobe,
                                         predicate=predicate)
                parts[pid] = res
        with svc._stage("merge"):
            return merge_partition_topk([(s, i) for s, i, _ in parts])

    # -- store-generation control (docs/SERVING.md) ------------------------
    def broadcast_refresh(self, generation: int, wait_s: float = 0.0,
                          split: Optional[int] = None,
                          refresh_own: bool = True) -> Dict:
        """Tell every live worker to re-open the store and rebuild its
        view (T_REFRESH carrying the target generation — and, in elastic
        mode, the split width to re-cut over) — the wire fleet's half of
        `SearchService.refresh()`: a store generation swap no longer
        needs a worker restart. Until a worker ACKS with its own
        T_REFRESH, routing treats it as generation-stale (and, elastic,
        split-stale) and the fan-out serves its slice from the front
        end's local view, so the swap stays byte-consistent while the
        fleet catches up. With `wait_s` > 0 the call blocks up to that
        long for every live worker's ack. `split` defaults to the
        routed table's width in elastic mode, 0 (unspecified: the
        worker keeps its cut) otherwise; `refresh_own=False` skips the
        private-pset rebuild when the caller (resplit) already did it."""
        svc = self._svc
        if self._own_pset is not None and refresh_own:
            # single-view service: the gateway's private 1-partition set
            # must follow the store too, or its table (and the local
            # fallback views in it) would serve the old generation
            # forever while generation gating kept every worker
            # ineligible
            self._own_pset.refresh(svc.store)
        if split is None:
            split = (len(self.partition_set._view_table)
                     if self._elastic else 0)
        with self._lock:
            workers = list(self._workers.values())
        age = self._alive_age_s()
        told = 0
        for w in workers:
            if not w.alive(age) or (w.generation == generation
                                    and (split <= 0 or w.split == split)):
                continue
            try:
                with w.wlock:
                    w.sender.send(
                        T_REFRESH,
                        transport.encode_refresh(generation, split),
                        counter=svc._m_wire_bytes,
                        raw_counter=svc._m_wire_raw)
                told += 1
            except OSError:
                pass              # a dying worker re-registers fresh
        if wait_s > 0:
            self.wait_for_generation(generation, timeout_s=wait_s,
                                     split=split)
        return {"workers_told": told,
                "workers_stale": self.stale_workers(generation,
                                                    split=split)}

    def stale_workers(self, generation: int, split: int = 0) -> int:
        """Live workers whose view still serves another generation (or,
        with `split` > 0, another partition-split width)."""
        with self._lock:
            workers = list(self._workers.values())
        age = self._alive_age_s()
        return sum(1 for w in workers
                   if w.alive(age) and (w.generation != generation
                                        or (split > 0
                                            and w.split != split)))

    def wait_for_generation(self, generation: int,
                            timeout_s: float = 30.0,
                            split: int = 0) -> bool:
        """Block until no live worker lags `generation` (and `split`,
        when > 0) — the fleet-wide refresh barrier for tests/cli; False
        on timeout, after recording how long it waited and how many
        workers stayed stale (`gateway_wait_timeout` event + stats()
        counter)."""
        t0 = time.perf_counter()
        t_end = t0 + timeout_s
        while time.perf_counter() < t_end:
            if self.stale_workers(generation, split=split) == 0:
                return True
            time.sleep(0.01)
        stale = self.stale_workers(generation, split=split)
        if stale == 0:
            return True
        self._note_wait_timeout(
            "generation", time.perf_counter() - t0, timeout_s,
            generation=int(generation), split=int(split), stale=stale,
            live=len(self.live_workers()))
        return False

    # -- elastic membership (docs/SCALING.md "Scale-out tier") -------------
    def _fleet_width(self) -> Optional[int]:
        """The partition-split width the live fleet implies: one slice
        per distinct live, non-draining partition index — but only when
        those indices are exactly {0..W-1}. Membership changes at the
        TAIL (spawn the next index, drain the highest); a gapped set
        (a mid-fleet crash, an out-of-order spawn) returns None and the
        split stays put — crash recovery is rejoin + local fallback,
        never a re-cut under a hole."""
        with self._lock:
            workers = list(self._workers.values())
        age = self._alive_age_s()
        pids = {w.partition for w in workers
                if w.alive(age) and not w.draining}
        if not pids:
            return None
        width = max(pids) + 1
        if pids != set(range(width)):
            return None
        return width

    def _maybe_resplit(self, trigger: str) -> Optional[Dict]:
        """Re-cut the partition split if the live fleet's width moved
        (no-op unless serve.elastic)."""
        if not self._elastic:
            return None
        width = self._fleet_width()
        if width is None:
            return None
        with self._resplit_lock:
            if width != len(self.partition_set._view_table):
                return self._resplit(width, trigger)
        return None

    # holds-lock: _resplit_lock
    def _resplit(self, width: int, trigger: str) -> Dict:
        """The elastic re-cut: rebuild the front end's view table over
        `width` partitions (`partition_shard_ranges` over the new fleet
        size — deterministic, so every front end sharing the fleet cuts
        identically), then broadcast the generation+split handoff. The
        resize runs under the SAME svc._refresh_lock a store refresh
        takes, and publishes the new table in one assignment — in-flight
        scatters keep their snapshot of the old cut, new scatters see
        the new one, and split gating keeps every worker unroutable
        until it acks the new width, so no result set ever mixes
        splits."""
        svc = self._svc
        pset = self.partition_set
        old = len(pset._view_table)
        with svc._refresh_lock:
            pset.resize(svc.store, width)
        generation = self._routed_generation(0)
        info = self.broadcast_refresh(generation, split=width,
                                      refresh_own=False)
        with self._lock:
            self._resplits += 1
        svc.registry.event("fleet_resplit", {
            "trigger": trigger, "from_partitions": old,
            "to_partitions": width, "generation": generation,
            "workers_told": info["workers_told"]})
        return dict(info, partitions=width)

    # -- telemetry / lifecycle --------------------------------------------
    def stats(self) -> Dict:
        """The metrics()/loadtest transport sub-block."""
        with self._lock:
            registered = self._registered
            rpcs = self._rpcs
            fallbacks = self._rpc_fallbacks
            resplits = self._resplits
            wait_timeouts = self._wait_timeouts
            workers = list(self._workers.values())
            compressing = sum(
                1 for w in workers
                if not w.dead and w.flags & FLAG_WIRE_COMPRESS)
            filtering = sum(1 for w in workers
                            if not w.dead and w.flags & FLAG_FILTERS)
            breakers = list(self._breakers.values())
        return {
            "workers_live": len(self.live_workers()),
            "workers_registered": registered,
            "workers_compressing": compressing,
            "workers_filtering": filtering,
            "workers_draining": sum(1 for w in workers
                                    if not w.dead and w.draining),
            "rpcs": rpcs,
            "rpc_fallbacks": fallbacks,
            "resplits": resplits,
            "wait_timeouts": wait_timeouts,
            "breakers_open": sum(1 for b in breakers
                                 if b.state == "open"),
            "breaker_trips": sum(b.trips for b in breakers),
        }

    def close(self) -> None:
        with self._lock:
            self._closed = True
            workers = list(self._workers.values())
            threads = list(self._threads)
        try:
            self._sock.close()
        except OSError:
            pass
        for w in workers:
            # a clean BYE first: workers exit their serve loop instead of
            # reading a reset mid-frame (part of the graceful-drain
            # contract — docs/SERVING.md)
            if not w.dead:
                try:
                    with w.wlock:
                        w.sender.send(T_BYE)
                except OSError:
                    pass
            w.mark_dead("gateway closed")
            try:
                w.sock.close()
            except OSError:
                pass
        self._accept_t.join(timeout=5.0)
        for t in threads:
            t.join(timeout=5.0)
        if self._own_pset is not None:
            self._own_pset.close()


# ---------------------------------------------------------------------------
# the worker side
# ---------------------------------------------------------------------------

class _GatewayLink:
    """One worker->gateway connection's session state. A PartitionWorker
    serving N front ends runs one link per `--connect` endpoint: each
    link owns its OWN socket, sender, negotiated capability flags,
    intern slots, block cache, heartbeat thread, and reconnect
    supervisor — per-gateway wire state stays isolated by construction
    (the same invariant the per-connection intern tables rely on) while
    every link serves the ONE shared view."""

    def __init__(self, connect: Tuple[str, int], index: int):
        self.connect = (connect[0], int(connect[1]))
        self.index = int(index)
        self.sock: Optional[socket.socket] = None
        self.send_lock = threading.Lock()  # serializes frame writes
        self.sender: Optional[FrameSender] = None  # guarded-by: send_lock
        # agreed capabilities — re-negotiated per connection, written
        # and read only on this link's serve loop
        self.flags = 0
        # per-hop block cache: (query-block bytes, k, nprobe) -> (view,
        # scores, ids, scan). Link serve-loop only. A hit replays ONLY
        # if the cached view IS this request's snapshotted view object —
        # identity, not equality — so a refresh or re-split swap makes
        # every old entry unreachable without any cross-thread clearing.
        self.block_cache: OrderedDict = OrderedDict()
        self.sessions = 0   # completed dial+REGISTER rounds (serve loop)


class PartitionWorker:
    """One partition replica serving its `PartitionSpec` slice over a
    socket. As a process: `cli partition-worker` (the production shape);
    in tests it also runs as a thread with its own service instance —
    either way it owns an independent restricted view built by the exact
    `_build_view` the in-process replicas use.

    Multi-front-end (docs/SCALING.md "Scale-out tier"): `connect` may be
    a LIST of gateway endpoints — the worker registers with every one
    and answers each over its own `_GatewayLink`, all serving the same
    view. T_REFRESH from any gateway re-cuts/re-opens the shared view
    (idempotent: a second gateway's broadcast for a state already served
    just acks), so N front ends converge on one split without talking
    to each other."""

    def __init__(self, cfg, store_dir: str, connect,
                 partition: int, partitions: int, replica: int = 0,
                 mesh=None, preload_hbm_gb: float = 4.0,
                 heartbeat_s: Optional[float] = None,
                 slow_ms: float = 0.0):
        from dnn_page_vectors_tpu.infer.partition import make_partition_specs
        from dnn_page_vectors_tpu.infer.serve import SearchService
        from dnn_page_vectors_tpu.infer.vector_store import VectorStore
        self.partition = int(partition)
        self.partitions = int(partitions)
        self.replica = int(replica)
        if connect and isinstance(connect[0], (list, tuple)):
            endpoints = [(h, int(p)) for h, p in connect]
        else:
            endpoints = [(connect[0], int(connect[1]))]
        self.connect = endpoints[0]   # primary endpoint (back-compat)
        self._links = [_GatewayLink(ep, i)
                       for i, ep in enumerate(endpoints)]
        self.heartbeat_s = (heartbeat_s if heartbeat_s is not None
                            else getattr(cfg.serve, "heartbeat_s", 0.5))
        # wire compression is ADVERTISED at REGISTER and only used after
        # the gateway confirms (T_HELLO ack) — a raw gateway, or a raw
        # sibling on the same gateway, interoperates untouched
        self.wire_compress = bool(getattr(cfg.serve, "wire_compress", True))
        # fleet result cache, advertised like compression and only used
        # after the gateway confirms: repeated vector blocks (the Zipf
        # head re-encoded to the same query matrix) replay their scored
        # answer without touching the store
        self.result_cache = bool(
            getattr(cfg.serve, "result_cache", False)
            and getattr(cfg.serve, "result_cache_fleet", False))
        # filtered retrieval, advertised like compression: the gateway
        # only ships the VQUERY filter field after confirming the flag
        self.filters = bool(getattr(cfg.serve, "filters", True))
        self._block_cache_cap = 64   # per-link block-cache entries
        # drill hook (tests, the bench hedge drill): added per-request
        # latency, so a deliberately slow replica provokes hedging
        self.slow_ms = float(slow_ms)
        if mesh is None:
            from dnn_page_vectors_tpu.parallel.multihost import local_mesh
            mesh = local_mesh(cfg.mesh)
        # the worker's own service answers exactly ONE slice: its config
        # is forced single-partition so no nested scatter can recurse
        cfg1 = cfg.replace(serve=dataclasses.replace(
            cfg.serve, partitions=1, replicas=1))
        store = VectorStore(store_dir)
        self.svc = SearchService(cfg1, MeshEmbedder(mesh), None, store,
                                 preload_hbm_gb=0.0)
        self.svc._preload_gb = preload_hbm_gb
        specs = make_partition_specs(store.shards(), self.partitions,
                                     hot_gb=cfg.serve.hot_postings_gb)
        if self.partition >= self.partitions:
            raise ValueError(
                f"partition {self.partition} does not exist: this worker "
                f"was asked for a {self.partitions}-way split")
        if self.partition < len(specs):
            self.spec = specs[self.partition]
        else:
            # the balanced split clamps below the requested width (more
            # workers than shards): an EMPTY slice is a valid elastic
            # member — it serves nothing until a re-split assigns it rows
            from dnn_page_vectors_tpu.infer.partition import PartitionSpec
            self.spec = PartitionSpec(pid=self.partition, entries=(),
                                      shard_indices=(), rows=0, hot_gb=0.0)
        self.view = self.svc._build_view(store,
                                         entries=list(self.spec.entries),
                                         hot_gb=self.spec.hot_gb)
        self._stop = threading.Event()
        # serializes the shared view/spec/split swap: T_REFRESH can now
        # arrive on N link threads at once; the swap itself stays one
        # reference assignment per field, the lock only orders rebuilds
        # (and lets a duplicate refresh short-circuit to an ack)
        # lock-order: PartitionWorker._swap_lock < _GatewayLink.send_lock
        self._swap_lock = threading.Lock()
        # self-healing (docs/ROBUSTNESS.md "Network failure model"): on
        # connection loss run() re-dials with exponential backoff +
        # jitter instead of exiting; serve.reconnect=False restores the
        # connection-loss-is-terminal behavior
        self.reconnect = bool(getattr(cfg.serve, "reconnect", True))
        self.reconnect_base_s = float(
            getattr(cfg.serve, "reconnect_base_s", 0.05))
        self.reconnect_max_s = float(
            getattr(cfg.serve, "reconnect_max_s", 2.0))
        # seeded per-replica jitter: deterministic under test, still
        # decorrelated across a fleet restarting together
        self._rng = random.Random(1 + (self.partition << 8) | self.replica)

    @property
    def sessions(self) -> int:
        """Completed dial+REGISTER rounds, across every gateway link."""
        return sum(ln.sessions for ln in self._links)

    # -- lifecycle ---------------------------------------------------------
    def _heartbeat_loop(self, link: _GatewayLink) -> None:
        while not self._stop.wait(self.heartbeat_s):
            try:
                with link.send_lock:
                    if link.sender is None:
                        return    # between sessions: this beat's done
                    link.sender.send(T_HEARTBEAT)
            except OSError:
                return

    def run(self) -> None:
        """Supervised serve loop (docs/ROBUSTNESS.md "Network failure
        model"): dial + REGISTER + serve on every gateway link; on EOF /
        torn frame / socket error a link re-dials with exponential
        backoff + jitter (base `serve.reconnect_base_s`, cap
        `serve.reconnect_max_s`) and re-REGISTERs with the CURRENT view
        generation, so a transient gateway blip costs one reconnect
        instead of the replica. A link exits on its gateway's clean
        T_BYE (deregistered), stop(), or — with serve.reconnect off —
        the first connection loss; run() returns when EVERY link has
        exited (one front end restarting never takes the worker down
        for its siblings). Blocking — the process entry point."""
        extra = [threading.Thread(target=self._run_link, args=(ln,),
                                  daemon=True,
                                  name=f"worker-p{self.partition}"
                                       f"r{self.replica}-g{ln.index}")
                 for ln in self._links[1:]]
        for t in extra:
            t.start()
        self._run_link(self._links[0])
        for t in extra:
            t.join()

    def _run_link(self, link: _GatewayLink) -> None:
        failures = 0
        while not self._stop.is_set():
            try:
                if self._serve_session(link):
                    break         # clean T_BYE: deregistered on purpose
                failures = 0      # a registered session resets the ramp
            except (FrameError, OSError):
                failures += 1     # gateway unreachable or stream torn
            if not self.reconnect or self._stop.is_set():
                break
            delay = min(self.reconnect_base_s * (2.0 ** max(failures - 1,
                                                            0)),
                        self.reconnect_max_s)
            delay += self._rng.uniform(0.0, delay / 2.0)
            faults.count("worker_reconnect")
            if self._stop.wait(delay):
                break

    def _dial(self, link: _GatewayLink) -> socket.socket:
        """Dial + REGISTER under the wire retry profile
        (faults.retry_wire — idempotent: a re-REGISTER replaces the
        previous registration), advertising the current view
        generation."""
        def _connect() -> socket.socket:
            faults.active().check("worker_dial")
            sock = socket.create_connection(link.connect)
            # an OSError on setsockopt or the REGISTER write must close
            # the socket on its way out (the retry dials fresh), not
            # leak it (graftcheck lifecycle rule)
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                view = self.view
                transport.write_frame(
                    sock, T_REGISTER,
                    transport.encode_register(
                        self.partition, self.replica, os.getpid(),
                        flags=(FLAG_WIRE_COMPRESS
                               if self.wire_compress else 0)
                        | (FLAG_RESULT_CACHE
                           if self.result_cache else 0)
                        | (FLAG_FILTERS if self.filters else 0),
                        generation=view.generation))
            except OSError:
                try:
                    sock.close()
                except OSError:
                    pass
                raise
            return sock
        return faults.retry_wire(_connect, op="worker_dial",
                                 backoff=self.reconnect_base_s,
                                 max_backoff=self.reconnect_max_s)

    def _serve_session(self, link: _GatewayLink) -> bool:
        """One dial + REGISTER + serve round on `link`. -> True on a
        clean T_BYE, False on EOF at a frame boundary (the supervisor
        re-dials); torn frames and socket errors propagate to the
        supervisor's backoff path."""
        sock = self._dial(link)
        hb: Optional[threading.Thread] = None
        slots: Dict[int, bytes] = {}   # per-connection intern table
        bye = False
        try:
            link.sock = sock
            link.sessions += 1
            link.flags = 0             # re-negotiated per connection
            with link.send_lock:
                link.sender = FrameSender(sock)
            hb = threading.Thread(target=self._heartbeat_loop,
                                  args=(link,), daemon=True,
                                  name=f"worker-p{self.partition}"
                                       f"r{self.replica}-g{link.index}-hb")
            hb.start()
            while not self._stop.is_set():
                frame = transport.read_frame(sock)
                if frame is None:
                    break
                ftype, payload = frame
                if ftype in (T_VQUERY, T_VQUERY_PUT, T_VQUERY_REF):
                    self._answer(link, ftype, payload, slots)
                elif ftype == T_HELLO:
                    # the gateway's negotiation ack: these capabilities
                    # are agreed for the rest of the connection
                    link.flags = transport.decode_hello(payload)
                elif ftype == T_REFRESH:
                    gen, parts = transport.decode_refresh(payload)
                    self._refresh(link, gen, parts)
                elif ftype == T_BYE:
                    bye = True
                    break
                # anything else from the gateway is ignorable control
        finally:
            # close FIRST: the heartbeat thread's next send then fails
            # fast and it exits inside the join window
            try:
                sock.close()
            except OSError:
                pass
            with link.send_lock:
                link.sender = None
            if hb is not None:
                hb.join(timeout=self.heartbeat_s + 2.0)
        return bye

    def _refresh(self, link: _GatewayLink, generation: int,
                 partitions: int = 0) -> None:
        """The T_REFRESH control path: re-open the store, rebuild this
        replica's restricted view over the shard split — re-cut over
        `partitions` when the extended frame carries a width (elastic
        re-split), the current width otherwise — swap it in with one
        reference assignment, and ack with the (generation, width) now
        served: byte-identical to a worker restarted against the same
        store, with no restart. With N gateways the rebuild is
        serialized and IDEMPOTENT — a second front end's broadcast for a
        state this worker already serves short-circuits straight to the
        ack. A rebuild failure keeps the OLD view serving (the gateway
        routes around the stale generation until a later refresh
        lands)."""
        from dnn_page_vectors_tpu.infer.partition import (
            make_partition_specs)
        from dnn_page_vectors_tpu.infer.vector_store import VectorStore
        with self._swap_lock:
            width = int(partitions) if partitions > 0 else self.partitions
            try:
                if (width != self.partitions
                        or self.view.generation != int(generation)):
                    new_store = VectorStore(self.svc.store.directory)
                    specs = make_partition_specs(
                        new_store.shards(), width,
                        hot_gb=self.svc.cfg.serve.hot_postings_gb)
                    if self.partition < len(specs):
                        spec = specs[self.partition]
                    else:    # the balanced split clamps under this slice
                        from dnn_page_vectors_tpu.infer.partition import (
                            PartitionSpec)
                        spec = PartitionSpec(pid=self.partition,
                                             entries=(), shard_indices=(),
                                             rows=0, hot_gb=0.0)
                    view = self.svc._build_view(new_store, reuse=self.view,
                                                entries=list(spec.entries),
                                                hot_gb=spec.hot_gb)
                    self.spec = spec
                    self.view = view   # THE swap: one reference assignment
                    self.partitions = width
                    self.svc.store = new_store
                    # this link's block cache self-invalidates (hits
                    # check view identity), but drop it eagerly anyway
                    # rather than letting dead entries squat the LRU;
                    # other links' caches age out on their own loops
                    link.block_cache.clear()
            except Exception:  # noqa: BLE001 — keep serving the old view
                pass
            try:
                with link.send_lock:
                    link.sender.send(T_REFRESH, transport.encode_refresh(
                        self.view.generation, self.partitions))
            except OSError:
                pass

    # graftcheck: hot
    def _answer(self, link: _GatewayLink, ftype: int, payload: bytes,
                slots: Dict[int, bytes]) -> None:
        req = transport.decode_vquery_any(ftype, payload, slots)
        t0 = time.perf_counter()
        parts: Tuple
        try:
            if self.slow_ms > 0:
                time.sleep(self.slow_ms / 1000.0)
            k = req.k or self.svc.cfg.eval.recall_k
            # the filter field only arrives when the gateway negotiated
            # FLAG_FILTERS with us; the canonical text folds into the
            # block-cache key so a filtered answer never replays for an
            # unfiltered repeat of the same block (or vice versa)
            from dnn_page_vectors_tpu.infer.serve import _compile_filters
            pred = _compile_filters(req.filters)
            # ONE view snapshot answers this request — the compute, the
            # cache hit check, and the cache fill all reference it, so a
            # concurrent refresh/re-split swap can't mix states
            view = self.view
            ckey = None
            hit = None
            if link.flags & FLAG_RESULT_CACHE:
                # per-hop block cache: a hit replays only when the
                # cached entry was computed on THIS view object
                # (identity check below), which makes it byte-identical
                # to a recompute — and unreachable the moment a refresh
                # or re-split swaps the view
                ckey = (req.qv.tobytes(), k, int(req.nprobe or 0),
                        req.filters or "")
                hit = link.block_cache.get(ckey)
                if hit is not None and hit[0] is view:
                    link.block_cache.move_to_end(ckey)
                else:
                    hit = None
            if hit is not None:
                _, scores, ids, scan = hit
            else:
                scores, ids, scan = self.svc._topk_view(
                    view, req.qv, req.qv.shape[0], k,
                    req.nprobe or None, predicate=pred)
                if ckey is not None:
                    link.block_cache[ckey] = (view, scores, ids, scan)
                    while len(link.block_cache) > self._block_cache_cap:
                        link.block_cache.popitem(last=False)
            if req.deadline_ms > 0 and \
                    (time.perf_counter() - t0) * 1000.0 > req.deadline_ms:
                # the budget died during compute: a late answer is waste
                # on the wire — the gateway already fell back
                rtype = T_SHED
                parts = (transport.encode_shed(
                    req.req_id, transport.SHED_DEADLINE,
                    "deadline expired during partition compute"),)
            elif link.flags & FLAG_WIRE_COMPRESS:
                rtype = T_RESULT_C
                parts = (transport.encode_result_c(req.req_id, scores,
                                                   ids, scan_bytes=scan),)
            else:
                rtype = T_RESULT
                scores = np.ascontiguousarray(scores, dtype="<f4")
                ids = np.ascontiguousarray(ids, dtype="<i8")
                parts = (transport._RESULT_HEAD.pack(
                    req.req_id, int(scan), *scores.shape), scores, ids)
        except Exception as e:  # noqa: BLE001 — the request fails, the
            # worker survives: per-request isolation like the batcher's
            rtype = T_ERROR
            parts = (transport.encode_error(req.req_id,
                                            f"{type(e).__name__}: {e}"),)
        with link.send_lock:
            link.sender.send(rtype, *parts)

    @staticmethod
    def _tear(sock: Optional[socket.socket]) -> None:
        """shutdown + close: a bare close() does not wake the serve
        loop's blocked recv (the in-flight syscall pins the kernel
        socket, so no FIN is sent either) — shutdown() tears the stream
        NOW, exactly like the process dying would."""
        if sock is None:
            return
        try:
            sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            sock.close()
        except OSError:
            pass

    def stop(self) -> None:
        """Abrupt local shutdown (tests' stand-in for kill -9): tear
        every link's socket out from under its serve loop."""
        self._stop.set()
        for ln in self._links:
            self._tear(ln.sock)

    def kill_connection(self) -> None:
        """Drill hook (tests, the bench chaos drill): tear every live
        connection out from under its serve loop WITHOUT stopping the
        worker — the supervised link loops re-dial and re-REGISTER,
        which is exactly the recovery path the chaos drills measure."""
        for ln in self._links:
            self._tear(ln.sock)

    def drain(self, wait_s: Optional[float] = None) -> None:
        """Graceful exit (docs/SCALING.md "Scale-out tier" drain rules):
        announce T_DRAIN on every link — each gateway stops routing this
        worker NEW work immediately and serves its slice from the local
        view (an elastic front end also shrinks the split around a
        drained tail index) — wait `wait_s` (default one heartbeat) for
        in-flight answers to flush, then BYE each gateway and stop. The
        announce-then-BYE split is what makes the handoff lossless: no
        request is ever in flight to a worker that has already gone."""
        for ln in self._links:
            try:
                with ln.send_lock:
                    if ln.sender is not None:
                        ln.sender.send(T_DRAIN)
            except OSError:
                pass              # that gateway already lost us
        time.sleep(self.heartbeat_s if wait_s is None else float(wait_s))
        for ln in self._links:
            try:
                with ln.send_lock:
                    if ln.sender is not None:
                        ln.sender.send(T_BYE)
            except OSError:
                pass
        self.stop()


def run_partition_worker(cfg, store_dir: str, connect: str, partition: int,
                         partitions: int, replica: int = 0,
                         preload_hbm_gb: float = 4.0) -> Dict:
    """`cli partition-worker` entry: build the worker (store + restricted
    view + mesh, NO model or checkpoint), print one ready line, serve
    until every gateway hangs up. `connect` is one `host:port` — or a
    comma-separated list of them for a worker shared by N front ends.
    Returns the exit record."""
    endpoints = []
    for one in connect.split(","):
        host, _, port = one.strip().rpartition(":")
        endpoints.append((host or "127.0.0.1", int(port)))
    slow = float(os.environ.get("DPV_WORKER_SLOW_MS", "0") or 0.0)
    worker = PartitionWorker(cfg, store_dir, endpoints,
                             partition=partition, partitions=partitions,
                             replica=replica, preload_hbm_gb=preload_hbm_gb,
                             slow_ms=slow)
    ready = {
        "partition_worker": worker.partition,
        "partitions": worker.partitions,
        "replica": worker.replica,
        "gateways": len(endpoints),
        "shards": list(worker.spec.shard_indices),
        "rows": worker.spec.rows,
        "pid": os.getpid(),
    }
    print(json.dumps(ready, sort_keys=True), flush=True)
    worker.run()
    return ready
