"""Inference layer: sharded corpus->vector bulk-embed job + vector store
(SURVEY.md §2 layer 5, §3 #19-20)."""
from dnn_page_vectors_tpu.infer.bulk_embed import BulkEmbedder
from dnn_page_vectors_tpu.infer.vector_store import VectorStore

__all__ = ["BulkEmbedder", "VectorStore"]
