"""Partitioned + replicated serving (docs/SCALING.md "Partitioned
serving").

The ROADMAP's "millions of users, 1B pages" north star needs serving to
scale *out*, and until this module every layer assumed one process owned
the whole corpus. The abstraction here is deliberately thin:

  * `PartitionSpec` — one partition's ownership contract: a CONTIGUOUS
    shard range (computed by `parallel/multihost.py:
    partition_shard_ranges`, balanced by row count), which implies its
    slice of the IVF posting files (`index/ivf.py:partition_view`) and
    its proportional cut of the `serve.hot_postings_gb` HBM hot set.
    Contiguity keeps a partition's page-id space an interval, so in a
    real multi-host deployment each host's shard files, posting files,
    and append ranges stay disjoint and the existing per-writer append
    leases (maintenance/lease.py) give mutual exclusion unchanged.
  * `_PartitionReplica` — one host-simulated worker: a thread draining a
    task queue, owning an independent `_ServeView` over the spec's
    entries. The view swap is the same single-reference-assignment
    hot-swap the single-view path uses (docs/UPDATES.md).
  * `PartitionSet` — P specs x R replicas plus the router. `topk()` is
    the scatter-gather: the (already encoded) query matrix broadcasts
    once to one routed replica per partition, each answers its local
    top-k via `SearchService._topk_view` over only its shard range — so
    per-query scan bytes drop ~1/P and partitions run concurrently — and
    the per-partition winners fold through
    `ops/topk.py:merge_partition_topk` (a balanced merge tree with
    `merge_topk_host` as the fold).

Health-based routing: the router prefers the first replica that is not
mid-restage, not degraded (staging failures pushed its shards onto the
streaming disk path), and under `serve.replica_shed_queue` requests in
flight. Leaving the primary counts `serve.replica_shed` and emits a
`replica_shed` event (on state transitions, not per request); when every
replica of a partition is degraded the least-bad one still serves —
degraded, visibly (`serve.partition_degraded`, `partition_degraded`
event) — never an empty slice of results.

Per-partition refresh: `refresh()` builds every partition's next view
BESIDE the serving table, partition by partition — while one replica
restages, its router sheds to a sibling (or, with R=1, the old view
keeps serving), and every OTHER partition is untouched — then publishes
the finished table with ONE reference assignment. A scatter snapshots
the table once, so a result set can never mix store generations across
partitions: the PR-5 no-mixed-result-sets pin, extended to P views.
Background maintenance (docs/MAINTENANCE.md) composes for free:
compaction and off-path rebuilds land through `SearchService.refresh()`,
which is this build-beside-then-publish swap.

Host simulation vs production: a replica worker thread stands in for one
serving host. On a multi-core host the scatter is real parallelism (the
scan work runs under released-GIL device/numpy calls); on the 1-core
build sandbox wall-clock threads cannot show multi-host scaling, so the
bench's `partitioned_serve` phase uses `simulate()` — sequential
per-partition execution with critical-path accounting (simulated latency
= max over partitions + the measured merge fold), the honest
one-box simulation of P independent hosts.
"""
from __future__ import annotations

import dataclasses
import queue as queue_mod
import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from dnn_page_vectors_tpu.ops.topk import merge_partition_topk
from dnn_page_vectors_tpu.parallel.multihost import partition_shard_ranges
from dnn_page_vectors_tpu.utils.profiling import LatencyStats


@dataclasses.dataclass(frozen=True)
class PartitionSpec:
    """One partition's ownership contract: its contiguous slice of the
    store's shard table (entry dicts frozen at spec time), the shard
    indices that slice implies for the IVF posting files, its live row
    count, and its cut of the hot-posting HBM budget (proportional to
    rows, so a lopsided split doesn't starve the big partition)."""

    pid: int
    entries: Tuple[Dict, ...]
    shard_indices: Tuple[int, ...]
    rows: int
    hot_gb: float


def make_partition_specs(entries: Sequence[Dict], partitions: int,
                         hot_gb: float = 0.0) -> List[PartitionSpec]:
    """Split a shard table into at most `partitions` contiguous,
    row-balanced PartitionSpecs (deterministic: pure arithmetic over the
    table, so every worker/host derives the identical split)."""
    entries = list(entries)
    total = sum(e["count"] for e in entries) or 1
    ranges = partition_shard_ranges([e["count"] for e in entries],
                                    partitions)
    specs = []
    for pid, (lo, hi) in enumerate(ranges):
        part = entries[lo:hi]
        rows = sum(e["count"] for e in part)
        specs.append(PartitionSpec(
            pid=pid, entries=tuple(part),
            shard_indices=tuple(e["index"] for e in part),
            rows=rows, hot_gb=hot_gb * rows / total))
    return specs


class _PartitionReplica:
    """One host-simulated partition worker: a task-queue thread owning an
    independent `_ServeView` over its spec's shard range. Health state
    (restaging flag, queue depth, per-replica stats) is lock-guarded; the
    view itself follows the `_ServeView` swap idiom — replaced by one
    reference assignment, snapshot-read by tasks in flight."""

    _STOP = object()

    def __init__(self, spec: PartitionSpec, rid: int):
        self.spec = spec
        self.rid = rid
        self.view = None                  # _ServeView; swapped by refresh
        self._lock = threading.Lock()
        self._q: "queue_mod.Queue[object]" = queue_mod.Queue()
        self._outstanding = 0             # guarded-by: _lock
        self._restaging = False           # guarded-by: _lock
        self.requests = 0                 # guarded-by: _lock
        self.scan_bytes = 0               # guarded-by: _lock
        self.lat = LatencyStats()         # guarded-by: _lock
        # the worker thread handle itself is only touched by the owner
        # (start here, join in close) — no lock
        self._t = threading.Thread(
            target=self._run, daemon=True,
            name=f"serve-part{spec.pid}r{rid}")
        self._t.start()

    # -- health ------------------------------------------------------------
    @property
    def restaging(self) -> bool:
        with self._lock:
            return self._restaging

    def set_restaging(self, flag: bool) -> None:
        with self._lock:
            self._restaging = bool(flag)

    @property
    def queue_depth(self) -> int:
        with self._lock:
            return self._outstanding

    @property
    def degraded(self) -> bool:
        """Staging failures pushed shards onto the streaming disk path
        (or no view yet): this replica answers, but slowly — routing
        prefers a healthy sibling."""
        view = self.view
        return view is None or bool(view.stream_entries)

    # -- work --------------------------------------------------------------
    def submit(self, fn) -> Future:
        fut: Future = Future()
        with self._lock:
            self._outstanding += 1
        self._q.put((fn, fut))
        return fut

    def run_inline(self, fn):
        """Execute one task ON THE CALLER (the bench's host-simulation
        mode): returns (result, seconds). Sequential execution keeps the
        per-partition timing free of same-core thread contention — the
        measured seconds are one simulated host's critical path."""
        t0 = time.perf_counter()
        res = fn()
        dt = time.perf_counter() - t0
        self._record(res, dt)
        return res, dt

    def _record(self, res, dt: float) -> None:
        with self._lock:
            self.requests += 1
            self.lat.add(dt)
            if isinstance(res, tuple) and len(res) == 3:
                self.scan_bytes += int(res[2])

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is self._STOP:
                return
            fn, fut = item
            t0 = time.perf_counter()
            try:
                res = fn()
            except BaseException as e:  # noqa: BLE001 — task errors ride
                fut.set_exception(e)    # the future back to the gather
                res = None
            else:
                fut.set_result(res)
            dt = time.perf_counter() - t0
            with self._lock:
                self._outstanding -= 1
            self._record(res, dt)

    def stats(self) -> Dict:
        with self._lock:
            return {
                "replica": self.rid,
                "requests": self.requests,
                "p50_ms": round(self.lat.percentile_ms(50), 3),
                "p99_ms": round(self.lat.percentile_ms(99), 3),
                "scan_bytes": self.scan_bytes,
                "queue": self._outstanding,
                "restaging": self._restaging,
                "degraded": self.degraded,
            }

    def close(self) -> None:
        self._q.put(self._STOP)
        self._t.join()


class PartitionSet:
    """P partitions x R replicas behind one scatter-gather router."""

    def __init__(self, svc, store, partitions: int, replicas: int,
                 shed_queue: int = 8):
        self._svc = svc
        self._shed_queue = max(0, int(shed_queue))
        specs = make_partition_specs(store.shards(), partitions,
                                     hot_gb=svc._hot_gb)
        self.partitions = len(specs)
        self.replicas = max(1, int(replicas))
        # the replica grid only ever GROWS (resize never removes rows):
        # an in-flight scatter that captured a wider table keeps routing
        # into the tail rows until it finishes — rows beyond
        # self.partitions are simply never routed by new scatters
        self._parts: List[List[_PartitionReplica]] = []
        table: List[tuple] = []
        for spec in specs:
            reps, row = [], []
            for rid in range(self.replicas):
                rep = _PartitionReplica(spec, rid)
                # each replica stages an INDEPENDENT view (its own device
                # arrays, its own restricted index) — the host simulation
                # of R copies on R hosts
                rep.view = svc._build_view(store, entries=list(spec.entries),
                                           hot_gb=spec.hot_gb)
                reps.append(rep)
                row.append(rep.view)
            self._parts.append(reps)
            table.append(tuple(row))
        # THE generation-consistency anchor: every scatter snapshots this
        # table once, and refresh() publishes a fully-built replacement
        # with one reference assignment — so one query's result set can
        # never mix store generations ACROSS partitions (the PR-5
        # no-mixed-result-sets pin, extended to P views)
        self._view_table = tuple(table)
        self._route_lock = threading.Lock()
        self._sheds = [0] * self.partitions        # guarded-by: _route_lock
        self._degraded_serves = [0] * self.partitions  # guarded-by: _route_lock
        self._last_health: Dict[int, tuple] = {}   # guarded-by: _route_lock
        # liveness oracle (docs/SERVING.md "Network front end"): when a
        # WorkerGateway is attached, (pid, rid) -> is that replica's
        # partition worker alive (registered + heartbeating)? None = the
        # in-process default, every replica counts as live. Swapped by
        # one reference assignment (set_liveness), snapshot-read per call.
        self._liveness = None
        # creation timestamp: written once here, read-only afterwards
        self._t0 = time.perf_counter()

    def primary_view(self):
        """Partition 0's primary view — the service's control view (its
        store-level fields are identical on every view)."""
        return self._parts[0][0].view

    def specs(self) -> List[PartitionSpec]:
        return [reps[0].spec for reps in self._parts]

    # -- routing -----------------------------------------------------------
    def set_liveness(self, fn) -> None:
        """Install (or clear, with None) the worker-liveness oracle:
        `fn(pid, rid) -> bool`. With a gateway attached, routing health
        derives from worker liveness (registration + heartbeats) on top
        of the in-process flags (docs/SERVING.md "Network front end")."""
        self._liveness = fn

    def _alive(self, pid: int, rid: int) -> bool:
        fn = self._liveness
        return True if fn is None else bool(fn(pid, rid))

    def _route(self, pid: int) -> _PartitionReplica:
        """Pick the replica that answers partition `pid`'s next request.
        Preference order: live + healthy (worker heartbeating, serving
        its HBM view, not restaging, under the queue budget) >
        live-but-over-budget > healthy-with-a-dead-worker (serves its
        LOCAL view — the gateway's fallback) > degraded. Leaving the
        primary is a shed (counted; `replica_shed` event on transitions,
        reason restaging/degraded/liveness/queue); serving on a degraded
        replica because every sibling is degraded too is a
        `partition_degraded` — the never-empty fallback the availability
        contract demands."""
        reps = self._parts[pid]
        primary = reps[0]
        chosen = None
        degraded_serve = False
        for r in reps:
            if (not r.restaging and not r.degraded
                    and self._alive(pid, r.rid)
                    and r.queue_depth <= self._shed_queue):
                chosen = r
                break
        if chosen is None:
            for r in reps:
                if (not r.restaging and not r.degraded
                        and self._alive(pid, r.rid)):
                    chosen = r
                    break
        if chosen is None:
            # no replica has a LIVE worker: a healthy replica still
            # serves from its local view (the gateway falls back to
            # in-process compute) — healthy local serving is NOT a
            # degraded serve
            for r in reps:
                if not r.restaging and not r.degraded:
                    chosen = r
                    break
        if chosen is None:
            for r in reps:
                if not r.restaging:
                    chosen = r
                    degraded_serve = True
                    break
        if chosen is None:
            # every replica mid-restage: the primary's OLD view is still
            # valid (the swap is atomic) — serve on it
            chosen = primary
            degraded_serve = primary.degraded
        svc = self._svc
        shed = chosen is not primary
        reason = None
        if shed:
            reason = ("restaging" if primary.restaging
                      else "degraded" if primary.degraded
                      else "liveness" if not self._alive(pid, primary.rid)
                      else "queue")
            svc._m_replica_shed.inc()
        if degraded_serve:
            svc._m_partition_degraded.inc()
        state = (chosen.rid, reason, degraded_serve)
        with self._route_lock:
            if shed:
                self._sheds[pid] += 1
            if degraded_serve:
                self._degraded_serves[pid] += 1
            changed = self._last_health.get(pid) != state
            self._last_health[pid] = state
        if changed:
            # events fire on TRANSITIONS, not per request — the ring
            # records the routing change, counters carry the volume
            if shed:
                svc.registry.event("replica_shed", {
                    "partition": pid, "from_replica": primary.rid,
                    "to_replica": chosen.rid, "reason": reason})
            if degraded_serve:
                svc.registry.event("partition_degraded", {
                    "partition": pid, "replica": chosen.rid})
        return chosen

    # -- the scatter-gather ------------------------------------------------
    def topk(self, qv: np.ndarray, n: int, k: int,
             nprobe: Optional[int] = None, predicate=None
             ) -> Tuple[np.ndarray, np.ndarray]:
        """Scatter the (already encoded) query matrix to one routed
        replica per partition, gather each partition's local top-k, fold
        through the partition merge tree. Returns (scores [n, k] fp32,
        page_ids [n, k] int64). `predicate` (index/attrs.py) rides the
        scatter verbatim: each partition intersects it with its own scan
        and the merge fold is predicate-blind — filtered results stay
        byte-identical to the single-view filtered path."""
        svc = self._svc
        qv = np.asarray(qv, np.float32)
        # ONE table snapshot for the whole scatter: every partition
        # answers from the same published generation set, so a refresh
        # landing mid-scatter cannot mix generations across partitions.
        # The scatter WIDTH also derives from the snapshot (not from
        # self.partitions): an elastic resize() publishing mid-scatter
        # can therefore never mix partition splits inside one result set
        # — the PR-14 no-mixed-generations pin, extended to splits
        table = self._view_table
        with svc._stage("scatter", partitions=len(table)):
            futs = []
            for pid in range(len(table)):
                rep = self._route(pid)
                view = table[pid][rep.rid]
                futs.append(rep.submit(
                    lambda v=view: svc._topk_view(v, qv, n, k, nprobe,
                                                  predicate=predicate)))
            parts = [f.result() for f in futs]
        with svc._stage("merge"):
            return merge_partition_topk([(s, i) for s, i, _ in parts])

    def simulate(self, qv: np.ndarray, n: int, k: int,
                 nprobe: Optional[int] = None, predicate=None) -> Dict:
        """Host-simulation mode (bench `partitioned_serve` phase): run
        every partition's task SEQUENTIALLY on the caller, timing each,
        then the merge fold. The simulated per-query latency is the
        critical path max(partition seconds) + merge seconds — what P
        independent hosts would deliver — with the per-partition scan
        bytes alongside. Returns {scores, ids, partition_seconds,
        merge_seconds, critical_path_seconds, scan_bytes}."""
        svc = self._svc
        qv = np.asarray(qv, np.float32)
        table = self._view_table
        parts, times, scans = [], [], []
        for pid in range(len(table)):
            rep = self._route(pid)
            view = table[pid][rep.rid]
            (res, dt) = rep.run_inline(
                lambda v=view: svc._topk_view(v, qv, n, k, nprobe,
                                              predicate=predicate))
            parts.append(res)
            times.append(dt)
            scans.append(int(res[2]))
        t0 = time.perf_counter()
        s, i = merge_partition_topk([(s_, i_) for s_, i_, _ in parts])
        merge_s = time.perf_counter() - t0
        return {
            "scores": s, "ids": i,
            "partition_seconds": times,
            "merge_seconds": merge_s,
            "critical_path_seconds": max(times) + merge_s,
            "scan_bytes": scans,
        }

    # -- rolling refresh (docs/UPDATES.md, per partition) ------------------
    def refresh(self, new_store, update_index: bool = False) -> List[Dict]:
        """Bring every replica onto `new_store`'s current generation:
        each partition's next views build BESIDE the serving table,
        partition by partition (the replica being restaged sheds — its
        router prefers a sibling — and every other partition keeps
        serving untouched: a compaction or off-path rebuild landing
        through here never blocks the fleet), the store-level IVF update
        runs exactly once on the first view built, and the finished table
        publishes with ONE reference assignment — a scatter snapshots the
        table, so no query ever mixes generations across partitions.
        Returns the per-partition restage record."""
        svc = self._svc
        specs = make_partition_specs(new_store.shards(), self.partitions,
                                     hot_gb=svc._hot_gb)
        # shard growth can change the balanced split width; a shrunken
        # table (quarantine) can yield fewer balanced ranges than live
        # partitions: the tail partitions get explicit EMPTY specs — they
        # serve nothing rather than a stale view
        while len(specs) < self.partitions:
            specs.append(PartitionSpec(pid=len(specs), entries=(),
                                       shard_indices=(), rows=0,
                                       hot_gb=0.0))
        out: List[Dict] = []
        first = True
        new_table: List[tuple] = []
        for pid, spec in enumerate(specs):
            reps = self._parts[pid]
            swaps = []
            row = []
            for rep in reps:
                t0 = time.perf_counter()
                rep.set_restaging(True)
                try:
                    row.append(svc._build_view(
                        new_store, reuse=rep.view,
                        update_index=update_index and first,
                        entries=list(spec.entries), hot_gb=spec.hot_gb))
                finally:
                    rep.set_restaging(False)
                first = False
                swaps.append(round((time.perf_counter() - t0) * 1000.0, 3))
            new_table.append(tuple(row))
            out.append({"partition": pid,
                        "shards": list(spec.shard_indices),
                        "rows": spec.rows,
                        "restage_ms": swaps})
        self._view_table = tuple(new_table)  # THE swap: one assignment
        for pid, row in enumerate(new_table):
            for rep, view in zip(self._parts[pid], row):
                # health/compat windows follow the published table; tasks
                # in flight keep the view they captured from the snapshot
                rep.view = view
                rep.spec = specs[pid]
        return out

    # -- elastic re-split (docs/SCALING.md "Scale-out tier") ---------------
    def resize(self, new_store, partitions: int) -> List[Dict]:
        """Re-split the store over a NEW partition width (elastic fleet
        membership: a worker joined or drained). Same build-beside-then-
        publish discipline as refresh(): every partition's view over its
        new contiguous slice builds beside the serving table, then the
        finished table — at the new width — publishes with ONE reference
        assignment. A scatter snapshots the table once and derives its
        width from the snapshot, so no result set ever mixes splits.
        Rows the shrink strands (pid >= new width) stay in the replica
        grid for scatters in flight but are never routed again. Returns
        the per-partition restage record (refresh()'s shape)."""
        svc = self._svc
        specs = make_partition_specs(new_store.shards(),
                                     max(1, int(partitions)),
                                     hot_gb=svc._hot_gb)
        width = len(specs)       # clamped to the shard count
        while len(self._parts) < width:
            pid = len(self._parts)
            reps = [_PartitionReplica(specs[pid], rid)
                    for rid in range(self.replicas)]
            self._parts.append(reps)
            with self._route_lock:
                self._sheds.append(0)
                self._degraded_serves.append(0)
        out: List[Dict] = []
        new_table: List[tuple] = []
        for pid in range(width):
            spec = specs[pid]
            swaps, row = [], []
            for rep in self._parts[pid]:
                t0 = time.perf_counter()
                rep.set_restaging(True)
                try:
                    row.append(svc._build_view(
                        new_store, reuse=rep.view,
                        entries=list(spec.entries), hot_gb=spec.hot_gb))
                finally:
                    rep.set_restaging(False)
                swaps.append(round((time.perf_counter() - t0) * 1000.0, 3))
            new_table.append(tuple(row))
            out.append({"partition": pid,
                        "shards": list(spec.shard_indices),
                        "rows": spec.rows,
                        "restage_ms": swaps})
        self._view_table = tuple(new_table)  # THE swap: one assignment
        self.partitions = width
        for pid in range(width):
            for rep, view in zip(self._parts[pid], new_table[pid]):
                rep.view = view
                rep.spec = specs[pid]
        return out

    # -- telemetry ---------------------------------------------------------
    def stats(self) -> List[Dict]:
        """Per-partition topology + routing health: the metrics() /
        loadtest "partitions" block."""
        elapsed = max(time.perf_counter() - self._t0, 1e-9)
        with self._route_lock:
            sheds = list(self._sheds)
            degr = list(self._degraded_serves)
        out = []
        # bounded by the LIVE width: rows a shrink stranded are not part
        # of the serving topology any more
        for pid, reps in enumerate(self._parts[:self.partitions]):
            rstats = [r.stats() for r in reps]
            out.append({
                "partition": pid,
                "shards": list(reps[0].spec.shard_indices),
                "rows": reps[0].spec.rows,
                "qps": round(sum(r["requests"] for r in rstats) / elapsed,
                             3),
                "p99_ms": max((r["p99_ms"] for r in rstats), default=0.0),
                "sheds": sheds[pid],
                "degraded_serves": degr[pid],
                "replicas": rstats,
            })
        return out

    def close(self) -> None:
        for reps in self._parts:
            for rep in reps:
                rep.close()
