"""The async socket front end (docs/SERVING.md "Network front end").

`SearchService` becomes a network service here and ONLY here: an asyncio
server speaking the `infer/transport.py` length-prefixed protocol —
connection handling on the host event loop, zero change to the device
path. A client connection sends `T_QUERY` (text) or `T_VQUERY` (raw
query vectors) frames and gets back `T_RESULT` (scores/ids/scan bytes),
`T_SHED` (the request was deliberately rejected at admission), or
`T_ERROR`.

Admission control happens AT THE SOCKET, before a request can touch the
micro-batcher (`SearchService._admit`): a deadline that already expired,
or one the windowed queue-wait p99 says cannot be met, is answered with
`T_SHED` immediately — it never consumes queue capacity or a bucket
slot, and it counts in `serve.deadline_shed` (a `deadline_shed` event
rides the ring), never in `serve.errors`. Requests that admit carry
their absolute deadline INTO the batcher, where the micro-batch door
sheds any that expire while queued (docs/SERVING.md).

Protocol robustness: a garbage header, an unknown frame type, or an
oversize length is REJECTED — one best-effort `T_ERROR` frame, then the
connection closes. Truncation mid-frame closes the connection. A
malformed peer can never park a handler coroutine on a half-read frame.

Tracing: every request runs under a root span opened AT THE SOCKET
(`socket` span, protocol + query count attrs). The dispatch hops to an
executor thread with an explicit `tracer.use` hand-off, so the
micro-batcher's captured context — and therefore the grafted
queue_wait/dispatch subtree — hangs under the socket root: one span tree
from the accept to the device dispatch and back
(docs/OBSERVABILITY.md)."""
from __future__ import annotations

import asyncio
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional, Tuple

import numpy as np

from dnn_page_vectors_tpu.infer import transport
from dnn_page_vectors_tpu.infer.serve import _compile_filters
from dnn_page_vectors_tpu.infer.transport import (
    DeadlineExceeded, FrameError, FLAG_FILTERS, FLAG_RESULT_CACHE,
    FLAG_WIRE_COMPRESS,
    T_CACHE_LOOKUP, T_CACHE_PUT, T_HELLO, T_QUERY, T_RESULT, T_RESULT_C,
    T_SHED, T_ERROR, T_VQUERY, T_VQUERY_PUT, T_VQUERY_REF)


def parse_listen(listen: str) -> Tuple[str, int]:
    """'host:port' -> (host, port); port 0 = ephemeral."""
    host, _, port = str(listen).rpartition(":")
    return host or "127.0.0.1", int(port or 0)


def _results_to_arrays(results: List[List[dict]], k: int
                       ) -> Tuple[np.ndarray, np.ndarray]:
    """Formatted per-query result lists -> fixed [n, k] score/id arrays
    (-1-id padding past each query's real hit count)."""
    n = len(results)
    scores = np.zeros((n, k), np.float32)
    ids = np.full((n, k), -1, np.int64)
    for qi, res in enumerate(results):
        for slot, hit in enumerate(res[:k]):
            scores[qi, slot] = hit["score"]
            ids[qi, slot] = hit["page_id"]
    return scores, ids


class SearchServer:
    """Asyncio front end over one `SearchService`. Run it on the caller's
    loop (`await start()`) or host it on a background thread
    (`start_background()` — the cli/loadgen shape; `close()` stops it)."""

    def __init__(self, svc, host: Optional[str] = None,
                 port: Optional[int] = None, executor_workers: int = 32,
                 front_end: int = 0):
        serve_cfg = getattr(svc.cfg, "serve", None)
        listen = (getattr(serve_cfg, "listen", "127.0.0.1:0")
                  if serve_cfg is not None else "127.0.0.1:0")
        cfg_host, cfg_port = parse_listen(listen)
        self.svc = svc
        # which front end of a scale-out tier this is (docs/SCALING.md
        # "Scale-out tier"): purely an identity label — it threads into
        # thread names and per-front-end trial records so N otherwise
        # interchangeable servers stay tellable apart in telemetry
        self.front_end = int(front_end)
        self.host = host if host is not None else cfg_host
        self.port = port if port is not None else cfg_port
        # serve.wire_compress gates what this end ADVERTISES: with it off
        # every connection negotiates down to the raw frames
        self._compress = bool(getattr(serve_cfg, "wire_compress", True)
                              if serve_cfg is not None else True)
        # fleet result-cache sharing (docs/SERVING.md "Result cache"):
        # advertised only when the service actually runs the cache —
        # a peer that negotiates the flag gets CACHE_LOOKUP / CACHE_PUT
        # answered from / into the service's generation-keyed cache
        self._rcache = bool(getattr(svc, "_rcache_fleet", False))
        # filtered retrieval (docs/ANN.md "Filtered retrieval"):
        # serve.filters gates ADVERTISING the capability; decoding stays
        # unconditional (negotiation governs what a peer sends)
        self._filters = bool(getattr(svc, "_filters_enabled", True))
        self._executor = ThreadPoolExecutor(
            max_workers=executor_workers,
            thread_name_prefix="serve-socket")
        self._server: Optional[asyncio.AbstractServer] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        # graceful-drain state, touched only on the event loop: close()
        # flips _draining, in-flight dispatches finish, fresh requests
        # shed with reason "draining" instead of dying mid-frame
        self._draining = False
        self._inflight = 0

    # -- lifecycle ---------------------------------------------------------
    async def start(self) -> "SearchServer":
        self._loop = asyncio.get_running_loop()
        self._server = await asyncio.start_server(self._handle, self.host,
                                                  self.port)
        self.host, self.port = self._server.sockets[0].getsockname()[:2]
        return self

    def start_background(self) -> "SearchServer":
        """Host the server on its own event-loop thread; returns once the
        listener is bound (self.port carries the ephemeral port)."""
        started = threading.Event()
        failed: List[BaseException] = []

        def _run() -> None:
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            self._loop = loop
            try:
                server = loop.run_until_complete(asyncio.start_server(
                    self._handle, self.host, self.port))
            except BaseException as e:  # noqa: BLE001 — surface bind errors
                failed.append(e)
                started.set()
                loop.close()
                return
            self._server = server
            self.host, self.port = server.sockets[0].getsockname()[:2]
            started.set()
            try:
                loop.run_forever()
            finally:
                server.close()
                loop.run_until_complete(server.wait_closed())
                loop.close()

        self._thread = threading.Thread(
            target=_run, daemon=True,
            name=f"serve-socket-loop-fe{self.front_end}")
        self._thread.start()
        started.wait()
        if failed:
            raise failed[0]
        return self

    def close(self, drain_s: float = 5.0) -> None:
        """Graceful shutdown: stop accepting, DRAIN in-flight requests —
        dispatches already on the executor finish and answer normally,
        fresh frames arriving on open connections shed with reason
        "draining" — then cancel the idle per-connection readers. A
        close never drops a socket mid-frame on a request the service
        already accepted; `drain_s` bounds how long a slow in-flight
        dispatch can hold the shutdown."""
        loop = self._loop
        if loop is not None and self._thread is not None:
            async def _shutdown() -> None:
                # stop accepting; flip draining BEFORE waiting so frames
                # that race the close get a clean SHED answer
                self._draining = True
                if self._server is not None:
                    self._server.close()
                    await self._server.wait_closed()
                t_end = loop.time() + max(drain_s, 0.0)
                while self._inflight > 0 and loop.time() < t_end:
                    await asyncio.sleep(0.005)
                # idle handler tasks (parked on client reads) cancel
                # last — a close must not leak destroyed-pending tasks
                tasks = [t for t in asyncio.all_tasks()
                         if t is not asyncio.current_task()]
                for t in tasks:
                    t.cancel()
                await asyncio.gather(*tasks, return_exceptions=True)

            try:
                asyncio.run_coroutine_threadsafe(
                    _shutdown(), loop).result(timeout=drain_s + 10.0)
            except Exception:  # noqa: BLE001 — stop the loop regardless
                pass
            loop.call_soon_threadsafe(loop.stop)
            self._thread.join(timeout=10.0)
            self._thread = None
        self._executor.shutdown(wait=False)

    # -- per-connection handler -------------------------------------------
    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        svc = self.svc
        flags = 0           # negotiated capabilities (T_HELLO handshake)
        slots = None        # per-connection intern table (slot -> block)
        try:
            while True:
                frame = await transport.read_frame_async(reader)
                if frame is None:
                    break
                ftype, payload = frame
                actual = transport.HEADER.size + len(payload)
                svc._m_wire_bytes.inc(actual)
                svc._m_wire_raw.inc(actual)
                if ftype == T_HELLO:
                    want = transport.decode_hello(payload)
                    mask = ((FLAG_WIRE_COMPRESS if self._compress else 0)
                            | (FLAG_RESULT_CACHE if self._rcache else 0)
                            | (FLAG_FILTERS if self._filters else 0))
                    flags = want & mask
                    if flags & FLAG_WIRE_COMPRESS and slots is None:
                        slots = {}
                    await self._write(writer, T_HELLO,
                                      transport.encode_hello(flags))
                    continue
                if ftype == T_CACHE_LOOKUP and flags & FLAG_RESULT_CACHE:
                    # pure probe: a hit answers straight from the
                    # generation-keyed cache (no admission, no bucket
                    # slot), a miss answers SHED_CACHE_MISS — the peer
                    # falls back to computing locally, never errors
                    ck = transport.decode_cache_lookup(payload)
                    got = svc._result_cache_wire_get(ck)
                    if got is None:
                        await self._write(writer, T_SHED,
                                          transport.encode_shed(
                                              ck.req_id,
                                              transport.SHED_CACHE_MISS,
                                              "cache_miss"))
                    elif flags & FLAG_WIRE_COMPRESS:
                        await self._write(
                            writer, T_RESULT_C,
                            transport.encode_result_c(ck.req_id, got[0],
                                                      got[1]),
                            raw_len=transport.result_raw_bytes(
                                *got[0].shape))
                    else:
                        await self._write(writer, T_RESULT,
                                          transport.encode_result(
                                              ck.req_id, got[0], got[1]))
                    continue
                if ftype == T_CACHE_PUT and flags & FLAG_RESULT_CACHE:
                    # fire-and-forget fill: NO response frame (the wire
                    # contract — the sender never reads one). The service
                    # validates the key's generations against its live
                    # view and silently drops a stale push.
                    ck, pscores, pids = transport.decode_cache_put(payload)
                    svc._result_cache_wire_put(ck, pscores, pids)
                    continue
                if ftype in (T_QUERY, T_VQUERY, T_VQUERY_PUT, T_VQUERY_REF):
                    if self._draining:
                        # graceful drain: the request is readable (so
                        # the peer is not left mid-frame) but the
                        # service is going away — shed, don't serve
                        # every request head leads with the u64 req id
                        rid = (transport._ERROR_HEAD.unpack_from(payload)[0]
                               if len(payload) >= 8 else 0)
                        svc._shed_deadline("draining", None)
                        await self._write(writer, T_SHED,
                                          transport.encode_shed(
                                              rid, transport.SHED_DRAINING,
                                              "draining"))
                        continue
                    if ftype == T_QUERY:
                        req = transport.decode_query(payload)
                        await self._answer(writer, req, vectors=False,
                                           flags=flags)
                    else:
                        req = transport.decode_vquery_any(ftype, payload,
                                                          slots)
                        if ftype == T_VQUERY_REF:
                            # raw-equivalent accounting: this frame
                            # REPLACED a full query block on the wire
                            svc._m_wire_raw.inc(req.qv.nbytes
                                                - transport._SLOT.size)
                        await self._answer(writer, req, vectors=True,
                                           flags=flags)
                else:
                    await self._write(writer, T_ERROR, transport.encode_error(
                        0, f"unexpected frame type {ftype} on a client "
                           "connection"))
                    break
        except FrameError as e:
            # the reject path the fuzz tests pin: one best-effort error
            # frame, then the connection CLOSES — never a hung peer
            try:
                await self._write(writer, T_ERROR,
                                  transport.encode_error(0, str(e)))
            except (ConnectionError, OSError):
                pass
        except asyncio.CancelledError:
            pass                  # server shutdown cancels idle handlers
        except (ConnectionError, OSError):
            pass
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except (asyncio.CancelledError, ConnectionError, OSError):
                pass

    async def _write(self, writer: asyncio.StreamWriter, ftype: int,
                     payload: bytes, raw_len: Optional[int] = None) -> None:
        frame = transport.pack_frame(ftype, payload)
        writer.write(frame)
        self.svc._m_wire_bytes.inc(len(frame))
        self.svc._m_wire_raw.inc(len(frame) if raw_len is None else raw_len)
        await writer.drain()

    async def _answer(self, writer: asyncio.StreamWriter, req,
                      vectors: bool, flags: int = 0) -> None:
        svc = self.svc
        n = req.qv.shape[0] if vectors else len(req.queries)
        k = req.k or svc.cfg.eval.recall_k
        nprobe = req.nprobe or None
        loop = asyncio.get_running_loop()
        # the span tree starts AT THE SOCKET: the executor hop below
        # re-activates this root on the dispatch thread, so the batcher's
        # captured context (queue_wait + the shared dispatch subtree)
        # hangs under it
        with svc.tracer.trace("socket",
                              protocol="vquery" if vectors else "query",
                              n_queries=n, k=k) as root:
            deadline = svc.default_deadline(
                req.deadline_ms if req.deadline_ms > 0 else None)
            # in-flight covers the ANSWER write too: a graceful drain
            # waits until the response frame left, never mid-write
            self._inflight += 1
            try:
                try:
                    scores, ids, scan = await loop.run_in_executor(
                        self._executor,
                        lambda: self._dispatch_blocking(root, req, vectors,
                                                        n, k, nprobe,
                                                        deadline))
                except DeadlineExceeded as e:
                    await self._write(writer, T_SHED, transport.encode_shed(
                        req.req_id, transport.SHED_DEADLINE, str(e)))
                    return
                except Exception as e:  # noqa: BLE001 — per-request
                    # isolation
                    await self._write(writer, T_ERROR,
                                      transport.encode_error(
                                          req.req_id,
                                          f"{type(e).__name__}: {e}"))
                    return
                if flags & FLAG_WIRE_COMPRESS:
                    await self._write(
                        writer, T_RESULT_C,
                        transport.encode_result_c(req.req_id, scores, ids,
                                                  scan_bytes=scan),
                        raw_len=transport.result_raw_bytes(*scores.shape))
                else:
                    await self._write(writer, T_RESULT,
                                      transport.encode_result(
                                          req.req_id, scores, ids,
                                          scan_bytes=scan))
            finally:
                self._inflight -= 1

    def _dispatch_blocking(self, root, req, vectors: bool, n: int, k: int,
                           nprobe: Optional[int],
                           deadline: Optional[float]):
        """The blocking half, on an executor thread: admission, then the
        batcher (single text query) or a direct dispatch; records the
        request into the windowed serving instruments exactly once."""
        svc = self.svc
        with svc.tracer.use(root):
            # compile the frame's predicate ONCE (canonicalizes whatever
            # text the client sent); a malformed predicate raises
            # FilterError here -> one T_ERROR answer, nothing admitted
            pred = _compile_filters(req.filters)
            # result-cache probe at the admission door (docs/SERVING.md
            # "Result cache"): a repeated text query answers before
            # _admit can shed it or a bucket slot is consumed
            if not vectors and n == 1:
                rkey = svc._result_cache_key(req.queries[0], req.k or None,
                                             nprobe, filters=pred)
                if rkey is not None:
                    t0 = time.perf_counter()
                    hits = svc._result_cache_get(rkey, count=False)
                    if hits is None:
                        hits = svc._peer_lookup(rkey)
                    if hits is not None:
                        svc._m_rcache_hits.inc()
                        svc._m_requests.inc()
                        svc._m_latency.observe(
                            (time.perf_counter() - t0) * 1000.0)
                        scores, ids = _results_to_arrays([hits], k)
                        return scores, ids, 0
                    svc._m_rcache_misses.inc()
            # admission control at the door (raises DeadlineExceeded;
            # already counted + evented by _admit)
            svc._admit(deadline)
            t0 = time.perf_counter()
            try:
                if vectors:
                    out = svc.topk_vectors(req.qv, k=k, nprobe=nprobe,
                                           deadline=deadline, filters=pred)
                    scores, ids = out[0], out[1]
                    scan = int(out[2]) if len(out) > 2 else 0
                elif svc._batcher is not None and n == 1:
                    res = [svc._batcher.submit(
                        req.queries[0], req.k or None, nprobe,
                        deadline=deadline,
                        filters=pred.text if pred is not None
                        else None).result()]
                    scores, ids = _results_to_arrays(res, k)
                    scan = 0
                else:
                    res = svc.search_many(list(req.queries),
                                          k=req.k or None, nprobe=nprobe,
                                          filters=pred,
                                          _record=False, deadline=deadline)
                    scores, ids = _results_to_arrays(res, k)
                    scan = 0
            except DeadlineExceeded:
                # shed at the micro-batch door: counted there, not an
                # error
                raise
            except BaseException:
                svc._m_errors.inc(n)
                raise
            svc._m_requests.inc(n)
            svc._m_latency.observe((time.perf_counter() - t0) * 1000.0, n=n)
            return scores, ids, scan


def serve_in_background(svc, host: Optional[str] = None,
                        port: Optional[int] = None,
                        front_end: int = 0) -> SearchServer:
    """One-call server hosting for cli/bench/tests: binds (serve.listen
    unless overridden), runs the loop on a daemon thread, returns the
    handle (`.host` / `.port` / `.close()`). `front_end` labels this
    server's slot in a scale-out tier (cli loadtest --front-ends)."""
    return SearchServer(svc, host=host, port=port,
                        front_end=front_end).start_background()
