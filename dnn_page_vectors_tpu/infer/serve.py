"""Query-time retrieval service (the serving half of call stack §4.3).

`cli.py search` originally rebuilt the corpus, tokenizer, and model per
invocation — fine as a demo, not a serving path (VERDICT r3 Weak #6).
SearchService is the serving path: everything is loaded ONCE (params on
device, store shards optionally pre-staged in HBM), so per-query cost is
one tokenize + one compiled encode + MXU top-k over resident vectors.

Throughput layer (docs/SERVING.md): the compiled encode/top-k programs are
BATCH-shaped (`query_batch` rows), so one-query-at-a-time serving wastes
most of every dispatch on padding. Three mechanisms recover that width:

  * `search_many(queries, k)` — vectorized multi-query search: one
    encode_batch over up to `query_batch` real queries, one fused per-shard
    top-k + device merge, one packed transfer, results split per query;
    larger lists tile over full buckets (one compiled shape throughout).
  * a dynamic micro-batcher (`serve.batch_window_ms` / `serve.max_batch`,
    start_batcher()): concurrent search() callers enqueue onto a bounded
    queue, a dispatcher thread coalesces whatever arrived within the window
    into one search_many dispatch, and per-request futures carry results
    (or exactly the failing request's exception) back to the callers. A
    lone caller pays at most one window of extra latency; under load the
    bucket fills and aggregate QPS scales toward bucket width.
  * an LRU query-embedding cache (`serve.query_cache_size`, keyed on
    whitespace-normalized query text + the store's model step): repeat
    queries skip tokenize+encode entirely; a store re-stamp
    (ensure_model_step / model reload) changes the key and invalidates
    every entry. Hit/miss counters surface through metrics().

ANN routing (docs/ANN.md): with `serve.index = "ivf"` queries route
through the inverted-file index (index/ivf.py) — centroid scan +
top-`serve.nprobe` posting-list gather + exact on-device re-rank, cost
~nprobe/nlist of the exact sweep — with automatic PER-REQUEST fallback to
the exact path when the index is missing, stale against the store's model
step, or quarantined. `ann_lists_scanned` / `ann_candidates_reranked` /
`ann_fallbacks` and the active index config surface through metrics().
The default `serve.index = "exact"` keeps the pre-index paths below
byte-identical. On a PQ index (built with `cli index --pq`, docs/ANN.md)
the candidate gather moves m-byte codes with on-device ADC scoring and
an exact re-rank, and `serve.hot_postings_gb` stages the hot posting
set's codes to device at view build time — resident lists answer with
zero per-request host gather (`ann_gather_bytes` measures what moves).

Partitioned + replicated serving (docs/SCALING.md "Partitioned serving"):
`serve.partitions` > 1 splits the shard table into P contiguous
partitions — each owning its shard range, its slice of the IVF posting
lists, and its cut of the hot-posting HBM budget — host-simulated as
per-partition worker threads each owning an independent `_ServeView`
(infer/partition.py). search_many becomes a scatter-gather: the coalesced
bucket's query matrix broadcasts once, every partition answers its local
top-k over only its rows (per-query scan bytes drop ~1/P, partitions run
concurrently), and results fold through the ops/topk.py partition merge
tree (`merge_topk_host` as the final host fold). `serve.replicas` adds R
copies of each partition with health-based routing: a replica mid-restage,
degraded to the streaming path, or past `serve.replica_shed_queue` sheds
to its siblings (`replica_shed` event); a partition whose replicas are
ALL degraded serves degraded locally (`partition_degraded`) — never an
empty result slice. refresh() restages partition by partition (one
partition's restage — or maintenance swapping in compaction/rebuild
results — never blocks the others) and publishes the finished view table
with one atomic reference assignment, so a scatter never mixes store
generations across partitions. P = R = 1 (the default) keeps the
single-view paths below byte-identical.

HBM pre-staging: when the store fits the configured budget, every shard is
device_put once (row-sharded over the mesh 'data' axis, padded to one
static shape so a single compiled top-k program serves all shards) and
page vectors never touch disk. Oversized stores transparently fall back to
the streaming path (ops/topk.py:topk_over_store) — same results, per-query
disk reads double-buffered behind a reader thread.

Live updates (docs/UPDATES.md): everything a corpus update can change —
the store handle with its generation chain and tombstones, the staged HBM
shards, the id table, the IVF index — lives in ONE immutable view object
(`_ServeView`). `refresh()` builds the next view off to the side (restaging
only the appended shards, updating the index incrementally) and publishes
it with a single reference assignment: in-flight search_many buckets
finish on the view they captured, the next bucket sees the new corpus —
zero downtime, no dropped futures, never a mixed result set. metrics()
reports `store_generation` / `index_generation` / `docs_appended` /
`tombstoned` / `incremental_updates` / `full_rebuilds`. Restaging is
tombstone-aware (`updates.restage_tombstone_density`): a staged shard
whose only drift is a few new tombstones is reused with the dead rows
masked in its id table, and restages compacted once the staged block's
dead density crosses the threshold (`restage_skipped`/`restage_forced`).

Observability (docs/OBSERVABILITY.md): every search/search_many call runs
under a request-scoped trace (utils/tracing.py) — a span tree covering
queue_wait (through the micro-batcher's thread hop, handed off explicitly)
-> tokenize/encode (cache hits annotated) -> topk (ANN lists_scanned /
gather_bytes / rows_reranked as span attributes) -> merge -> format; a
request slower than `obs.slow_ms` lands, tree and all, in the bounded
slow-query log, and `cli trace` exports the recent ring as Chrome/Perfetto
trace_event JSON. Serving counters live in a per-service MetricsRegistry
(utils/telemetry.py): windowed qps/error-rate/cache-hit/p99 over the last
`obs.window_s` seconds next to the since-boot totals, lifecycle events
(view hot-swap, shard quarantine, drift rebuild, degraded/restored) with
trace-id correlation, and a Prometheus-text + JSON snapshot exposition
(`cli serve-metrics`, the `:metrics` control line).

Degradation (docs/ROBUSTNESS.md): a shard that FAILS to stage — an I/O
fault during the device_put, a checksum mismatch, or the HBM budget
overrunning mid-stage — does not kill the service. Checksum failures are
quarantined (the store drops them); every other failure falls back
PER-SHARD to the streaming top-k path: staged shards answer from HBM, the
failed ones are re-read from disk and merged on host — ONCE PER COALESCED
BATCH, not once per query, so degraded-mode disk traffic amortizes over
the batch exactly like the device dispatches do. The service marks itself
`degraded`, bumps fault counters, and reports both through the metrics
log, so a half-staged service is visible, not silent.
"""
from __future__ import annotations

import contextlib
import queue as queue_mod
import threading
import time
from collections import OrderedDict
from concurrent.futures import Future
from typing import Dict, List, Optional, Sequence

import numpy as np

from dnn_page_vectors_tpu.infer.bulk_embed import BulkEmbedder
from dnn_page_vectors_tpu.infer.transport import DeadlineExceeded
from dnn_page_vectors_tpu.infer.vector_store import VectorStore, read_ahead
from dnn_page_vectors_tpu.ops.topk import (
    merge_shard_topk, sharded_topk, stage_shard, topk_over_store)
from dnn_page_vectors_tpu.utils import faults
from dnn_page_vectors_tpu.utils.profiling import LatencyStats, PipelineProfiler
from dnn_page_vectors_tpu.utils.telemetry import MetricsRegistry
from dnn_page_vectors_tpu.utils.tracing import Tracer


class _MicroBatcher:
    """Dynamic request coalescing for SearchService.search().

    Callers enqueue (query, k, Future) onto a bounded queue; ONE dispatcher
    thread pulls the first pending request, waits up to `window_ms` for
    more (never past `max_batch`), and answers the whole batch with one
    search_many call per distinct k. The bounded queue backpressures
    callers when the dispatcher falls behind instead of buffering
    unboundedly — the serving analogue of the bulk-embed writer's pending
    budget.

    Failure isolation: when a coalesced dispatch raises (one poisoned
    query must not fail its batch-mates), the batch is retried one request
    at a time so the exception lands on exactly the failing request's
    future; the rest still get results.

    The coalescing window is read from the service PER BATCH (`window_s`
    callable): with `serve.batch_window_adaptive` the AdaptiveWindow
    controller moves it between the configured base and
    `serve.batch_window_max_ms` off the windowed queue-wait p99, and every
    measured queue wait feeds the serve.queue_wait_ms instrument the
    controller reads — the control loop closes through the registry, not
    through ad-hoc state.
    """

    _STOP = object()

    def __init__(self, svc: "SearchService", window_s, max_batch: int,
                 max_queue: int):
        self._svc = svc
        self._window_s = window_s            # () -> seconds, read per batch
        self._max = max(1, int(max_batch))
        self._q: "queue_mod.Queue[object]" = queue_mod.Queue(
            maxsize=max(self._max, int(max_queue)))
        self.batch_sizes: List[int] = []     # dispatch telemetry
        self._t = threading.Thread(target=self._run, daemon=True,
                                   name="serve-batcher")
        self._t.start()

    def submit(self, query: str, k: Optional[int],
               nprobe: Optional[int] = None,
               deadline: Optional[float] = None,
               filters: Optional[str] = None) -> Future:
        """Enqueue one request. `deadline` is ABSOLUTE on the service
        clock (svc._clock); admission-time shedding (expired / SLO
        budget) happens in the CALLER (`SearchService._admit`) before
        anything touches this queue — an already-hopeless request must
        never consume queue capacity or a bucket slot. `filters` is the
        CANONICAL predicate text (index/attrs.py) or None: coalescing
        groups per distinct (k, nprobe, filters), so a filtered request
        can never share a dispatch with a differently-filtered one."""
        fut: Future = Future()
        # capture the caller's active span HERE: the dispatcher runs on
        # another thread where the contextvar chain breaks, so the trace
        # context rides the queue explicitly (docs/OBSERVABILITY.md)
        ctx = self._svc.tracer.current()
        self._q.put((query, (k, nprobe, filters), fut, time.perf_counter(),
                     ctx, deadline))
        return fut

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is self._STOP:
                return
            batch = [item]
            deadline = time.perf_counter() + max(0.0, self._window_s())
            while len(batch) < self._max:
                rem = deadline - time.perf_counter()
                try:
                    nxt = (self._q.get_nowait() if rem <= 0
                           else self._q.get(timeout=rem))
                except queue_mod.Empty:
                    break
                if nxt is self._STOP:
                    self._dispatch(batch)
                    return
                batch.append(nxt)
            self._dispatch(batch)
            self._svc._adapt_window()

    def _dispatch(self, batch) -> None:
        svc = self._svc
        tracer = svc.tracer
        # THE DOOR (docs/SERVING.md "Network front end"): a request whose
        # deadline expired while it queued is rejected here, BEFORE it
        # can occupy a bucket slot — its caller gets DeadlineExceeded now
        # instead of a result that arrives too late to use, and the
        # requests that can still make their deadlines dispatch in a
        # smaller (= faster) bucket. Shed requests are excluded from the
        # queue-wait instrument: they never dispatched, so their waits
        # must not steer the adaptive-window controller.
        live = []
        for item in batch:
            deadline = item[5]
            if deadline is not None and svc._clock() >= deadline:
                item[2].set_exception(
                    svc._shed_deadline("expired_in_queue", deadline,
                                       trace=item[4]))
            else:
                live.append(item)
        if not live:
            return
        batch = live
        now = time.perf_counter()
        for _, _, _, t0, ctx, _ in batch:
            svc.profiler.add("queue_wait", now - t0)
            svc._m_queue_wait.observe((now - t0) * 1000.0)
            if ctx is not None:
                # finished child stamped onto the REQUEST's tree: how long
                # this request sat in the queue before its dispatch
                ctx.child("queue_wait", now - t0, t0=t0)
        # graftcheck: off=locks -- single-writer: only the dispatcher
        # thread appends; readers consume after stop() joins the thread
        self.batch_sizes.append(len(batch))
        by_key: Dict[tuple, list] = {}
        for query, key, fut, _, ctx, deadline in batch:
            by_key.setdefault(key, []).append((query, fut, ctx, deadline))
        for (k, nprobe, ftext), items in by_key.items():
            # the shared dispatch honors the TIGHTEST deadline of the
            # coalesced group: the RPC fan-out budgets per-partition
            # waits against it
            deadlines = [d for _, _, _, d in items if d is not None]
            group_dl = min(deadlines) if deadlines else None
            try:
                # the coalesced dispatch traces ONCE under a detached root
                # (record=False: it only exists grafted into request
                # trees), then every request adopts the finished subtree —
                # one measurement, N complete span trees
                with tracer.trace("dispatch", record=False,
                                  batch_size=len(items)) as dsp:
                    res = svc.search_many(
                        [q for q, _, _, _ in items], k=k, nprobe=nprobe,
                        filters=ftext, _record=False, deadline=group_dl)
            except BaseException:  # noqa: BLE001 — isolate per request
                for q, fut, ctx, deadline in items:
                    try:
                        # per-request retry: re-activate the caller's span
                        # on THIS thread so retry spans nest under it
                        with tracer.use(ctx):
                            fut.set_result(svc.search_many(
                                [q], k=k, nprobe=nprobe, filters=ftext,
                                _record=False, deadline=deadline)[0])
                    except BaseException as e:  # noqa: BLE001
                        fut.set_exception(e)
                continue
            for (_, fut, ctx, _), r in zip(items, res):
                if ctx is not None:
                    ctx.adopt(dsp)
                fut.set_result(r)

    def close(self) -> None:
        self._q.put(self._STOP)
        self._t.join()


class AdaptiveWindow:
    """Telemetry-driven micro-batch window controller (docs/SERVING.md).

    The fixed `serve.batch_window_ms` is a compromise: too narrow and a
    loaded service dispatches half-empty buckets, too wide and a lone
    caller pays the whole window as latency. This controller moves the
    window between `base_ms` and `max_ms` off ONE signal, the windowed
    queue-wait p99 from the serve.queue_wait_ms histogram (the PR-7
    registry, not wall-clock re-derivation):

      * pressure — queue-wait p99 >= `pressure_ratio` x the current
        window (requests are stacking behind in-flight dispatches, not
        just riding out the window) -> double the window, capped at
        `max_ms`. Wider window = fuller buckets = fewer dispatches per
        second = the queue drains.
      * idle — no queue-wait samples in the rolling window, or a p99
        below `idle_ratio` x the current window -> halve back toward
        `base_ms`, so the next lone caller pays base latency again.

    Note the discriminator: a lone caller's queue wait ~= the window
    itself (it sits in the batch while the dispatcher waits out the
    window), which lands BETWEEN the idle and pressure thresholds — a
    quiet trickle of traffic holds the window steady instead of
    oscillating. Every change sets the serve.batch_window_ms gauge and
    emits a `window_adapt` event with the p99 that drove it."""

    def __init__(self, base_ms: float, max_ms: float, queue_wait,
                 gauge=None, on_change=None, pressure_ratio: float = 1.5,
                 idle_ratio: float = 0.25, min_samples: int = 4):
        self.base_ms = max(0.1, float(base_ms))
        self.max_ms = max(self.base_ms, float(max_ms))
        self._queue_wait = queue_wait        # Histogram (windowed)
        self._gauge = gauge
        self._on_change = on_change
        self.pressure_ratio = float(pressure_ratio)
        self.idle_ratio = float(idle_ratio)
        self.min_samples = max(1, int(min_samples))
        self._cur = self.base_ms
        self._lock = threading.Lock()
        if gauge is not None:
            gauge.set(self._cur)

    @property
    def current_ms(self) -> float:
        with self._lock:
            return self._cur

    def current_s(self) -> float:
        return self.current_ms / 1000.0

    def update(self) -> float:
        """One control step: read the windowed queue-wait stats, move the
        window if warranted, return the (possibly new) window in ms."""
        n = self._queue_wait.window_count()
        p99 = self._queue_wait.window_percentile(99)
        with self._lock:
            cur = self._cur
            new, reason = cur, None
            if n >= self.min_samples and p99 >= self.pressure_ratio * cur:
                new, reason = min(self.max_ms, cur * 2.0), "pressure"
            elif cur > self.base_ms and (
                    n == 0 or p99 <= self.idle_ratio * cur):
                new, reason = max(self.base_ms, cur / 2.0), "idle"
            if new == cur:
                return cur
            self._cur = new
        if self._gauge is not None:
            self._gauge.set(new)
        if self._on_change is not None:
            self._on_change(cur, new, p99, reason)
        return new


def _compile_filters(spec):
    """Normalize a filters argument (None / predicate text / compiled
    Predicate) to a Predicate-or-None. Lazy import: `index/__init__`
    pulls the whole ANN stack, which serve only loads when routing
    through it (same reason `_index()` imports ivf in-function)."""
    if spec is None or spec == "":
        return None
    from dnn_page_vectors_tpu.index import attrs as attrs_mod
    return attrs_mod.compile_filters(spec)


def _merge_topk_host(s1, i1, s2, i2, k: int):
    """Fold two [n, k] (scores fp32, page_ids int64) candidate sets into
    one top-k on host — the cross-stamp merge for the streaming dual-stamp
    path (docs/MAINTENANCE.md "Rolling model migration"); the resident
    path merges all stamps on device through the view's packed program.
    Stable on ties (first set wins), -inf/-1 padding sorts last."""
    s = np.concatenate([s1, s2], axis=1)
    i = np.concatenate([i1, i2], axis=1)
    order = np.argsort(-s, axis=1, kind="stable")[:, :k]
    return (np.take_along_axis(s, order, axis=1),
            np.take_along_axis(i, order, axis=1))


class _ServeView:
    """One atomic serving snapshot (docs/UPDATES.md): everything
    search_many touches that a refresh() can change — the store handle
    (with its frozen generation chain and tombstone map), the staged HBM
    shards, the combined-id table, the device merge program, the
    degraded-tail entries, and the IVF index. The hot-swap is a single
    reference assignment: in-flight dispatches finish on the view they
    captured at entry, the next dispatch sees the new one — no lock on
    the query path, no torn half-view ever observable."""

    __slots__ = ("store", "entries", "generation", "shards", "shard_keys",
                 "shard_steps", "steps", "stream_entries", "pid_table",
                 "merge", "pad_rows", "index", "index_error", "index_info",
                 "docs_appended", "tombstoned", "num_vectors", "maint_stats",
                 "restricted")

    def __init__(self, store: VectorStore,
                 entries: Optional[List[Dict]] = None):
        self.store = store
        # frozen table snapshot — the whole store, or (partitioned
        # serving, infer/partition.py) one partition's contiguous shard
        # range; `restricted` routes the streaming sweep through THIS
        # entry subset instead of the live table
        self.entries: List[Dict] = (store.shards() if entries is None
                                    else list(entries))
        self.restricted = entries is not None
        self.generation = store.generation
        self.docs_appended = store.appended_vectors()
        self.tombstoned = store.tombstoned_count()
        self.num_vectors = store.num_vectors
        # the compaction trigger's inputs, frozen with the chain they
        # describe (docs/MAINTENANCE.md): density/dead-rows/reclaimable
        self.maint_stats: Dict = store.maintenance_stats()
        # distinct model stamps over the FULL table, ascending — mid-
        # migration (docs/MAINTENANCE.md "Rolling model migration") this is
        # [from_step, to_step] and queries encode once per stamp; computed
        # store-wide even for a restricted view so every partition splits a
        # stacked query matrix on the same block order
        self.steps: List[int] = store.model_steps()
        self.shards = None   # [(ids np[int64], n, pages [R, D], scl|None)]
        self.shard_keys: List[tuple] = []
        self.shard_steps: List[Optional[int]] = []   # stamp per staged shard
        self.stream_entries: List[Dict] = []
        self.pid_table = None
        self.merge = None
        self.pad_rows = 0
        self.index = None
        self.index_error: Optional[str] = None
        self.index_info: Optional[Dict] = None


class SearchService:
    def __init__(self, cfg, embedder: BulkEmbedder, corpus,
                 store: VectorStore, preload_hbm_gb: float = 4.0,
                 snippet_chars: int = 160, query_batch: Optional[int] = None,
                 log=None, profiler: Optional[PipelineProfiler] = None,
                 registry: Optional[MetricsRegistry] = None,
                 clock=None):
        self.cfg = cfg
        self.embedder = embedder
        self.corpus = corpus
        self.store = store
        # extra query towers keyed by model step (docs/MAINTENANCE.md
        # "Rolling model migration"): begin_migration() attaches the target
        # model's params here so mid-migration queries can encode with BOTH
        # stamps; the refresh() that observes the completed stamp flip
        # adopts the new tower into `embedder` and drops this reference.
        # Whole-dict swap on write, snapshot read on the query path.
        self._towers: Dict[int, object] = {}
        self.snippet_chars = snippet_chars
        self.degraded = False
        self.fault_counters: Dict[str, int] = {}
        # per-stage serving breakdown (queue_wait/tokenize/encode/topk/
        # merge/format) — one shared instance; the batcher and concurrent
        # callers all add into it
        self.profiler = profiler or PipelineProfiler()
        # -- telemetry (docs/OBSERVABILITY.md) ----------------------------
        # One registry per service (counters must not mix across services)
        # holding every serving instrument; request-scoped tracing follows
        # the obs.* section. PipelineProfiler stays the cumulative stage
        # accountant; the registry adds what it can't say: live windowed
        # rates (qps/error/cache-hit over obs.window_s), bounded latency
        # percentiles, and the lifecycle event channel.
        obs = getattr(cfg, "obs", None)
        window_s = getattr(obs, "window_s", 10.0) if obs is not None else 10.0
        reservoir = getattr(obs, "reservoir", 4096) if obs is not None \
            else 4096
        self._window_s = window_s
        self.registry = registry or MetricsRegistry(
            events=getattr(obs, "events", 256) if obs is not None else 256)
        self.tracer = Tracer(
            enabled=getattr(obs, "enabled", True) if obs is not None
            else True,
            slow_ms=getattr(obs, "slow_ms", -1.0) if obs is not None
            else -1.0,
            slow_log_size=getattr(obs, "slow_log_size", 64)
            if obs is not None else 64,
            buffer=getattr(obs, "trace_buffer", 64) if obs is not None
            else 64)
        reg = self.registry
        self._m_requests = reg.counter("serve.requests", window_s=window_s)
        self._m_errors = reg.counter("serve.errors", window_s=window_s)
        self._m_latency = reg.histogram("serve.latency_ms",
                                        window_s=window_s, cap=reservoir)
        self._m_cache_hits = reg.counter("serve.cache_hits",
                                         window_s=window_s)
        self._m_cache_misses = reg.counter("serve.cache_misses",
                                           window_s=window_s)
        self._m_ann_lists = reg.counter("serve.ann_lists_scanned")
        self._m_ann_reranked = reg.counter("serve.ann_candidates_reranked")
        self._m_ann_fallbacks = reg.counter("serve.ann_fallbacks")
        self._m_ann_gather = reg.counter("serve.ann_gather_bytes")
        self._m_refreshes = reg.counter("serve.refreshes")
        self._m_incremental = reg.counter("serve.incremental_updates")
        self._m_rebuilds = reg.counter("serve.full_rebuilds")
        self._m_restage_skipped = reg.counter("serve.restage_skipped")
        self._m_restage_forced = reg.counter("serve.restage_forced")
        # queue-wait distribution behind the adaptive-batching control
        # loop (docs/SERVING.md): the micro-batcher observes every
        # request's measured wait here; AdaptiveWindow reads the windowed
        # p99 back out
        self._m_queue_wait = reg.histogram("serve.queue_wait_ms",
                                           window_s=window_s, cap=reservoir)
        # recompilation visibility (docs/OBSERVABILITY.md): the serving
        # path tracks every (program, shape) key it dispatches; a
        # first-seen key means XLA compiles — the classic hidden p99
        # cliff an SLO trial would otherwise misattribute to load
        self._m_recompiles = reg.counter("serve.recompiles")
        self._compiled_keys: set = set()   # guarded-by: _compiled_lock
        self._compiled_lock = threading.Lock()
        # LRU query-embedding cache: normalized text + the store's model
        # step -> host fp32 query vector. Step in the KEY means a store
        # re-stamp (ensure_model_step) invalidates without a flush.
        serve_cfg = getattr(cfg, "serve", None)
        # guarded-by: _cache_lock
        self._cache: "OrderedDict[tuple, np.ndarray]" = OrderedDict()
        self._cache_cap = (serve_cfg.query_cache_size
                           if serve_cfg is not None else 0)
        self._cache_lock = threading.Lock()
        # Generation-keyed result cache (docs/SERVING.md "Result cache"):
        # (normalized text, k, nprobe, store generation, index generation)
        # -> formatted top-k hits, probed at the admission door BEFORE a
        # repeat can consume a micro-batch bucket slot. refresh() bumps
        # the generations, so a swap invalidates for free — stale entries
        # age out of the LRU under unreachable keys.
        self._rcache_cap = (
            serve_cfg.result_cache_size
            if serve_cfg is not None
            and getattr(serve_cfg, "result_cache", False) else 0)
        # fleet sharing (FLAG_RESULT_CACHE / T_CACHE_* frames) rides on
        # top of the local cache — never enabled without it
        self._rcache_fleet = bool(
            self._rcache_cap
            and getattr(serve_cfg, "result_cache_fleet", False))
        # guarded-by: _rcache_lock
        self._rcache: "OrderedDict[tuple, list]" = OrderedDict()
        # guarded-by: _rcache_lock
        self._rcache_bytes = 0
        self._rcache_lock = threading.Lock()
        # result-cache peers (attach_cache_peers): SocketSearchClient
        # handles to sibling front ends sharing the hot set
        # guarded-by: _rcache_lock
        self._rcache_peers: list = []
        # per-peer circuit breakers, index-aligned with _rcache_peers
        # (cache_peer breaker scope — docs/ROBUSTNESS.md): a down
        # sibling is skipped cheaply instead of costing a dial/timeout
        # on every local miss
        # guarded-by: _rcache_lock
        self._rcache_peer_breakers: list = []
        self._m_rcache_hits = reg.counter("serve.result_cache_hits",
                                          window_s=window_s)
        self._m_rcache_misses = reg.counter("serve.result_cache_misses",
                                            window_s=window_s)
        # IVF ANN routing (docs/ANN.md): serve.index="ivf" tries the
        # inverted-file index; every request re-checks it against the
        # store's stamp and falls back to the exact path (counted) when
        # the index is missing/stale/quarantined. "exact" (the default)
        # never touches the index machinery — byte-identical behavior.
        self._serve_index = (getattr(serve_cfg, "index", "exact")
                             if serve_cfg is not None else "exact")
        self._nprobe = (getattr(serve_cfg, "nprobe", 8)
                        if serve_cfg is not None else 8)
        # PQ/ADC knobs (docs/ANN.md): exact-rerank depth per query (0 =
        # the index default) and the HBM budget for the resident hot
        # posting set — staged at view build, so resident lists answer
        # with zero per-request host gather
        self._pq_rerank = (getattr(serve_cfg, "pq_rerank", 0)
                           if serve_cfg is not None else 0)
        # filtered retrieval (docs/ANN.md "Filtered retrieval"):
        # serve.filters gates accepting/advertising predicates on the
        # wire; serve.filter_escalate is the probe-widening factor when
        # a filtered IVF probe set under-fills k (<=1 disables)
        self._filters_enabled = (getattr(serve_cfg, "filters", True)
                                 if serve_cfg is not None else True)
        self._filter_escalate = (getattr(serve_cfg, "filter_escalate", 4.0)
                                 if serve_cfg is not None else 4.0)
        self._hot_gb = (getattr(serve_cfg, "hot_postings_gb", 0.0)
                        if serve_cfg is not None else 0.0)
        # partitioned + replicated serving (infer/partition.py,
        # docs/SCALING.md "Partitioned serving"): P x R host-simulated
        # partition workers behind the scatter-gather; 1 x 1 keeps the
        # single-view path below byte-identical
        self._partitions = (getattr(serve_cfg, "partitions", 1)
                            if serve_cfg is not None else 1)
        self._replicas = (getattr(serve_cfg, "replicas", 1)
                          if serve_cfg is not None else 1)
        self._shed_queue = (getattr(serve_cfg, "replica_shed_queue", 8)
                            if serve_cfg is not None else 8)
        self._m_replica_shed = reg.counter("serve.replica_shed")
        self._m_partition_degraded = reg.counter("serve.partition_degraded")
        # -- over-the-wire serving (infer/transport.py, infer/server.py,
        # infer/partition_host.py; docs/SERVING.md "Network front end") --
        # The admission clock is injectable so deadline semantics are
        # testable on a fake clock; everything else on the query path
        # keeps using time.perf_counter directly.
        self._clock = clock if clock is not None else time.perf_counter
        # default per-request deadline budget applied at the network edge
        # when a request carries none (0 = no deadline)
        self._deadline_ms = (getattr(serve_cfg, "deadline_ms", 0.0)
                             if serve_cfg is not None else 0.0)
        # deadline-aware admission: a request shed at the door (expired,
        # or the windowed queue-wait p99 says it cannot make its budget)
        # counts here — and ONLY here; a shed is not an error
        self._m_deadline_shed = reg.counter("serve.deadline_shed",
                                            window_s=window_s)
        # hedged fan-out + wire accounting (populated by the worker
        # gateway / socket front end when transport serving is attached).
        # wire_raw_bytes is the raw-frame EQUIVALENT of the same traffic
        # — compressed RESULT frames and interned query blocks count what
        # they replaced — so raw/actual is the live wire-compression
        # ratio (serve.wire_compress, docs/SERVING.md)
        self._m_hedge_fired = reg.counter("serve.hedge_fired")
        self._m_wire_bytes = reg.counter("serve.wire_bytes")
        self._m_wire_raw = reg.counter("serve.wire_raw_bytes")
        # the RPC fan-out (partition_host.WorkerGateway), attached by
        # attach_gateway(); None = the in-process scatter-gather
        self._fanout = None
        upd_cfg = getattr(cfg, "updates", None)
        self._rebuild_drift = (getattr(upd_cfg, "rebuild_drift", 0.25)
                               if upd_cfg is not None else 0.25)
        self._auto_update_index = (
            getattr(upd_cfg, "auto_update_index", True)
            if upd_cfg is not None else True)
        self._restage_density = (
            getattr(upd_cfg, "restage_tombstone_density", 0.05)
            if upd_cfg is not None else 0.05)
        # micro-batch window: fixed at serve.batch_window_ms, or driven by
        # the AdaptiveWindow controller under serve.batch_window_adaptive
        # (off by default — the fixed path is byte-identical to before).
        # The live window is always readable as the serve.batch_window_ms
        # gauge; every adaptive change emits a window_adapt event.
        self._window_base_ms = (getattr(serve_cfg, "batch_window_ms", 2.0)
                                if serve_cfg is not None else 2.0)
        win_gauge = reg.gauge("serve.batch_window_ms")
        win_gauge.set(self._window_base_ms)
        self._window_ctl: Optional[AdaptiveWindow] = None
        if serve_cfg is not None and getattr(
                serve_cfg, "batch_window_adaptive", False):
            self._window_ctl = AdaptiveWindow(
                self._window_base_ms,
                getattr(serve_cfg, "batch_window_max_ms", 25.0),
                self._m_queue_wait, gauge=win_gauge,
                on_change=self._on_window_adapt)
        self._batcher: Optional[_MicroBatcher] = None
        self._batch_sizes: List[int] = []   # telemetry after close()
        # background maintenance (docs/MAINTENANCE.md): start_maintenance()
        # attaches the service and — under maintenance.bg_rebuild — moves
        # drift-triggered IVF full rebuilds off the refresh() caller onto
        # its rebuild worker (refresh defers; the worker builds beside the
        # live index and hot-swaps). Without the service attached, refresh
        # keeps the inline-rebuild behavior.
        self._maintenance = None
        self._defer_rebuilds = False
        reg.gauge("serve.index_rebuild_pending").set(0.0)
        self._log = log
        # Per-query encode is O(1 query), not the 512-row bulk-embed batch
        # wearing a serving hat (VERDICT r4 Weak #2): queries pad only to a
        # small compiled bucket, rounded UP to the next multiple of the mesh
        # 'data' axis so the batch always shards evenly — max(8, n_data)
        # broke the jitted _encode_query for non-dividing axes like 3/5/6
        # (ADVICE r5). warmup() measures the warm per-query latency.
        # ONE n_data for the whole service: the ["data"] spelling raised
        # KeyError on meshes without a 'data' axis.
        n_data = max(embedder.mesh.shape.get("data", 1), 1)
        self._n_data = n_data
        self.query_batch = query_batch or -(-8 // n_data) * n_data
        self.warm_latency_ms: Optional[float] = None
        self._preload_gb = preload_hbm_gb
        self._refresh_lock = threading.Lock()   # one refresh at a time
        # the refresh lock is an outer layer: the view build under it
        # counts fault retries, never the reverse (graftcheck lock-order)
        # lock-order: SearchService._refresh_lock < faults._COUNTER_LOCK
        self._pset = None
        if self._partitions * self._replicas > 1:
            from dnn_page_vectors_tpu.infer.partition import PartitionSet
            self._pset = PartitionSet(self, store,
                                      partitions=self._partitions,
                                      replicas=self._replicas,
                                      shed_queue=self._shed_queue)
            # the control view: partition 0's primary — store-level fields
            # (generation, maint stats) are identical on every view; the
            # compat windows (_shards/_index) read partition 0's slice
            self._view = self._pset.primary_view()
        else:
            self._view = self._build_view(store)
        self.registry.gauge("serve.degraded").set(
            1.0 if self.degraded else 0.0)
        self.registry.gauge("serve.store_generation").set(
            self._view.generation)
        if log is not None:
            view = self._view
            log.write({
                "serve_degraded": self.degraded,
                "serve_hbm_shards": len(view.shards or []),
                "serve_stream_shards": len(view.stream_entries),
                "serve_vectors": view.num_vectors,
                "serve_query_batch": self.query_batch,
                "serve_query_cache_size": self._cache_cap,
                "serve_index": self._serve_index,
                "serve_ann_available": view.index is not None,
                "store_generation": view.generation,
                "fault_counters": faults.counters(),
            })

    @property
    def preloaded(self) -> bool:
        return self._view.shards is not None

    # read-only compatibility windows into the current view (tests and
    # telemetry peek at these; the query path captures the view ONCE)
    @property
    def _shards(self):
        return self._view.shards

    @property
    def _stream_entries(self) -> List[Dict]:
        return self._view.stream_entries

    @property
    def _index(self):
        return self._view.index

    @property
    def _index_error(self) -> Optional[str]:
        return self._view.index_error

    # serving counters are registry instruments (docs/OBSERVABILITY.md);
    # these read-only windows keep the pre-registry attribute surface that
    # tests, bench, and operator scripts already use
    @property
    def cache_hits(self) -> int:
        return self._m_cache_hits.value

    @property
    def cache_misses(self) -> int:
        return self._m_cache_misses.value

    @property
    def result_cache_hits(self) -> int:
        return self._m_rcache_hits.value

    @property
    def result_cache_misses(self) -> int:
        return self._m_rcache_misses.value

    @property
    def ann_lists_scanned(self) -> int:
        return self._m_ann_lists.value

    @property
    def ann_candidates_reranked(self) -> int:
        return self._m_ann_reranked.value

    @property
    def ann_fallbacks(self) -> int:
        return self._m_ann_fallbacks.value

    @property
    def ann_gather_bytes(self) -> int:
        return self._m_ann_gather.value

    @property
    def refreshes(self) -> int:
        return self._m_refreshes.value

    @property
    def incremental_updates(self) -> int:
        return self._m_incremental.value

    @property
    def full_rebuilds(self) -> int:
        return self._m_rebuilds.value

    # tombstone-aware restage policy counters (docs/UPDATES.md):
    # skipped = staged shard reused with its new dead rows masked in
    # the id table; forced = dead density crossed the threshold and
    # the shard restaged compacted
    @property
    def restage_skipped(self) -> int:
        return self._m_restage_skipped.value

    @property
    def restage_forced(self) -> int:
        return self._m_restage_forced.value

    # partitioned-serving routing counters (docs/SCALING.md): shed =
    # traffic moved off a partition's primary replica (restaging /
    # degraded / over queue budget); partition_degraded = a partition
    # whose replicas were ALL degraded served degraded locally instead of
    # returning an empty slice
    @property
    def replica_shed(self) -> int:
        return self._m_replica_shed.value

    @property
    def partition_degraded_serves(self) -> int:
        return self._m_partition_degraded.value

    @property
    def partition_set(self):
        """The live PartitionSet (None on a single-view service)."""
        return self._pset

    # -- over-the-wire serving (docs/SERVING.md "Network front end") -------
    @property
    def deadline_sheds(self) -> int:
        return self._m_deadline_shed.value

    @property
    def hedge_fires(self) -> int:
        return self._m_hedge_fired.value

    @property
    def wire_bytes(self) -> int:
        return self._m_wire_bytes.value

    @property
    def wire_raw_bytes(self) -> int:
        """Raw-frame equivalent of wire_bytes (the compression ratio's
        numerator); equals wire_bytes when nothing negotiated
        compression."""
        return self._m_wire_raw.value

    @property
    def fanout(self):
        """The attached WorkerGateway (None = in-process scatter)."""
        return self._fanout

    def attach_gateway(self, gateway) -> None:
        """Wire a partition_host.WorkerGateway into the query path: the
        scatter becomes an RPC fan-out to registered partition workers,
        and replica routing derives health from worker LIVENESS
        (heartbeats) on top of the in-process flags — a partition whose
        worker connection died sheds with reason "liveness" exactly like
        a restaging replica sheds today. Detach with attach_gateway(None)
        (the gateway itself is closed by whoever opened it)."""
        self._fanout = gateway
        pset = gateway.partition_set if gateway is not None else self._pset
        if pset is not None:
            pset.set_liveness(
                gateway.worker_alive if gateway is not None else None)

    def default_deadline(self, deadline_ms: Optional[float] = None
                         ) -> Optional[float]:
        """Resolve a RELATIVE deadline budget (ms; None/<=0 = the
        serve.deadline_ms default, which may itself be off) into an
        ABSOLUTE deadline on the service clock, or None."""
        dl = self._deadline_ms if deadline_ms is None else deadline_ms
        if dl is None or dl <= 0:
            return None
        return self._clock() + dl / 1000.0

    def _shed_deadline(self, reason: str, deadline: Optional[float],
                       queue_wait_p99_ms: Optional[float] = None,
                       trace=None) -> DeadlineExceeded:
        """Count + record one admission shed and BUILD (not raise) the
        exception: admission raises it, the micro-batch door sets it on
        the shed request's future."""
        self._m_deadline_shed.inc()
        rem_ms = (None if deadline is None
                  else round((deadline - self._clock()) * 1000.0, 3))
        cur = trace if trace is not None else self.tracer.current()
        attrs = {"reason": reason, "remaining_ms": rem_ms}
        if queue_wait_p99_ms is not None:
            attrs["queue_wait_p99_ms"] = round(queue_wait_p99_ms, 3)
        self.registry.event(
            "deadline_shed", attrs,
            trace_id=getattr(cur, "trace_id", None))
        msg = f"request shed at admission ({reason}"
        if rem_ms is not None:
            msg += f"; {rem_ms} ms remaining"
        if queue_wait_p99_ms is not None:
            msg += f"; queue-wait p99 {queue_wait_p99_ms:.1f} ms"
        return DeadlineExceeded(msg + ")")

    def _admit(self, deadline: Optional[float]) -> None:
        """The admission-control ladder (docs/SERVING.md "Network front
        end"): (1) a deadline that has ALREADY expired is shed
        immediately — it must never consume queue capacity or a
        micro-batch bucket slot; (2) SLO-budget shedding — when the
        windowed queue-wait p99 (the same instrument the adaptive-window
        controller reads) says the queue alone will eat the remaining
        budget, the request cannot make its deadline and is shed at the
        door instead of timing out after occupying a slot. Raises
        DeadlineExceeded; no-deadline requests always admit."""
        if deadline is None:
            return
        rem_ms = (deadline - self._clock()) * 1000.0
        if rem_ms <= 0.0:
            raise self._shed_deadline("expired", deadline)
        if self._batcher is not None:
            qw = self._m_queue_wait
            if qw.window_count() >= 4:
                p99 = qw.window_percentile(99)
                if p99 > rem_ms:
                    raise self._shed_deadline("slo_budget", deadline,
                                              queue_wait_p99_ms=p99)

    @contextlib.contextmanager
    def _stage(self, name: str, **attrs):
        """One serving stage, observed twice from one clock: cumulative
        seconds into the PipelineProfiler (the aggregate view) and a span
        on the active request trace (the per-request view). Yields the
        span so call sites can attach attributes (ANN stats, cache hits)."""
        t0 = time.perf_counter()
        with self.tracer.span(name, **attrs) as sp:
            try:
                yield sp
            finally:
                self.profiler.add(name, time.perf_counter() - t0)

    def _count_fault(self, name: str) -> None:
        self.fault_counters[name] = self.fault_counters.get(name, 0) + 1
        faults.count(name)

    # -- adaptive batching (docs/SERVING.md) -------------------------------
    @property
    def batch_window_ms(self) -> float:
        """The micro-batch window currently in force (ms): the configured
        base, or wherever the adaptive controller has moved it."""
        return (self._window_ctl.current_ms if self._window_ctl is not None
                else self._window_base_ms)

    def _adapt_window(self) -> None:
        """One adaptive-window control step; no-op with adaptation off.
        Called by the micro-batcher after every dispatch."""
        if self._window_ctl is not None:
            self._window_ctl.update()

    def _on_window_adapt(self, old_ms: float, new_ms: float,
                         queue_wait_p99_ms: float, reason: str) -> None:
        cur = self.tracer.current()
        self.registry.event("window_adapt", {
            "old_ms": round(old_ms, 3), "new_ms": round(new_ms, 3),
            "queue_wait_p99_ms": round(queue_wait_p99_ms, 3),
            "reason": reason,
        }, trace_id=cur.trace_id if cur is not None else None)

    # -- recompilation visibility (docs/OBSERVABILITY.md) ------------------
    @property
    def recompiles(self) -> int:
        return self._m_recompiles.value

    def _note_dispatch_shape(self, program: str, **shape) -> None:
        """Count a jit cache miss when the serving path dispatches a
        (program, shape) key it has never dispatched before — first-seen
        keys are exactly the dispatches XLA must compile for. Silent
        recompiles (a new k, a ragged bucket, a refresh changing pad_rows)
        are the classic hidden p99 cliff; the `recompile` event carries
        the bucket shape so an SLO trial's latency spike attributes to
        the compile, not to offered load."""
        key = (program, tuple(sorted(shape.items())))
        with self._compiled_lock:
            if key in self._compiled_keys:
                return
            self._compiled_keys.add(key)
        self._m_recompiles.inc()
        cur = self.tracer.current()
        self.registry.event("recompile", {"program": program, **shape},
                            trace_id=cur.trace_id if cur is not None
                            else None)

    # -- hot-swap refresh (docs/UPDATES.md) --------------------------------
    def refresh(self, update_index: Optional[bool] = None) -> Dict:
        """Swap in the store's CURRENT generation chain with zero downtime:
        re-open the store (fresh handle — the serving view's generations
        and tombstones are frozen per view, so in-flight queries never see
        a half-applied update), restage only the shards the old view
        doesn't already hold on device, bring the IVF index up to date
        (incremental posting append, or drift-triggered full rebuild —
        `update_index` overrides updates.auto_update_index), and publish
        the new view with one atomic reference assignment between
        micro-batcher dispatches. Queries keep flowing the whole time:
        buckets in flight finish on the old view, the next bucket sees the
        new one, and a failed index update degrades THAT view to exact
        search instead of taking the service down."""
        t0 = time.perf_counter()
        part_info = None
        with self._refresh_lock:
            old = self._view
            # fresh handle: verify() gates appended bytes exactly like the
            # base open did, and the old view's store object stays frozen
            new_store = VectorStore(self.store.directory)
            upd = (self._auto_update_index if update_index is None
                   else update_index)
            if self._pset is not None:
                # partitioned: a ROLLING per-partition swap — while one
                # partition restages (its router sheds to a replica), the
                # others keep serving their current views untouched; the
                # store-level IVF update runs exactly once, on the first
                # view built (infer/partition.py)
                t_swap = time.perf_counter()
                part_info = self._pset.refresh(new_store, update_index=upd)
                view = self._pset.primary_view()
                self._view = view
            else:
                view = self._build_view(new_store, reuse=old,
                                        update_index=upd)
                t_swap = time.perf_counter()
                self._view = view    # THE swap: one reference assignment
            self.store = new_store
            self._m_refreshes.inc()
            # tower adoption (docs/MAINTENANCE.md "Rolling model
            # migration"): once the store's migration record is gone the
            # sweep either completed (stamp flipped — the target tower
            # becomes THE query encoder) or was abandoned by a reset;
            # either way the extra towers unload here, and the superseded
            # params drop with this reference
            adopted_step = None
            tw = self._towers
            if tw and new_store.migration is None:
                if new_store.model_step in tw:
                    self.embedder.params = tw[new_store.model_step]
                    adopted_step = int(new_store.model_step)
                self._towers = {}
        swap_ms = (time.perf_counter() - t_swap) * 1000.0
        info = {
            "store_generation": view.generation,
            "index_generation": (view.index.index_generation
                                 if view.index is not None else None),
            "docs_appended": view.docs_appended,
            "new_docs": view.docs_appended - old.docs_appended,
            "tombstoned": view.tombstoned,
            "vectors": view.num_vectors,
            "hbm_shards": len(view.shards or []),
            "stream_shards": len(view.stream_entries),
            "refresh_seconds": round(time.perf_counter() - t0, 3),
            "swap_ms": round(swap_ms, 3),
        }
        if view.index_info is not None:
            info["index_update"] = view.index_info
        if view.index_error is not None:
            info["index_error"] = view.index_error
        mig = view.store.migration
        if mig is not None:
            # migration progress rides every refresh log line while the
            # sweep runs: which stamps this view serves, and how far the
            # shard table has moved to the target
            table = view.store.shards()
            info["migration"] = {
                "from_step": mig.get("from_step"),
                "to_step": mig.get("to_step"),
                "shards_migrated": sum(
                    1 for e in table
                    if view.store.entry_step(e) == mig.get("to_step")),
                "shards_total": len(table),
                "stamps_serving": list(view.steps)}
        if adopted_step is not None:
            info["migration_adopted_step"] = adopted_step
        if part_info is not None:
            # per-partition rolling-swap record (docs/SCALING.md): which
            # partition restaged when, and each replica's swap window
            info["partitions"] = part_info
        if self._fanout is not None:
            # over-the-wire fleet (docs/SERVING.md "Network front end"):
            # tell every registered worker to rebuild onto this
            # generation (T_REFRESH control frame) — no worker restart.
            # The broadcast does NOT block the refresh: until a worker
            # acks, routing treats it as generation-stale and its slice
            # serves from the local view just swapped in above, so
            # results stay byte-consistent while the fleet catches up
            info["workers_refresh"] = self._fanout.broadcast_refresh(
                view.generation)
        # lifecycle event (docs/OBSERVABILITY.md): the hot-swap is the
        # transition dashboards alert on; trace-id correlation ties it to
        # the request that observed it when refresh runs under a trace
        cur = self.tracer.current()
        self.registry.event("view_swap", {
            "store_generation": view.generation,
            "new_docs": info["new_docs"],
            "swap_ms": info["swap_ms"],
            "index_error": view.index_error,
        }, trace_id=cur.trace_id if cur is not None else None)
        self.registry.gauge("serve.store_generation").set(view.generation)
        if view.index is not None:
            self.registry.gauge("serve.index_generation").set(
                view.index.index_generation)
        if self._log is not None:
            self._log.write({"serve_refresh": self.refreshes, **info})
        return info

    def restage_hot(self) -> Dict:
        """Re-rank and re-stage the CURRENT view's HBM-resident hot
        posting set against the measured popularity window (docs/ANN.md
        "Popularity tiering") — no store re-open, no view swap: the same
        index object re-pins the lists its own scan counts say are
        hottest, then halves the window. The staged state publishes with
        one reference assignment, so in-flight ADC searches finish on
        whichever residency they captured. Returns the stage_hot summary
        ({} when there is nothing to restage: exact serving, no PQ, or
        no HBM budget), and emits a `hot_restaged` event."""
        view = self._view
        idx = view.index if view is not None else None
        if idx is None or idx.pq is None or self._hot_gb <= 0:
            return {}
        with self._refresh_lock:
            hot = idx.stage_hot(self._hot_gb * 2 ** 30)
        self.registry.event("hot_restaged", dict(hot))
        return hot

    def begin_migration(self, params, step: int) -> None:
        """Attach the TARGET model's params as a second query tower for a
        rolling migration (docs/MAINTENANCE.md "Rolling model migration").
        Until the completion flip, every search encodes with both towers
        and each shard's scores come from the tower matching its recorded
        stamp; the refresh() that observes the flipped store adopts this
        tower and unloads the old one. Idempotent per step; whole-dict
        swap, so the query path never sees a half-updated tower map."""
        self._towers = {**self._towers, int(step): params}
        if self._log is not None:
            self._log.write({"serve_migration_tower": int(step),
                             "serving_step": self.store.model_step})

    def _build_view(self, store: VectorStore, reuse: "_ServeView" = None,
                    update_index: bool = False,
                    entries: Optional[List[Dict]] = None,
                    hot_gb: Optional[float] = None) -> "_ServeView":
        """One serving view over `store` — the whole shard table, or
        (partitioned serving) the `entries` subset with `hot_gb` as this
        partition's cut of the hot-posting HBM budget."""
        view = _ServeView(store, entries=entries)
        # dead-byte accounting as registry gauges (docs/MAINTENANCE.md):
        # the compaction trigger's inputs ride the same exposition as
        # every other serving number (metrics(), cli serve-metrics)
        ms = view.maint_stats
        self.registry.gauge("serve.tombstone_density").set(
            ms["tombstone_density"])
        self.registry.gauge("serve.dead_rows").set(ms["dead_rows"])
        self.registry.gauge("serve.reclaimable_bytes").set(
            ms["reclaimable_bytes"])
        # Budget against the ACTUAL device footprint: every shard is padded
        # to the max shard row count for one static compiled shape, so an
        # uneven store (merged multi-writer shards) costs
        # n_shards * padded_rows, which can far exceed num_vectors.
        rows = max((s["count"] for s in view.entries), default=0)
        rows += (-rows) % self._n_data
        view.pad_rows = rows
        # budget is PER DEVICE: shards are row-sharded over 'data', so each
        # device holds rows/n_data of every staged shard (ADVICE r4) — at
        # the STORED width (fp16 rows, or int8 codes + fp16 scale per row)
        per_row = (store.dim + 2 if store.manifest["dtype"] == "int8"
                   else store.dim * 2)
        need = len(view.entries) * rows * per_row / self._n_data
        # rows > 0: a store of only zero-count shards has nothing to stage
        # (need == 0 would pass even the explicit never-preload 0.0 budget)
        if view.entries and rows > 0 and need <= self._preload_gb * 2**30:
            self._stage_view(view, rows,
                             budget_bytes=self._preload_gb * 2**30,
                             per_row=per_row, reuse=reuse)
            if not view.shards:       # nothing survived staging
                view.shards = None    # stream instead; handles empty stores
        if self._serve_index == "ivf":
            self._attach_index(
                view, update_index,
                shard_indices=([e["index"] for e in view.entries]
                               if view.restricted else None),
                hot_gb=hot_gb, reuse=reuse)
            if (reuse is not None and reuse.index_error is not None
                    and view.index is not None):
                # a degraded-to-exact view healed across the refresh
                self.registry.event("index_restored", {
                    "was": reuse.index_error[:200],
                    "index_generation": view.index.index_generation})
        return view

    # -- IVF ANN index (docs/ANN.md, docs/UPDATES.md) ----------------------
    def _attach_index(self, view: "_ServeView", update_index: bool,
                      shard_indices: Optional[List[int]] = None,
                      hot_gb: Optional[float] = None,
                      reuse: "_ServeView" = None) -> None:
        from dnn_page_vectors_tpu.index.ivf import IndexUnavailable, IVFIndex
        hot_gb = self._hot_gb if hot_gb is None else hot_gb
        try:
            if update_index:
                serve_cfg = self.cfg.serve
                view.index, view.index_info = IVFIndex.update(
                    view.store, self.embedder.mesh,
                    rebuild_drift=self._rebuild_drift,
                    nlist=serve_cfg.nlist, iters=serve_cfg.kmeans_iters,
                    init=getattr(serve_cfg, "kmeans_init", "kmeans++"),
                    defer_rebuild=self._defer_rebuilds)
                action = view.index_info.get("action")
                if action == "incremental":
                    self._m_incremental.inc()
                elif action == "rebuild":
                    self._m_rebuilds.inc()
                    self.registry.event("drift_rebuild", {
                        "drift": view.index_info.get("drift"),
                        "nlist": view.index_info.get("nlist")})
                # a drift overrun deferred off this caller: the gauge is
                # the hand-off to the background rebuild worker
                # (docs/MAINTENANCE.md) — it clears when the worker swaps
                self.registry.gauge("serve.index_rebuild_pending").set(
                    1.0 if view.index_info.get("rebuild_pending") else 0.0)
            else:
                view.index = IVFIndex.open(view.store)
            view.index_error = None
            if view.index is not None and shard_indices is not None:
                # partitioned serving: THIS view searches only its slice
                # of the inverted file — posting gathers, ADC code reads,
                # and the hot staging below all see the partition's
                # shards and nothing else (index/ivf.py partition_view)
                view.index = view.index.partition_view(shard_indices)
            if (view.index is not None and reuse is not None
                    and reuse.index is not None
                    and reuse.index.nlist == view.index.nlist):
                # carry the measured popularity window across the view
                # rebuild (docs/ANN.md "Popularity tiering"): the fresh
                # index object starts cold, but the traffic didn't — the
                # staged hot set below should keep tracking the head
                # instead of reverting to biggest-first on every refresh
                view.index.scan_counts = reuse.index.scan_counts.copy()
            if (view.index is not None and view.index.pq is not None
                    and hot_gb > 0):
                # HBM-resident hot posting set (docs/ANN.md): staged per
                # VIEW — a refresh re-opens the index, so the staged codes
                # (and their tombstone masks) follow the same hot-swap
                # cadence as the staged store shards. A staging failure
                # costs the residency, never the index.
                try:
                    hot = view.index.stage_hot(hot_gb * 2 ** 30)
                    if view.index_info is not None:
                        view.index_info = {**view.index_info, **hot}
                except Exception as e:  # noqa: BLE001
                    self._count_fault("serve_hot_stage_faults")
                    faults.warn(f"hot posting staging failed "
                                f"({type(e).__name__}: {e}); serving the "
                                "mmap gather path")
        except IndexUnavailable as e:
            view.index = None
            view.index_error = str(e)
            self.registry.event("index_degraded",
                                {"reason": str(e)[:200], "mode": "exact"})
            faults.warn(f"IVF index unavailable ({e}); serving the exact "
                        "path per request")
        except Exception as e:  # noqa: BLE001 — e.g. a posting-append
            # fault mid-update: the on-disk manifest is untouched (it lands
            # last), but it no longer matches the live table, so THIS view
            # serves exact — visibly — until a later refresh/rebuild
            view.index = None
            view.index_error = f"{type(e).__name__}: {e}"
            self._count_fault("serve_index_update_failures")
            self.registry.event("index_degraded", {
                "reason": view.index_error[:200], "mode": "exact"})
            faults.warn(f"IVF index update failed ({view.index_error}); "
                        "serving the exact path until a rebuild")

    def _ann_topk(self, view: "_ServeView", qv: np.ndarray, n: int, k: int,
                  nprobe: Optional[int] = None, predicate=None):
        """ANN (scores [n, k], page_ids [n, k], scan_bytes) for `n` real
        queries, or None to fall back to the exact path (index missing,
        stale against the view store's CURRENT model step, mid-migration
        mixed stamps, or failing at search time — the failure quarantine
        already happened inside the index layer). `nprobe` overrides the
        serve.nprobe default per request (mixed-profile load tests)."""
        idx = view.index
        if idx is None or idx.model_step != view.store.model_step:
            return None
        if len(view.steps) > 1:
            # mid-migration a single-stamp index would rank OLD-encoder
            # centroids against new-encoder shards (or vice versa): the
            # exact path routes per shard stamp instead, and the per-stamp
            # rebuild swaps a matching index back in after completion
            return None
        nprobe = nprobe or self._nprobe
        # the index pads queries to a power-of-two bucket internally:
        # mirror that key so the counter moves exactly when XLA compiles
        self._note_dispatch_shape("ivf_search", k=k, nprobe=nprobe,
                                  qpad=1 << (max(1, n) - 1).bit_length())
        try:
            with self._stage("topk") as sp:
                scores, ids, st = idx.search(
                    qv[:n], k=k, nprobe=nprobe,
                    rerank=self._pq_rerank or None,
                    predicate=predicate,
                    escalate=self._filter_escalate)
                # the ANN cost triple ON the request's span (why THIS
                # query was slow): lists probed, payload bytes gathered,
                # rows exact-reranked — plus, filtered, how many queries
                # under-filled k and re-probed wider
                sp.set_attrs(
                    lists_scanned=st.get("lists_scanned", 0),
                    gather_bytes=st.get("gather_bytes", 0),
                    rows_reranked=st.get("candidates_reranked", 0),
                    filter_escalations=st.get("filter_escalations", 0))
        except Exception as e:  # noqa: BLE001 — any index failure degrades
            view.index = None
            view.index_error = f"{type(e).__name__}: {e}"
            cur = self.tracer.current()
            self.registry.event(
                "index_degraded",
                {"reason": view.index_error[:200], "mode": "exact"},
                trace_id=cur.trace_id if cur is not None else None)
            faults.warn(f"IVF search failed ({view.index_error}); "
                        "falling back to exact search")
            return None
        self._m_ann_lists.inc(st.get("lists_scanned", 0))
        self._m_ann_reranked.inc(st.get("candidates_reranked", 0))
        self._m_ann_gather.inc(st.get("gather_bytes", 0))
        return (np.asarray(scores, np.float32), np.asarray(ids, np.int64),
                int(st.get("gather_bytes", 0)))

    def _stage_view(self, view: "_ServeView", rows: int,
                    budget_bytes: float, per_row: int,
                    reuse: "_ServeView" = None) -> None:
        import jax
        import jax.numpy as jnp
        from jax import lax

        plan = faults.active()
        store = view.store
        # restage only what the old view doesn't hold: appended generations
        # arrive as NEW shard indices, so a refresh re-uses every already-
        # staged device array (keyed on gen/index/count/crc) and pays
        # device transfer for exactly the delta; ids reload host-side so
        # newer tombstones re-mask rows the device copy still carries
        reuse_map = {}
        if (reuse is not None and reuse.shards
                and reuse.pad_rows == rows):
            reuse_map = {key: tup for key, tup
                         in zip(reuse.shard_keys, reuse.shards)}
        staged, keys, stamps = [], [], []
        used = 0.0
        per_shard = rows * per_row / self._n_data
        for entry in view.entries:
            if entry["count"] == 0:   # zero-count shards hold nothing to score
                continue
            # one stamp per shard, never mixed within one (the migration
            # pin, docs/MAINTENANCE.md): recorded here so _dispatch_bucket
            # can score the shard with the matching tower's query block
            estep = store.entry_step(entry)
            key = (entry.get("gen", 0), entry["index"], entry["count"],
                   entry.get("crc", {}).get("vec"))
            try:
                hit = reuse_map.get(key)
                if hit is not None:
                    old_ids, old_n, pages, scl = hit
                    ids = store.load_ids(entry)
                    live = np.asarray(ids[ids >= 0], np.int64)
                    alive_old = old_ids[old_ids >= 0]
                    if np.array_equal(live, alive_old):
                        # staged block current (modulo rows already masked
                        # by an earlier skip): plain reuse
                        staged.append((old_ids, old_n, pages, scl))
                        keys.append(key)
                        stamps.append(estep)
                        used += per_shard
                        continue
                    # tombstone-aware restage policy (docs/UPDATES.md):
                    # key equality pins the shard BYTES, so the only
                    # possible drift is newer tombstones. Below the
                    # density threshold the staged block is REUSED with
                    # the dead rows masked in its id table — they can
                    # still occupy a per-shard top-k slot (one result
                    # short, bounded by the threshold) but never surface;
                    # past the threshold the shard restages compacted.
                    dead_frac = (old_n - live.size) / max(old_n, 1)
                    if dead_frac <= self._restage_density:
                        masked = np.where(np.isin(old_ids, live),
                                          old_ids, np.int64(-1))
                        staged.append((masked, old_n, pages, scl))
                        keys.append(key)
                        stamps.append(estep)
                        used += per_shard
                        self._m_restage_skipped.inc()
                        continue
                    self._m_restage_forced.inc()   # falls through: restage
                plan.check("hbm_stage")
                err = store.entry_error(entry)
                if err is not None:
                    # corrupt bytes must never reach the device: quarantine
                    # drops the shard from the table entirely (its id-range
                    # returns on the next embed resume), and this service
                    # serves without it — degraded, visibly
                    store.quarantine(entry, err)
                    self._count_fault("serve_quarantined_shards")
                    self.degraded = True
                    self.registry.gauge("serve.degraded").set(1.0)
                    self.registry.event("shard_quarantine", {
                        "shard": entry["index"], "error": str(err)[:200]})
                    continue
                if used + per_shard > budget_bytes:
                    raise MemoryError(
                        f"HBM budget overrun mid-stage: shard "
                        f"{entry['index']} needs {per_shard:.0f} B on top of "
                        f"{used:.0f} staged (budget {budget_bytes:.0f})")
                ids, vecs, scl = store._load_entry(entry, raw=True)
                ids = np.asarray(ids, np.int64)
                keep = ids >= 0
                if not keep.all():
                    # compact tombstoned rows out BEFORE the device copy: a
                    # dead vector must not occupy a per-shard top-k slot
                    # (the exact merge would drop it and return short)
                    ids = ids[keep]
                    vecs = np.asarray(vecs)[keep]
                    scl = None if scl is None else np.asarray(scl)[keep]
                staged.append((ids, int(ids.shape[0]),
                               *stage_shard(vecs, rows, store.dim,
                                            self.embedder.mesh, scales=scl)))
                keys.append(key)
                stamps.append(estep)
                used += per_shard
            except Exception as e:  # noqa: BLE001 — any staging failure
                # (injected I/O fault, real device OOM, budget overrun)
                # degrades THIS shard to the streaming path; the service
                # stays up on the shards that did stage
                view.stream_entries.append(entry)
                self.degraded = True
                self._count_fault("serve_stage_faults")
                self.registry.gauge("serve.degraded").set(1.0)
                self.registry.event("degraded", {
                    "shard": entry["index"],
                    "reason": f"{type(e).__name__}: {e}"[:200],
                    "mode": "streaming"})
                faults.warn(
                    f"HBM staging failed for shard {entry['index']} "
                    f"({type(e).__name__}: {e}); serving it via the "
                    "streaming path (degraded)")
        view.shards = staged
        view.shard_keys = keys
        view.shard_steps = stamps
        if not staged:
            return
        # combined-id -> page-id table for the device-side merge below:
        # shard slot s, padded row r  ->  slot s * rows + r
        view.pid_table = np.full((len(staged) * rows,), -1, np.int64)
        for slot, (sids, n, _, _) in enumerate(staged):
            view.pid_table[slot * rows: slot * rows + n] = sids
        if reuse is not None and reuse.merge is not None \
                and reuse.pad_rows == rows:
            # the merge program depends only on pad_rows (and retraces per
            # candidate-list structure): reusing the jitted fn object keeps
            # the XLA cache warm across refreshes
            view.merge = reuse.merge
            return

        def merge(cands):
            # Device-side cross-shard merge, output PACKED into one fp32
            # array: per-query serving latency is dominated by host<->device
            # round trips (~100 ms each over a tunneled chip), so the k
            # winners across all resident shards must come back in a single
            # transfer — scores in [:, :k], int32 combined ids bitcast into
            # [:, k:].
            scs = [s for s, _ in cands]
            cat_s = jnp.concatenate(scs, axis=1)
            cat_i = jnp.concatenate(
                [jnp.where(i >= 0, i + slot * rows, -1)
                 for slot, (_, i) in enumerate(cands)], axis=1)
            k = scs[0].shape[1]
            top_s, pos = lax.top_k(cat_s, k)          # cat width S*k >= k
            top_i = jnp.take_along_axis(cat_i, pos, axis=1)
            top_i = jnp.where(jnp.isfinite(top_s), top_i, -1)
            # pack as INT32, scores bitcast into int bits — NOT ids into
            # float bits: small ids make denormal floats, and at least one
            # transport (the tunneled-chip backend) flushes denormals to
            # zero in float transfers, silently remapping every result to
            # page_ids[0]. Integer transfers are byte-faithful.
            return jnp.concatenate(
                [lax.bitcast_convert_type(top_s, jnp.int32), top_i], axis=1)

        view.merge = jax.jit(merge)

    # -- query-embedding cache --------------------------------------------
    @staticmethod
    def _normalize(query: str) -> str:
        return " ".join(query.split())

    def clear_cache(self) -> None:
        """Flush EVERY serving cache — the query-embedding LRU and the
        generation-keyed result cache — and emit a `cache_cleared` event.
        The manual escape hatch for out-of-band store mutation: normal
        refresh() never needs it (generation keys invalidate for free),
        but a store mutated underneath a live view would otherwise keep
        stale results servable."""
        with self._cache_lock:
            embed_n = len(self._cache)
            self._cache.clear()
        with self._rcache_lock:
            result_n = len(self._rcache)
            self._rcache.clear()
            self._rcache_bytes = 0
        ev = {"embed_entries": embed_n, "result_entries": result_n}
        mig = self.store.migration
        if mig is not None:
            # a flush mid-migration is worth flagging: entries keyed under
            # the OLD stamp composition never come back after the flip, so
            # repeated clears here usually mean a misdriven sweep
            ev["migration"] = (f"{mig.get('from_step')}->"
                               f"{mig.get('to_step')}")
        self.registry.event("cache_cleared", ev)
        if self._log is not None:
            self._log.write({"serve_cache_cleared": True, **ev})

    # -- generation-keyed result cache (docs/SERVING.md "Result cache") ---
    def _result_cache_key(self, query: str, k: Optional[int],
                          nprobe: Optional[int],
                          view=None, filters=None) -> Optional[tuple]:
        """(normalized text, k, nprobe, store gen, index gen, predicate)
        — or None when the cache is off. Generations in the KEY are the
        whole invalidation story: refresh() bumps them, so an entry
        filled against the old view can never answer a post-swap probe.

        The predicate slot is the CANONICAL filter text ("" unfiltered,
        index/attrs.py): a filtered hit and its unfiltered twin live
        under different keys, so a filtered probe can never be answered
        by an unfiltered fill (or vice versa) — same staleness-zero
        story as the generations, by construction not by TTL.

        The store-gen slot COMPOSES the view's model stamp into its high
        32 bits (docs/MAINTENANCE.md "Rolling model migration"): scores
        cached under one encoder must never answer a query encoded by
        another, even across a stamp flip that somehow left both
        generation numbers unchanged — e.g. a restored-from-backup store
        whose counters ran behind. One u64 keeps the peer-cache wire
        format (`transport._CACHE_HEAD`) and cross-front-end keys
        byte-identical without a protocol bump."""
        if self._rcache_cap <= 0:
            return None
        if view is None:
            view = self._view
        if view is None:
            return None          # partitioned serving caches per-request
        index_gen = (view.index.index_generation
                     if view.index is not None else -1)
        sgen = ((int(view.generation) & 0xFFFFFFFF)
                | ((int(view.store.model_step or 0) & 0xFFFFFFFF) << 32))
        return (self._normalize(query), int(k or self.cfg.eval.recall_k),
                int(nprobe or 0), sgen, int(index_gen),
                str(getattr(filters, "text", filters) or ""))

    def _result_cache_get(self, key: Optional[tuple],
                          count: bool = True) -> Optional[list]:
        if key is None:
            return None
        with self._rcache_lock:
            hits = self._rcache.get(key)
            if hits is not None:
                self._rcache.move_to_end(key)
        if hits is None:
            if count:
                self._m_rcache_misses.inc()
            return None
        if count:
            self._m_rcache_hits.inc()
        # copy per hit: callers may mutate the dicts they receive, and
        # the cached entry must stay byte-identical for the next repeat
        return [dict(h) for h in hits]

    def _result_cache_put(self, key: Optional[tuple], hits: list) -> None:
        if key is None:
            return
        size = 96 + sum(64 + len(h.get("snippet") or "") for h in hits)
        entry = [dict(h) for h in hits]
        with self._rcache_lock:
            old = self._rcache.pop(key, None)
            if old is not None:
                self._rcache_bytes -= self._entry_bytes(old)
            self._rcache[key] = entry
            self._rcache_bytes += size
            while len(self._rcache) > self._rcache_cap:
                _, ev = self._rcache.popitem(last=False)
                self._rcache_bytes -= self._entry_bytes(ev)

    @staticmethod
    def _entry_bytes(hits: list) -> int:
        return 96 + sum(64 + len(h.get("snippet") or "") for h in hits)

    def attach_cache_peers(self, clients: Sequence) -> None:
        """Attach sibling front ends' SocketSearchClient handles (built
        with result_cache=True) for fleet-wide sharing: a local miss
        probes each peer's cache before computing, and a local fill is
        pushed to every peer fire-and-forget. Peers that never negotiated
        FLAG_RESULT_CACHE degrade to no-ops per the transport contract.

        Each peer gets its own circuit breaker (`serve.breaker_*` knobs,
        docs/ROBUSTNESS.md "Network failure model"): after K consecutive
        probe failures the sibling is skipped outright — a down peer
        costs one failed dial per open interval, not a dial/timeout on
        every local miss. `serve.breaker_failures <= 0` disables."""
        serve_cfg = getattr(self.cfg, "serve", None)
        k_fail = int(getattr(serve_cfg, "breaker_failures", 3)
                     if serve_cfg is not None else 3)
        open_s = float(getattr(serve_cfg, "breaker_open_s", 0.25)
                       if serve_cfg is not None else 0.25)
        max_s = float(getattr(serve_cfg, "breaker_max_s", 30.0)
                      if serve_cfg is not None else 30.0)
        with self._rcache_lock:
            self._rcache_peers = list(clients)
            self._rcache_peer_breakers = [
                faults.CircuitBreaker(
                    failures=k_fail, open_s=open_s, max_open_s=max_s,
                    on_open=lambda b: faults.count(
                        "cache_peer_breaker_open"))
                if k_fail > 0 else None
                for _ in self._rcache_peers]

    def _peers_with_breakers(self) -> list:
        with self._rcache_lock:
            return list(zip(self._rcache_peers,
                            self._rcache_peer_breakers))

    def _peer_lookup(self, key: tuple) -> Optional[list]:
        """Probe attached peers for a miss; a hit is re-formatted against
        the LOCAL store (same corpus fleet-wide, so byte-identical) and
        inserted locally so the next repeat stays in-process."""
        peers = self._peers_with_breakers()
        if not peers:
            return None
        text, k, nprobe, store_gen, index_gen, ftext = key
        if ftext:
            # the peer-cache wire format (`transport._CACHE_HEAD`) has no
            # predicate slot: filtered entries stay front-end-local, so a
            # cross-peer probe can never alias a filtered key onto an
            # unfiltered sibling entry
            return None
        for peer, br in peers:
            if br is not None and not br.allow():
                continue         # breaker open: skip the down sibling
            try:
                got = peer.cache_lookup(text, k=k, nprobe=nprobe,
                                        store_gen=store_gen,
                                        index_gen=index_gen)
            except Exception:
                if br is not None:
                    br.record_failure()
                continue         # a broken peer never breaks a query
            if br is not None:
                br.record_success()
            if got is None:
                continue
            scores, ids = got
            hits = self._format(scores[0], ids[0])
            self._result_cache_put(key, hits)
            return hits
        return None

    def _peer_put(self, key: Optional[tuple], hits: list) -> None:
        if key is None:
            return
        peers = self._peers_with_breakers()
        if not peers:
            return
        text, k, nprobe, store_gen, index_gen, ftext = key
        if ftext:
            return               # filtered fills never ship to peers
        scores = np.full((k,), -np.inf, np.float32)
        ids = np.full((k,), -1, np.int64)
        for i, h in enumerate(hits[:k]):
            scores[i] = h["score"]
            ids[i] = h["page_id"]
        for peer, br in peers:
            if br is not None and not br.allow():
                continue
            try:
                # False = the frame never left (broken connection, or a
                # peer that never negotiated the flag — skipping that one
                # is free either way), so the bool feeds the breaker
                ok = peer.cache_put(text, k=k, nprobe=nprobe,
                                    store_gen=store_gen,
                                    index_gen=index_gen,
                                    scores=scores, ids=ids)
            except Exception:
                if br is not None:
                    br.record_failure()
                continue
            if br is not None:
                if ok:
                    br.record_success()
                else:
                    br.record_failure()

    # wire-facing helpers (infer/server.py T_CACHE_LOOKUP / T_CACHE_PUT):
    # operate on the raw [1, k] score/id arrays the RESULT frame ships
    def _result_cache_wire_get(self, ck) -> Optional[tuple]:
        """CacheKey probe from a peer. Returns ([1,k] scores, [1,k] ids)
        on a hit, None on a miss / disabled / generation mismatch. Never
        computes — a probe is cheaper than the shed it would replace."""
        if self._rcache_cap <= 0 or not self._rcache_fleet:
            return None
        key = (self._normalize(ck.query), ck.k, int(ck.nprobe),
               ck.store_gen, ck.index_gen, "")
        hits = self._result_cache_get(key)
        if hits is None:
            return None
        scores = np.full((1, ck.k), -np.inf, np.float32)
        ids = np.full((1, ck.k), -1, np.int64)
        for i, h in enumerate(hits[:ck.k]):
            scores[0, i] = h["score"]
            ids[0, i] = h["page_id"]
        return scores, ids

    def _result_cache_wire_put(self, ck, scores: np.ndarray,
                               ids: np.ndarray) -> bool:
        """CacheKey fill from a peer. The generations in the key are
        validated against the LIVE view — a stale push (peer behind a
        refresh) is silently dropped, never inserted under a reachable
        key. Formatting runs against the local store: same corpus
        fleet-wide, so the entry is byte-identical to a local fill."""
        if self._rcache_cap <= 0 or not self._rcache_fleet:
            return False
        live = self._result_cache_key(ck.query, ck.k, ck.nprobe or None)
        if live is None:
            return False
        if (live[3], live[4]) != (ck.store_gen, ck.index_gen):
            return False         # stale generations: drop
        key = (self._normalize(ck.query), ck.k, int(ck.nprobe),
               ck.store_gen, ck.index_gen, "")
        self._result_cache_put(
            key, self._format(np.asarray(scores).reshape(-1),
                              np.asarray(ids).reshape(-1)))
        return True

    def _tower_params(self, step) -> object:
        """Query-tower params for `step`: the extra tower attached by
        begin_migration() when one is loaded for that stamp, else THE
        embedder's own params (snapshot read — the tower map is whole-dict
        swapped)."""
        tw = self._towers
        if step is not None and step in tw:
            return tw[step]
        return self.embedder.params

    def _embed_queries_cached(self, queries: Sequence[str],
                              steps: Optional[Sequence[int]] = None
                              ) -> np.ndarray:
        """[n] texts -> [n, D] fp32 host query vectors — or, when `steps`
        lists more than one model stamp (dual-stamp serving,
        docs/MAINTENANCE.md "Rolling model migration"), [n, S*D] with one
        D-wide block per stamp in ascending-step order; `_qv_blocks` is
        the inverse. Each stamp encodes through the matching tower and its
        own cache keyspace."""
        if steps is None or len(steps) <= 1:
            return self._embed_queries_step(
                queries, steps[0] if steps else self.store.model_step)
        return np.concatenate(
            [self._embed_queries_step(queries, s) for s in steps], axis=1)

    def _embed_queries_step(self, queries: Sequence[str],
                            step) -> np.ndarray:
        """[n] texts -> [n, D] fp32 host query vectors for ONE model
        stamp, through the LRU cache; only the misses pay tokenize +
        compiled encode (in query_batch buckets). Host-side vectors cost
        the queries one device round trip per bucket — amortized over the
        coalesced batch, and the price of cache hits skipping the encode
        dispatch entirely."""
        params = self._tower_params(step)
        keys = [(step, self._normalize(q)) for q in queries]
        out = np.zeros((len(queries), self.store.dim), np.float32)
        miss: List[int] = []
        if self._cache_cap > 0:
            with self._cache_lock:
                for i, key in enumerate(keys):
                    vec = self._cache.get(key)
                    if vec is not None:
                        self._cache.move_to_end(key)
                        out[i] = vec
                    else:
                        miss.append(i)
            self._m_cache_hits.inc(len(queries) - len(miss))
            self._m_cache_misses.inc(len(miss))
        else:
            miss = list(range(len(queries)))
        # cache-hit annotation on the request trace: an all-hit request
        # legitimately has NO tokenize/encode spans — the annotation says
        # why, instead of the trace just looking truncated
        cur = self.tracer.current()
        if cur is not None:
            cur.set_attrs(cache_hits=len(queries) - len(miss),
                          cache_misses=len(miss))
        if not miss:
            return out
        # intra-batch dedup: a coalesced batch of head-skewed traffic
        # repeats queries — encode each unique missing key once, fan the
        # vector out to its duplicates (they still count as lookup misses)
        first: Dict[tuple, int] = {}
        alias: List[tuple] = []
        uniq: List[int] = []
        for i in miss:
            j = first.get(keys[i])
            if j is None:
                first[keys[i]] = i
                uniq.append(i)
            else:
                alias.append((i, j))
        tok = self.embedder.query_tok or self.embedder.page_tok
        B = self.query_batch
        for s in range(0, len(uniq), B):
            grp = uniq[s: s + B]
            with self._stage("tokenize", queries=len(grp)):
                enc = tok.encode_batch([queries[i] for i in grp])
            pad = B - enc.shape[0]
            if pad:
                enc = np.concatenate(
                    [enc, np.zeros((pad,) + enc.shape[1:], enc.dtype)])
            self._note_dispatch_shape("encode_query", batch=B,
                                      tokens=int(enc.shape[1]))
            with self._stage("encode", queries=len(grp)):
                vecs = np.asarray(
                    self.embedder._encode_query(params,
                                                self.embedder._put(enc)),
                    np.float32)[: len(grp)]
            out[grp] = vecs
        for i, j in alias:
            out[i] = out[j]
        if self._cache_cap > 0:
            with self._cache_lock:
                for i in miss:
                    self._cache[keys[i]] = out[i]
                    self._cache.move_to_end(keys[i])
                while len(self._cache) > self._cache_cap:
                    self._cache.popitem(last=False)
        return out

    # -- micro-batcher -----------------------------------------------------
    def start_batcher(self) -> "SearchService":
        """Route subsequent search() calls through the dynamic micro-batcher
        (serve.batch_window_ms / serve.max_batch): concurrent callers
        coalesce into shared search_many dispatches. Idempotent; close()
        stops it."""
        if self._batcher is None:
            s = self.cfg.serve
            # the batcher reads the window per batch: fixed base, or
            # wherever the adaptive controller currently has it
            window_s = (self._window_ctl.current_s
                        if self._window_ctl is not None
                        else lambda: self._window_base_ms / 1000.0)
            self._batcher = _MicroBatcher(self, window_s,
                                          s.max_batch, s.max_queue)
        return self

    @property
    def batching(self) -> bool:
        return self._batcher is not None

    # -- background maintenance (docs/MAINTENANCE.md) ----------------------
    def start_maintenance(self, threads: bool = True):
        """Attach the background MaintenanceService to this service:
        compaction, off-path index rebuilds, and the janitor run against
        this store, hot-swapping completed work in via refresh(). Under
        maintenance.bg_rebuild (the default), drift-triggered full
        rebuilds are DEFERRED off the refresh() caller from here on — the
        worker builds the next index generation beside the live one.
        `threads=False` attaches without spawning workers (callers drive
        `run_once()` themselves: the loadtest mutator, bench). Idempotent;
        close() stops it."""
        if self._maintenance is None:
            from dnn_page_vectors_tpu.maintenance import MaintenanceService
            m_cfg = getattr(self.cfg, "maintenance", None)
            if getattr(m_cfg, "bg_rebuild", True):
                self._defer_rebuilds = True
            self._maintenance = MaintenanceService(
                self.cfg, self.store.directory, self.embedder.mesh,
                svc=self)
            if threads:
                self._maintenance.start()
        return self._maintenance

    def close(self) -> None:
        if self._maintenance is not None:
            self._maintenance.close()
            self._maintenance = None
        if self._pset is not None:
            self._pset.close()
        if self._batcher is not None:
            self._batcher.close()
            # telemetry survives the thread: metrics() after close still
            # reports what the batcher did
            self._batch_sizes = self._batcher.batch_sizes
            self._batcher = None
        if self._log is not None:
            self._log.write(self.metrics())

    def __enter__(self) -> "SearchService":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def metrics(self) -> Dict:
        """Serving counters + the per-stage breakdown, metrics-log ready."""
        total = self.cache_hits + self.cache_misses
        view = self._view
        rec = {
            "serve_degraded": self.degraded,
            "serve_cache_hits": self.cache_hits,
            "serve_cache_misses": self.cache_misses,
            "serve_cache_hit_rate": round(self.cache_hits / total, 4)
            if total else 0.0,
            # live-update state (docs/UPDATES.md): which store/index
            # generation this service is answering from, and how it got
            # there — always present so dashboards can alert on drift
            "store_generation": view.generation,
            "index_generation": (view.index.index_generation
                                 if view.index is not None else None),
            "docs_appended": view.docs_appended,
            "tombstoned": view.tombstoned,
            "refreshes": self.refreshes,
            "incremental_updates": self.incremental_updates,
            "full_rebuilds": self.full_rebuilds,
            # tombstone-aware restage policy (docs/UPDATES.md)
            "restage_skipped": self.restage_skipped,
            "restage_forced": self.restage_forced,
            # dead-byte accounting (docs/MAINTENANCE.md): what the
            # background compactor would reclaim from THIS view's chain
            "tombstone_density": view.maint_stats["tombstone_density"],
            "dead_rows": view.maint_stats["dead_rows"],
            "reclaimable_bytes": view.maint_stats["reclaimable_bytes"],
            # recompilation + adaptive-window state (docs/SERVING.md):
            # how many distinct compiled shapes this service has
            # dispatched, and the micro-batch window currently in force
            "serve_recompiles": self.recompiles,
            "serve_batch_window_ms": round(self.batch_window_ms, 3),
            **self._window_metrics(),
            **self.profiler.summary(prefix="serve_stage_"),
        }
        sizes = (self._batcher.batch_sizes if self._batcher is not None
                 else self._batch_sizes)
        if sizes:
            rec["serve_batches"] = len(sizes)
            rec["serve_mean_batch"] = round(sum(sizes) / len(sizes), 2)
        if self._pset is not None:
            # partitioned-serving topology + routing health
            # (docs/SCALING.md): per-partition/replica qps, p99, queue
            # depth, shed and degraded-serve counts — the loadtest report
            # and dashboards read this block as-is
            rec["serve_partitions"] = self._pset.partitions
            rec["serve_replicas"] = self._pset.replicas
            rec["replica_shed"] = self.replica_shed
            rec["partition_degraded"] = self.partition_degraded_serves
            rec["partitions"] = self._pset.stats()
        # over-the-wire serving block (docs/SERVING.md "Network front
        # end") — emitted ONLY when non-empty, so every pre-transport
        # consumer of this record (report-shape tests, dashboards, the
        # loadgen trial records that copy it) stays byte-stable on an
        # in-process service
        transport: Dict = {}
        if self.wire_bytes:
            transport["wire_bytes"] = self.wire_bytes
            if self.wire_raw_bytes > self.wire_bytes:
                # the wire-compression pair (docs/SERVING.md): what the
                # same traffic would have cost raw, and the live ratio
                transport["wire_raw_bytes"] = self.wire_raw_bytes
                transport["wire_compression_ratio"] = round(
                    self.wire_raw_bytes / self.wire_bytes, 3)
        if self.deadline_sheds:
            transport["deadline_sheds"] = self.deadline_sheds
        if self.hedge_fires:
            transport["hedge_fires"] = self.hedge_fires
        if self._fanout is not None:
            transport.update(self._fanout.stats())
        if transport:
            rec["transport"] = transport
        if self._rcache_cap > 0:
            # generation-keyed result cache (docs/SERVING.md "Result
            # cache") — emitted ONLY when the feature is on, so the
            # default record shape stays byte-stable
            rhits = self.result_cache_hits
            rmiss = self.result_cache_misses
            with self._rcache_lock:
                entries = len(self._rcache)
                rbytes = self._rcache_bytes
            rec["result_cache"] = {
                "hits": rhits, "misses": rmiss,
                "hit_rate": round(rhits / (rhits + rmiss), 4)
                if (rhits + rmiss) else 0.0,
                "entries": entries, "bytes": rbytes,
                "capacity": self._rcache_cap,
                "fleet": self._rcache_fleet,
            }
        if self._serve_index != "exact":
            # ANN counters + the active index config (the PR 3
            # cache-counter pattern: flat keys, always present when the
            # feature is on, so dashboards need no key-existence logic)
            rec["ann_lists_scanned"] = self.ann_lists_scanned
            rec["ann_candidates_reranked"] = self.ann_candidates_reranked
            rec["ann_fallbacks"] = self.ann_fallbacks
            # store payload bytes the ANN gather actually moved (codes +
            # rerank rows on a PQ index, stored-width rows otherwise) —
            # the bandwidth denominator behind ann_gather_mbytes_per_s
            rec["ann_gather_bytes"] = self.ann_gather_bytes
            rec["ann_index"] = {
                "index": self._serve_index, "nprobe": self._nprobe,
                "nlist": self._index.nlist if self._index else None,
                "available": self._index is not None,
                "pq_m": self._index.pq_m if self._index else 0,
                "hot_rows": self._index.hot_rows if self._index else 0,
                **({"error": self._index_error}
                   if self._index_error else {})}
        if self.fault_counters:
            rec["fault_counters"] = faults.counters()
        return rec

    def _window_metrics(self) -> Dict[str, float]:
        """The live windowed view (docs/OBSERVABILITY.md): rates and tail
        latency over the last obs.window_s seconds, not since boot — the
        "qps @ p99 < X ms" SLO pair reads straight off these."""
        req_w = self._m_requests.window_count()
        err_w = self._m_errors.window_count()
        hit_w = self._m_cache_hits.window_count()
        miss_w = self._m_cache_misses.window_count()
        lat = self._m_latency
        return {
            "serve_window_s": self._window_s,
            "serve_window_qps": round(self._m_requests.rate(), 3),
            "serve_window_error_rate": round(
                err_w / (req_w + err_w), 4) if (req_w + err_w) else 0.0,
            "serve_window_cache_hit_rate": round(
                hit_w / (hit_w + miss_w), 4) if (hit_w + miss_w) else 0.0,
            "serve_window_p50_ms": round(lat.window_percentile(50), 3),
            "serve_window_p99_ms": round(lat.window_percentile(99), 3),
            "serve_window_queue_wait_p99_ms": round(
                self._m_queue_wait.window_percentile(99), 3),
        }

    def autoscale_signals(self) -> Dict[str, float]:
        """The two windowed pressure signals the maintenance autoscale
        pillar ladders on (docs/SCALING.md "Scale-out tier"): queue-wait
        p99 over the telemetry window — requests stacking faster than
        dispatches drain — and the deadline-shed rate — admission
        already refusing work. Both read the SAME instruments the
        adaptive batcher and the admission door feed, so the policy
        sees exactly what the serving path saw."""
        return {
            "queue_wait_p99_ms": round(
                self._m_queue_wait.window_percentile(99), 3),
            "queue_wait_samples": float(self._m_queue_wait.window_count()),
            "shed_rate": round(self._m_deadline_shed.rate(), 4),
            "window_s": self._window_s,
        }

    # -- exposition (docs/OBSERVABILITY.md) --------------------------------
    def metrics_snapshot(self) -> Dict:
        """JSON snapshot endpoint: the flat metrics() record plus the full
        registry view (typed instruments, windowed stats, the lifecycle
        event ring). Everything json-serializable — served by
        `cli serve-metrics --json` and the `:metrics` control line."""
        return {"metrics": self.metrics(), **self.registry.snapshot()}

    def prometheus_text(self) -> str:
        """Prometheus text exposition of the service registry — served by
        `cli serve-metrics`; one scrape of this is the dashboard feed."""
        return self.registry.prometheus_text()

    # -- search ------------------------------------------------------------
    def warmup(self, k: Optional[int] = None, timing_iters: int = 3) -> None:
        """Compile the encode + top-k programs before the first query, then
        time `timing_iters` warm searches (MEDIAN, so one GC pause or
        tunnel hiccup can't skew the reported number; results are fully
        materialized to host, so the clock covers tokenize + encode +
        top-k + snippet end-to-end) into `warm_latency_ms`. The cache is
        bypassed while timing — warm latency means the real encode path,
        not a dictionary lookup. Pass the SAME k the queries will use —
        the top-k program cache is keyed on it, so a different k would
        leave the real program cold."""
        self.search_many(["warmup"], k=k)
        lat = LatencyStats()
        cap, self._cache_cap = self._cache_cap, 0
        rcap, self._rcache_cap = self._rcache_cap, 0
        try:
            for _ in range(max(1, timing_iters)):
                with lat.timed():
                    self.search_many(["warmup"], k=k)
        finally:
            self._cache_cap = cap
            self._rcache_cap = rcap
        self.warm_latency_ms = lat.percentile_ms(50)

    def search(self, query: str, k: Optional[int] = None,
               nprobe: Optional[int] = None,
               deadline_ms: Optional[float] = None,
               deadline: Optional[float] = None,
               filters=None) -> List[Dict]:
        """One query -> top-k results. With the micro-batcher running
        (start_batcher), the call enqueues and blocks on its future —
        concurrent callers share dispatches; otherwise it is a direct
        single-query search_many. Either way the request is traced
        (obs.enabled) and lands in the windowed latency/qps instruments:
        the batched path's trace follows the request THROUGH the
        dispatcher thread (queue_wait + the adopted shared dispatch).
        `nprobe` overrides serve.nprobe for this request on an IVF
        service (the batcher coalesces per distinct (k, nprobe)).

        `deadline_ms` is this request's RELATIVE latency budget (None =
        the serve.deadline_ms default; <= 0 disables); `deadline` is an
        ABSOLUTE deadline on the service clock, already anchored — the
        network front end resolves each request's budget at frame
        receipt and passes it through here, so a request that aged out
        between the socket and this thread is ALREADY expired at
        admission. A request that cannot make its deadline is shed at
        admission — or at the micro-batch door if it expires while
        queued — with DeadlineExceeded; sheds count in
        serve.deadline_shed, never in serve.errors (docs/SERVING.md
        "Network front end").

        `filters` restricts results to rows whose packed attribute word
        satisfies the predicate (text or compiled, index/attrs.py,
        docs/ANN.md "Filtered retrieval"): the canonical form keys the
        cache and the batcher's coalescing group, the IVF path
        intersects it with the posting gather BEFORE ADC scoring, and
        the exact fallback scans only matching rows. A malformed
        predicate raises FilterError (a ValueError) before admission."""
        pred = _compile_filters(filters)
        if deadline is None:
            deadline = self.default_deadline(deadline_ms)
        # result-cache probe at the admission door (docs/SERVING.md
        # "Result cache"): a repeat answers BEFORE admission, so a hit
        # can never be shed and never consumes a micro-batch bucket
        # slot — the generation-qualified key makes a stale hit
        # impossible, not merely unlikely
        rkey = self._result_cache_key(query, k, nprobe, filters=pred)
        if rkey is not None:
            t0 = time.perf_counter()
            hits = self._result_cache_get(rkey, count=False)
            if hits is None:
                hits = self._peer_lookup(rkey)
            if hits is not None:
                self._m_rcache_hits.inc()
                self._m_requests.inc()
                self._m_latency.observe(
                    (time.perf_counter() - t0) * 1000.0)
                return hits
            self._m_rcache_misses.inc()
        # admission happens BEFORE the queue: a shed request never
        # consumes queue capacity or a bucket slot (raises out of here)
        self._admit(deadline)
        b = self._batcher
        if b is None:
            return self.search_many([query], k=k, nprobe=nprobe,
                                    filters=pred, deadline=deadline,
                                    _probe_cache=False)[0]
        t0 = time.perf_counter()
        try:
            with self.tracer.trace("search",
                                   k=k or self.cfg.eval.recall_k,
                                   query=self._normalize(query)[:80]):
                res = b.submit(query, k, nprobe, deadline=deadline,
                               filters=pred.text if pred is not None
                               else None).result()
        except DeadlineExceeded:
            # the micro-batch door shed it (expired while queued): a
            # deliberate availability decision, already counted in
            # serve.deadline_shed — not a serving error
            raise
        except BaseException:
            self._m_errors.inc()
            raise
        self._m_requests.inc()
        self._m_latency.observe((time.perf_counter() - t0) * 1000.0)
        return res

    def search_many(self, queries: Sequence[str], k: Optional[int] = None,
                    nprobe: Optional[int] = None, filters=None,
                    *, _record: bool = True, _probe_cache: bool = True,
                    deadline: Optional[float] = None) -> List[List[Dict]]:
        """Vectorized multi-query search: one result list per query, in
        order. Queries fill the compiled `query_batch` bucket (larger lists
        tile over full buckets — one compiled program regardless of count);
        per-shard top-k and the cross-shard merge run once per bucket, and
        on a degraded service the failed shards' disk sweep folds in once
        per bucket too.

        Telemetry: the call runs under a request trace (a fresh root for
        direct callers, a child span inside a batcher dispatch) and — for
        direct callers (`_record`) — counts every query into the windowed
        request/error/latency instruments; the batcher records per-request
        numbers itself so coalesced queries are never double-counted.
        `filters` applies ONE attribute predicate (text or compiled,
        index/attrs.py) to the whole batch — per-query predicates arrive
        as separate calls (the batcher coalesces per predicate)."""
        k = k or self.cfg.eval.recall_k
        n = len(queries)
        if n == 0:
            return []
        pred = _compile_filters(filters)
        # result-cache shortcut for direct callers (`_record` — batcher
        # dispatches and search()'s delegated misses skip the re-probe):
        # an ALL-hit batch answers without embedding or scanning anything;
        # a partial batch recomputes whole (one dispatch either way) and
        # only the true misses count as misses
        if _record and _probe_cache and self._rcache_cap > 0:
            t0 = time.perf_counter()
            cached = [self._result_cache_get(
                self._result_cache_key(q, k, nprobe, filters=pred),
                count=False) for q in queries]
            miss_n = sum(1 for c in cached if c is None)
            if miss_n == 0:
                self._m_rcache_hits.inc(n)
                self._m_requests.inc(n)
                self._m_latency.observe(
                    (time.perf_counter() - t0) * 1000.0, n=n)
                return cached
            self._m_rcache_misses.inc(miss_n)
        # ONE view for the whole call (docs/UPDATES.md): a refresh() swap
        # mid-call cannot mix generations inside a result set — this
        # dispatch finishes on the view it captured, the next one sees the
        # new view
        view = self._view
        t0 = time.perf_counter()
        try:
            with self.tracer.root_or_span("search_many", n_queries=n, k=k):
                out = self._search_view(view, list(queries), n, k, nprobe,
                                        deadline=deadline, predicate=pred)
        except BaseException:
            if _record:
                self._m_errors.inc(n)
            raise
        if _record:
            self._m_requests.inc(n)
            self._m_latency.observe((time.perf_counter() - t0) * 1000.0,
                                    n=n)
        return out

    def _search_view(self, view: "_ServeView", queries: List[str],
                     n: int, k: int,
                     nprobe: Optional[int] = None,
                     deadline: Optional[float] = None,
                     predicate=None) -> List[List[Dict]]:
        if predicate is not None:
            # one event per filtered dispatch (docs/OBSERVABILITY.md):
            # which predicate ran, how many queries rode it
            self.registry.event("filtered_query", {
                "predicate": predicate.text[:200], "n_queries": n})
        # mid-migration the view serves two stamps: encode the batch once
        # per stamp (stacked [n, S*D]) so every shard can be scored by the
        # tower matching its recorded model step; the stacked matrix ships
        # over the scatter paths unchanged (VQUERY frames carry a dynamic
        # dim) and each receiver splits it against ITS view's stamp list.
        # The kwarg only appears when the view's stamp table disagrees
        # with the serving model step — two stamps mid-sweep, or one
        # stamp that isn't the manifest's (a crash landed between the
        # last unit flip and complete()'s stamp flip): model-free tests
        # swap in single-argument embed stubs on the common path.
        qv = (self._embed_queries_cached(queries, steps=view.steps)
              if len(view.steps) > 1
              or (view.steps and view.steps[0] != view.store.model_step)
              else self._embed_queries_cached(queries))
        fanout = self._fanout
        if fanout is not None and fanout.active():
            # over-the-wire scatter (infer/partition_host.py): the RPC
            # fan-out to registered partition workers, with per-partition
            # deadlines, hedged requests, and a per-partition LOCAL
            # fallback that keeps results byte-identical when a worker
            # dies mid-request
            best_s, best_i = fanout.topk(qv, n, k, nprobe,
                                         deadline=deadline,
                                         predicate=predicate)
        elif self._pset is not None:
            # partitioned scatter-gather (infer/partition.py): the
            # coalesced bucket's query matrix broadcasts ONCE to every
            # partition; each answers its local top-k over only its shard
            # range, results fold through the partition merge tree
            best_s, best_i = self._pset.topk(qv, n, k, nprobe,
                                             predicate=predicate)
        else:
            best_s, best_i, _ = self._topk_view(view, qv, n, k, nprobe,
                                                predicate=predicate)
        with self._stage("format"):
            out = [self._format(best_s[i], best_i[i]) for i in range(n)]
        if self._rcache_cap > 0:
            # fill keyed against the CAPTURED view's generations: a
            # refresh that swapped mid-compute files this result under
            # the old (now unreachable) key, so a stale fill can never
            # answer a post-swap probe — staleness-zero by construction
            for q, hits in zip(queries, out):
                key = self._result_cache_key(q, k, nprobe, view=view,
                                             filters=predicate)
                self._result_cache_put(key, hits)
                self._peer_put(key, hits)
        return out

    def topk_vectors(self, qv: np.ndarray, k: Optional[int] = None,
                     nprobe: Optional[int] = None,
                     deadline: Optional[float] = None,
                     filters=None) -> tuple:
        """Raw retrieval for PRE-COMPUTED query vectors: (scores [n, k]
        fp32, page_ids [n, k] int64, -1-padded), skipping tokenize/encode
        and snippet formatting. The bench's host-simulated partitioned
        phase, the network front end's vector protocol, and vector-level
        tests drive the full serving top-k (RPC fan-out, partitioned, or
        single-view) through this without a model."""
        k = k or self.cfg.eval.recall_k
        pred = _compile_filters(filters)
        qv = np.asarray(qv, np.float32)
        n = qv.shape[0]
        fanout = self._fanout
        if fanout is not None and fanout.active():
            return fanout.topk(qv, n, k, nprobe, deadline=deadline,
                               predicate=pred)
        if self._pset is not None:
            return self._pset.topk(qv, n, k, nprobe, predicate=pred)
        s, i, _ = self._topk_view(self._view, qv, n, k, nprobe,
                                  predicate=pred)
        return s, i

    def _topk_view(self, view: "_ServeView", qv: np.ndarray, n: int, k: int,
                   nprobe: Optional[int] = None, predicate=None):
        """Raw top-k of `n` real query rows of `qv` over ONE view:
        (scores [n, k] fp32, page_ids [n, k] int64, scan_bytes). This is
        the per-partition unit of work of the scatter-gather — a
        partition worker runs it over its own restricted view — and the
        whole retrieval of the single-view path. `scan_bytes` is the
        candidate payload this view scanned to answer: the ANN gather
        bytes, or the view's full row bytes on the exact path — the
        per-partition critical-path byte count the partitioned bench
        phase records (drops ~1/P under partitioning)."""
        qv = np.asarray(qv, np.float32)
        blocks = self._qv_blocks(view, qv)
        if self._serve_index == "ivf":
            # a mixed-stamp view never consults the index (_ann_topk's
            # migration guard): each shard must be scored by its own
            # tower's block, which the exact path below routes per shard
            res = (self._ann_topk(view, next(iter(blocks.values())),
                                  n, k, nprobe, predicate=predicate)
                   if len(view.steps) <= 1 else None)
            if res is not None:
                return res
            # exact path serves this request; visible in metrics + counters
            self._m_ann_fallbacks.inc(n)
            faults.count("serve_ann_fallbacks", n)
        if predicate is not None:
            # filtered exact: host-mask each shard's attribute words and
            # scan only the matching rows — the resident HBM program and
            # the streaming sweep both score EVERY row, so neither can
            # honor the scan-bytes contract for a predicate
            return self._filtered_exact(view, blocks, n, k, predicate)
        B = self.query_batch
        row_bytes = view.store.row_bytes
        if view.shards is None:
            # streaming store: pad the query matrix to a bucket multiple so
            # every call reuses one compiled shape, then sweep disk ONCE
            # per stamp group (one group total outside a migration). The
            # sweep reads the VIEW's store handle — refresh() never mutates
            # it (it opens a fresh handle for the next view), so a swap
            # mid-sweep cannot mix generations, while an in-place store
            # mutation (ensure_model_step under a live service) still
            # propagates per request like it always did. A RESTRICTED
            # (partition) view sweeps its frozen entry subset instead —
            # its shard range is the ownership contract.
            groups: Dict = {}
            for e in view.entries:
                groups.setdefault(view.store.entry_step(e), []).append(e)
            scan = sum(e["count"] for e in view.entries) * row_bytes
            fallback = next(iter(blocks.values()))

            def _sweep(step, entries):
                qp = blocks.get(step, fallback)[:n]
                pad = (-n) % B
                if pad:
                    qp = np.concatenate(
                        [qp, np.zeros((pad, qp.shape[1]), np.float32)])
                self._note_dispatch_shape("topk_over_store", batch=B, k=k)
                return topk_over_store(
                    qp, view.store, self.embedder.mesh, k=k,
                    query_batch=B, entries=entries)

            if len(groups) <= 1:
                step = next(iter(groups)) if groups else None
                with self._stage("topk", path="streaming"):
                    scores, ids = _sweep(
                        step, view.entries if view.restricted else None)
                return scores[:n], ids[:n], scan
            out_s = np.full((n, k), -np.inf, np.float32)
            out_i = np.full((n, k), -1, np.int64)
            with self._stage("topk", path="streaming",
                             stamps=len(groups)):
                for step, entries in groups.items():
                    s_g, i_g = _sweep(step, entries)
                    out_s, out_i = _merge_topk_host(
                        out_s, out_i, np.asarray(s_g[:n], np.float32),
                        np.asarray(i_g[:n], np.int64), k)
            return out_s, out_i, scan
        # Two passes over the buckets: dispatch them ALL first (the merge
        # output stays on device — JAX's async queue runs bucket i+1's
        # top-k while bucket i's packed transfer drains), THEN materialize
        # in order. A >bucket batch therefore pipelines compute against
        # transfer instead of serializing dispatch/drain per bucket.
        pending = [(s, self._dispatch_bucket(
                        view, {st: blk[s: s + B]
                               for st, blk in blocks.items()}, k))
                   for s in range(0, n, B)]
        out_s = np.full((n, k), -np.inf, np.float32)
        out_i = np.full((n, k), -1, np.int64)
        for s0, (nreal, qs, packed) in pending:
            bs, bi = self._collect_bucket(view, nreal, qs, packed, k)
            out_s[s0: s0 + nreal] = bs[:nreal]
            out_i[s0: s0 + nreal] = bi[:nreal]
        scan = (sum(nv for _, nv, _, _ in view.shards)
                + sum(e["count"] for e in view.stream_entries)) * row_bytes
        return out_s, out_i, scan

    def _filtered_exact(self, view: "_ServeView", blocks: Dict, n: int,
                        k: int, predicate) -> tuple:
        """Exact filtered retrieval over ONE view: per shard, evaluate
        the predicate against the packed attribute words on host, gather
        ONLY the matching rows, and fold their exact scores into the
        running top-k (docs/ANN.md "Filtered retrieval"). Every topology
        — local, partitioned scatter, socket fan-out — answers a
        filtered exact query through this method over its own frozen
        entry subset, and the stable host merge makes the folded result
        byte-identical to the single-process filtered oracle, the same
        contract the unfiltered exact path pins.

        `scan_bytes` counts the attribute words read (4 B/row over the
        view) plus the matching rows' stored payload: a predicate of
        selectivity s scans ~s× the unfiltered exact bytes — the number
        bench.py's filtered phase records against its <=0.3x gate."""
        row_bytes = view.store.row_bytes
        fallback = next(iter(blocks.values()))
        out_s = np.full((n, k), -np.inf, np.float32)
        out_i = np.full((n, k), -1, np.int64)
        scan = 0
        with self._stage("topk", path="filtered_exact"):
            for entry in view.entries:
                if entry["count"] == 0:
                    continue
                words = view.store.load_attrs(entry)
                scan += int(words.nbytes)
                keep = predicate.matches(words)
                if not keep.any():
                    continue
                ids, vecs = view.store._load_entry(entry)
                ids = ids[keep]
                live = ids >= 0      # tombstones match nothing
                if not live.any():
                    continue
                rows = np.asarray(np.asarray(vecs)[keep][live], np.float32)
                ids = ids[live]
                scan += int(rows.shape[0]) * row_bytes
                qp = np.asarray(
                    blocks.get(view.store.entry_step(entry), fallback)[:n],
                    np.float32)
                scores = qp @ rows.T
                kk = min(k, scores.shape[1])
                part = np.argpartition(-scores, kk - 1,
                                       axis=1)[:, :kk]
                out_s, out_i = _merge_topk_host(
                    out_s, out_i,
                    np.take_along_axis(scores, part, axis=1)
                    .astype(np.float32),
                    ids[part].astype(np.int64), k)
        return out_s, out_i, scan

    def _qv_blocks(self, view: "_ServeView",
                   qv: np.ndarray) -> Dict:
        """Split a query matrix into per-stamp [n, D] blocks keyed by
        model step, ascending — the inverse of the stacked encode in
        _embed_queries_cached (docs/MAINTENANCE.md "Rolling model
        migration"). Handles the two transient skews a rolling fleet
        walk-through can produce:

          * WIDE matrix onto a single-stamp view (a dual-stamp front end
            scattering to a receiver whose store handle hasn't caught the
            migration record yet, or already passed the completion flip):
            pick this view's block by the migration record's
            ascending-stamp order, else the LAST block — completion skew
            is the common case and the target stamp stacks last;
          * NARROW matrix onto a mixed view (an encoder predating the
            record): score every shard with the one block — old-stamp
            shards exactly, new-stamp shards approximately, for the one
            refresh round it takes the caller to catch up (counted as
            `serve_stamp_skew`)."""
        D = int(view.store.dim)
        w = int(qv.shape[1])
        steps = view.steps
        if len(steps) <= 1:
            step = steps[0] if steps else None
            if w <= D:
                return {step: qv}
            nb = w // D
            mig = view.store.migration or {}
            order = sorted({int(s) for s in (mig.get("from_step"),
                                             mig.get("to_step"))
                            if s is not None})
            pos = (order.index(step)
                   if step in order and order.index(step) < nb
                   else nb - 1)
            return {step: qv[:, pos * D:(pos + 1) * D]}
        if w <= D:
            self._count_fault("serve_stamp_skew")
            return {s: qv for s in steps}
        nb = w // D
        return {s: qv[:, min(i, nb - 1) * D: (min(i, nb - 1) + 1) * D]
                for i, s in enumerate(steps)}

    # graftcheck: hot
    def _dispatch_bucket(self, view: "_ServeView", qblocks: Dict, k: int):
        """HBM-resident fast path for ONE compiled bucket (<= query_batch
        real rows): every resident shard's top-k program dispatches under
        JAX's async queue and the cross-shard merge runs ON DEVICE; the
        packed [B, 2k] result is returned still on device — exactly ONE
        drain round trip per BUCKET happens later in _collect_bucket,
        regardless of shard count or how many queries share the dispatch.
        (The old per-shard host merge cost ~2 transfers per shard: ~100 ms
        each over a tunneled chip, and a forced pipeline bubble even on
        local PCIe.)

        `qblocks` maps model stamp -> [<=B, D] query block (_qv_blocks):
        each shard is scored by the block matching its recorded stamp, so
        a mid-migration bucket runs the same one merged dispatch — the
        dual-stamp routing costs one extra h2d put per extra stamp, not a
        second sweep."""
        import jax.numpy as jnp

        nreal = next(iter(qblocks.values())).shape[0]
        B = self.query_batch
        qs: Dict = {}
        for st, blk in qblocks.items():
            if blk.shape[0] < B:
                blk = np.concatenate(
                    [blk, np.zeros((B - blk.shape[0], blk.shape[1]),
                                   np.float32)])
            qs[st] = jnp.asarray(blk, jnp.float32)
        fallback = next(iter(qs.values()))
        self._note_dispatch_shape("sharded_topk", batch=B, k=k,
                                  rows=view.pad_rows,
                                  shards=len(view.shards))
        with self._stage("topk", shards=len(view.shards)):
            cands = [
                sharded_topk(qs.get(st, fallback), pages,
                             self.embedder.mesh, k=k, valid=n, scales=scl)
                for st, (_, n, pages, scl) in zip(view.shard_steps,
                                                  view.shards)]
            packed = view.merge(cands)                 # async, on device
        return nreal, qs, packed

    # graftcheck: hot
    def _collect_bucket(self, view: "_ServeView", nreal: int, qs, packed,
                        k: int):
        """Drain one dispatched bucket to host (scores [nreal, k] fp32,
        page_ids [nreal, k] int64) — formatting happens once per call in
        _search_view, so the partitioned scatter-gather can fold raw
        per-partition candidates before any snippet work."""
        with self._stage("merge"):
            # graftcheck: off=host-sync -- THE one packed d2h per
            # bucket: the whole point of the merged [B, 2k] layout
            packed = np.asarray(packed)
        top_s = np.ascontiguousarray(packed[:, :k]).view(np.float32)
        top_i = packed[:, k:]
        pids = np.where(top_i >= 0,
                        view.pid_table[np.clip(top_i, 0, None)], -1)
        best_s = np.where(np.isfinite(top_s), top_s, -np.inf).astype(
            np.float32)
        best_i = pids.astype(np.int64)
        if not view.stream_entries:
            return best_s[:nreal], best_i[:nreal]
        # degraded tail: shards that failed to stage are re-read from disk
        # — ONCE for the whole bucket, prefetched one shard ahead on a
        # reader thread — and folded into the resident results through the
        # same merge_shard_topk the streaming path uses: identical results,
        # per-bucket disk reads for exactly the failed shards

        def _load_tail():
            for entry in view.stream_entries:
                ids, vecs, scl = view.store._load_entry(entry, raw=True)
                # graftcheck: off=host-sync -- mmap'd host arrays
                # (degraded tail reads disk, no device involved)
                yield np.asarray(ids, np.int64), np.asarray(vecs), scl

        fallback = next(iter(qs.values()))
        with self._stage("topk", path="degraded_tail",
                         shards=len(view.stream_entries)):
            tail = read_ahead(_load_tail(), depth=1)
            for entry, (ids, vecs, scl) in zip(view.stream_entries, tail):
                nrows = vecs.shape[0]
                if nrows == 0:
                    continue
                pages, scales = stage_shard(vecs, view.pad_rows,
                                            view.store.dim,
                                            self.embedder.mesh, scales=scl)
                # the degraded tail routes by stamp too: a failed-to-stage
                # shard still scores against its own tower's block
                q_e = qs.get(view.store.entry_step(entry), fallback)
                best_s, best_i = merge_shard_topk(
                    q_e, pages, ids, nrows, self.embedder.mesh, k,
                    best_s, best_i, scales=scales)
        return best_s[:nreal], best_i[:nreal]

    def _format(self, scores, ids) -> List[Dict]:
        return [
            {"page_id": int(i), "score": round(float(s), 4),
             "snippet": self.corpus.page_text(int(i))[: self.snippet_chars]}
            for s, i in zip(scores, ids) if i >= 0]
