"""Query-time retrieval service (the serving half of call stack §4.3).

`cli.py search` originally rebuilt the corpus, tokenizer, and model per
invocation — fine as a demo, not a serving path (VERDICT r3 Weak #6).
SearchService is the serving path: everything is loaded ONCE (params on
device, store shards optionally pre-staged in HBM), so per-query cost is
one tokenize + one compiled encode + MXU top-k over resident vectors.

HBM pre-staging: when the store fits the configured budget, every shard is
device_put once (row-sharded over the mesh 'data' axis, padded to one
static shape so a single compiled top-k program serves all shards) and
queries never touch disk. Oversized stores transparently fall back to the
streaming path (ops/topk.py:topk_over_store) — same results, per-query
disk reads.

Degradation (docs/ROBUSTNESS.md): a shard that FAILS to stage — an I/O
fault during the device_put, a checksum mismatch, or the HBM budget
overrunning mid-stage — does not kill the service. Checksum failures are
quarantined (the store drops them); every other failure falls back
PER-SHARD to the streaming top-k path: staged shards answer from HBM, the
failed ones are re-read from disk per query and merged on host. The
service marks itself `degraded`, bumps fault counters, and reports both
through the metrics log, so a half-staged service is visible, not silent.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional

import numpy as np

from dnn_page_vectors_tpu.infer.bulk_embed import BulkEmbedder
from dnn_page_vectors_tpu.infer.vector_store import VectorStore
from dnn_page_vectors_tpu.ops.topk import (
    merge_shard_topk, sharded_topk, stage_shard, topk_over_store)
from dnn_page_vectors_tpu.utils import faults


class SearchService:
    def __init__(self, cfg, embedder: BulkEmbedder, corpus,
                 store: VectorStore, preload_hbm_gb: float = 4.0,
                 snippet_chars: int = 160, query_batch: Optional[int] = None,
                 log=None):
        self.cfg = cfg
        self.embedder = embedder
        self.corpus = corpus
        self.store = store
        self.snippet_chars = snippet_chars
        self.degraded = False
        self.fault_counters: Dict[str, int] = {}
        self._stream_entries: List[Dict] = []
        # Per-query encode is O(1 query), not the 512-row bulk-embed batch
        # wearing a serving hat (VERDICT r4 Weak #2): queries pad only to a
        # small compiled bucket, rounded UP to the next multiple of the mesh
        # 'data' axis so the batch always shards evenly — max(8, n_data)
        # broke the jitted _encode_query for non-dividing axes like 3/5/6
        # (ADVICE r5). warmup() measures the warm per-query latency.
        # ONE n_data for the whole service: the ["data"] spelling raised
        # KeyError on meshes without a 'data' axis.
        n_data = max(embedder.mesh.shape.get("data", 1), 1)
        self._n_data = n_data
        self.query_batch = query_batch or -(-8 // n_data) * n_data
        self.warm_latency_ms: Optional[float] = None
        self._shards = None  # [(ids np[int64], n, pages [R, D], scl|None)]
        # Budget against the ACTUAL device footprint: every shard is padded
        # to the max shard row count for one static compiled shape, so an
        # uneven store (merged multi-writer shards) costs
        # n_shards * padded_rows, which can far exceed num_vectors.
        entries = store.shards()
        rows = max((s["count"] for s in entries), default=0)
        rows += (-rows) % n_data
        self._pad_rows = rows
        # budget is PER DEVICE: shards are row-sharded over 'data', so each
        # device holds rows/n_data of every staged shard (ADVICE r4) — at
        # the STORED width (fp16 rows, or int8 codes + fp16 scale per row)
        per_row = (store.dim + 2 if store.manifest["dtype"] == "int8"
                   else store.dim * 2)
        need = len(entries) * rows * per_row / n_data
        # rows > 0: a store of only zero-count shards has nothing to stage
        # (need == 0 would pass even the explicit never-preload 0.0 budget)
        if entries and rows > 0 and need <= preload_hbm_gb * 2**30:
            self._preload(rows, budget_bytes=preload_hbm_gb * 2**30,
                          per_row=per_row)
            if not self._shards:      # nothing survived staging
                self._shards = None   # stream instead; handles empty stores
        if log is not None:
            log.write({
                "serve_degraded": self.degraded,
                "serve_hbm_shards": len(self._shards or []),
                "serve_stream_shards": len(self._stream_entries),
                "serve_vectors": store.num_vectors,
                "fault_counters": faults.counters(),
            })

    @property
    def preloaded(self) -> bool:
        return self._shards is not None

    def _count_fault(self, name: str) -> None:
        self.fault_counters[name] = self.fault_counters.get(name, 0) + 1
        faults.count(name)

    def _preload(self, rows: int, budget_bytes: float, per_row: int) -> None:
        import jax
        import jax.numpy as jnp
        from jax import lax

        plan = faults.active()
        staged = []
        used = 0.0
        per_shard = rows * per_row / self._n_data
        for entry in self.store.shards():
            if entry["count"] == 0:   # zero-count shards hold nothing to score
                continue
            try:
                plan.check("hbm_stage")
                err = self.store.entry_error(entry)
                if err is not None:
                    # corrupt bytes must never reach the device: quarantine
                    # drops the shard from the table entirely (its id-range
                    # returns on the next embed resume), and this service
                    # serves without it — degraded, visibly
                    self.store.quarantine(entry, err)
                    self._count_fault("serve_quarantined_shards")
                    self.degraded = True
                    continue
                if used + per_shard > budget_bytes:
                    raise MemoryError(
                        f"HBM budget overrun mid-stage: shard "
                        f"{entry['index']} needs {per_shard:.0f} B on top of "
                        f"{used:.0f} staged (budget {budget_bytes:.0f})")
                ids, vecs, scl = self.store._load_entry(entry, raw=True)
                staged.append((np.asarray(ids, np.int64), vecs.shape[0],
                               *stage_shard(vecs, rows, self.store.dim,
                                            self.embedder.mesh, scales=scl)))
                used += per_shard
            except Exception as e:  # noqa: BLE001 — any staging failure
                # (injected I/O fault, real device OOM, budget overrun)
                # degrades THIS shard to the streaming path; the service
                # stays up on the shards that did stage
                self._stream_entries.append(entry)
                self.degraded = True
                self._count_fault("serve_stage_faults")
                faults.warn(
                    f"HBM staging failed for shard {entry['index']} "
                    f"({type(e).__name__}: {e}); serving it via the "
                    "streaming path (degraded)")
        self._shards = staged
        if not staged:
            return
        # combined-id -> page-id table for the device-side merge below:
        # shard slot s, padded row r  ->  slot s * rows + r
        self._pid_table = np.full((len(self._shards) * rows,), -1, np.int64)
        for slot, (sids, n, _, _) in enumerate(self._shards):
            self._pid_table[slot * rows: slot * rows + n] = sids

        def merge(cands):
            # Device-side cross-shard merge, output PACKED into one fp32
            # array: per-query serving latency is dominated by host<->device
            # round trips (~100 ms each over a tunneled chip), so the k
            # winners across all resident shards must come back in a single
            # transfer — scores in [:, :k], int32 combined ids bitcast into
            # [:, k:].
            scs = [s for s, _ in cands]
            cat_s = jnp.concatenate(scs, axis=1)
            cat_i = jnp.concatenate(
                [jnp.where(i >= 0, i + slot * rows, -1)
                 for slot, (_, i) in enumerate(cands)], axis=1)
            k = scs[0].shape[1]
            top_s, pos = lax.top_k(cat_s, k)          # cat width S*k >= k
            top_i = jnp.take_along_axis(cat_i, pos, axis=1)
            top_i = jnp.where(jnp.isfinite(top_s), top_i, -1)
            # pack as INT32, scores bitcast into int bits — NOT ids into
            # float bits: small ids make denormal floats, and at least one
            # transport (the tunneled-chip backend) flushes denormals to
            # zero in float transfers, silently remapping every result to
            # page_ids[0]. Integer transfers are byte-faithful.
            return jnp.concatenate(
                [lax.bitcast_convert_type(top_s, jnp.int32), top_i], axis=1)

        self._merge = jax.jit(merge)

    def warmup(self, k: Optional[int] = None, timing_iters: int = 3) -> None:
        """Compile the encode + top-k programs before the first query, then
        time `timing_iters` warm searches (median-free mean; results are
        fully materialized to host, so the clock covers tokenize + encode +
        top-k + snippet end-to-end) into `warm_latency_ms`. Pass the SAME k
        the queries will use — the top-k program cache is keyed on it, so a
        different k would leave the real program cold."""
        self.search("warmup", k=k)
        t0 = time.perf_counter()
        for _ in range(max(1, timing_iters)):
            self.search("warmup", k=k)
        self.warm_latency_ms = ((time.perf_counter() - t0)
                                / max(1, timing_iters) * 1000.0)

    def search(self, query: str, k: Optional[int] = None) -> List[Dict]:
        import jax.numpy as jnp

        k = k or self.cfg.eval.recall_k
        if self._shards is None:
            qv = np.asarray(
                self.embedder.embed_texts([query], tower="query",
                                          batch_size=self.query_batch),
                np.float32)
            scores, ids = topk_over_store(qv, self.store,
                                          self.embedder.mesh, k=k)
            return self._format(scores[0], ids[0])
        # HBM-resident fast path: the query vector NEVER round-trips to the
        # host, every resident shard's top-k program dispatches under JAX's
        # async queue, the cross-shard merge runs ON DEVICE, and exactly ONE
        # packed array comes back — one drain round trip per query total,
        # regardless of shard count. (The old per-shard host merge cost ~2
        # transfers per shard: ~100 ms each over a tunneled chip, and a
        # forced pipeline bubble even on local PCIe.)
        tok = self.embedder.query_tok or self.embedder.page_tok
        enc = tok.encode_batch([query])
        pad = self.query_batch - enc.shape[0]
        if pad:
            enc = np.concatenate(
                [enc, np.zeros((pad,) + enc.shape[1:], enc.dtype)])
        q = self.embedder._encode_query(self.embedder.params,
                                        self.embedder._put(enc))
        cands = [
            sharded_topk(q, pages, self.embedder.mesh, k=k, valid=n,
                         scales=scl)
            for _, n, pages, scl in self._shards]
        packed = np.asarray(self._merge(cands))           # the one transfer
        top_s = np.ascontiguousarray(packed[:1, :k]).view(np.float32)[0]
        top_i = packed[0, k:]
        pids = np.where(top_i >= 0,
                        self._pid_table[np.clip(top_i, 0, None)], -1)
        if not self._stream_entries:
            return self._format(top_s, pids)
        # degraded tail: shards that failed to stage are re-read from disk
        # and folded into the resident results through the same
        # merge_shard_topk the streaming path uses — identical results,
        # per-query disk reads for exactly the failed shards
        B = self.query_batch
        best_s = np.full((B, k), -np.inf, np.float32)
        best_i = np.full((B, k), -1, np.int64)
        best_s[0] = np.where(np.isfinite(top_s), top_s, -np.inf)
        best_i[0] = pids
        qnp = jnp.asarray(np.asarray(q, np.float32))
        for entry in self._stream_entries:
            ids, vecs, scl = self.store._load_entry(entry, raw=True)
            n = vecs.shape[0]
            if n == 0:
                continue
            pages, scales = stage_shard(vecs, self._pad_rows, self.store.dim,
                                        self.embedder.mesh, scales=scl)
            best_s, best_i = merge_shard_topk(
                qnp, pages, np.asarray(ids, np.int64), n,
                self.embedder.mesh, k, best_s, best_i, scales=scales)
        return self._format(best_s[0], best_i[0])

    def _format(self, scores, ids) -> List[Dict]:
        return [
            {"page_id": int(i), "score": round(float(s), 4),
             "snippet": self.corpus.page_text(int(i))[: self.snippet_chars]}
            for s, i in zip(scores, ids) if i >= 0]
