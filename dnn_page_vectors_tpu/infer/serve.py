"""Query-time retrieval service (the serving half of call stack §4.3).

`cli.py search` originally rebuilt the corpus, tokenizer, and model per
invocation — fine as a demo, not a serving path (VERDICT r3 Weak #6).
SearchService is the serving path: everything is loaded ONCE (params on
device, store shards optionally pre-staged in HBM), so per-query cost is
one tokenize + one compiled encode + MXU top-k over resident vectors.

HBM pre-staging: when the store fits the configured budget, every shard is
device_put once (row-sharded over the mesh 'data' axis, padded to one
static shape so a single compiled top-k program serves all shards) and
queries never touch disk. Oversized stores transparently fall back to the
streaming path (ops/topk.py:topk_over_store) — same results, per-query
disk reads.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from dnn_page_vectors_tpu.infer.bulk_embed import BulkEmbedder
from dnn_page_vectors_tpu.infer.vector_store import VectorStore
from dnn_page_vectors_tpu.ops.topk import (
    merge_shard_topk, stage_shard, topk_over_store)


class SearchService:
    def __init__(self, cfg, embedder: BulkEmbedder, corpus,
                 store: VectorStore, preload_hbm_gb: float = 4.0,
                 snippet_chars: int = 160):
        self.cfg = cfg
        self.embedder = embedder
        self.corpus = corpus
        self.store = store
        self.snippet_chars = snippet_chars
        self._shards = None       # [(ids np[int64], n, pages jax [R, D])]
        # Budget against the ACTUAL device footprint: every shard is padded
        # to the max shard row count for one static compiled shape, so an
        # uneven store (merged multi-writer shards) costs
        # n_shards * padded_rows, which can far exceed num_vectors.
        entries = store.shards()
        n_data = max(embedder.mesh.shape["data"], 1)
        rows = max((s["count"] for s in entries), default=0)
        rows += (-rows) % n_data
        need = len(entries) * rows * store.dim * 4   # fp32 on device
        if entries and need <= preload_hbm_gb * 2**30:
            self._preload(rows)

    @property
    def preloaded(self) -> bool:
        return self._shards is not None

    def _preload(self, rows: int) -> None:
        self._shards = [
            (np.asarray(ids, np.int64), vecs.shape[0],
             stage_shard(vecs, rows, self.store.dim, self.embedder.mesh))
            for ids, vecs in self.store.iter_shards()]

    def warmup(self, k: Optional[int] = None) -> None:
        """Compile the encode + top-k programs before the first query.
        Pass the SAME k the queries will use — the top-k program cache is
        keyed on it, so a different k would leave the real program cold."""
        self.search("warmup", k=k)

    def search(self, query: str, k: Optional[int] = None) -> List[Dict]:
        k = k or self.cfg.eval.recall_k
        qv = np.asarray(
            self.embedder.embed_texts([query], tower="query"), np.float32)
        if self._shards is None:
            scores, ids = topk_over_store(qv, self.store,
                                          self.embedder.mesh, k=k)
        else:
            import jax.numpy as jnp
            scores = np.full((1, k), -np.inf, np.float32)
            ids = np.full((1, k), -1, np.int64)
            q = jnp.asarray(qv)
            for sids, n, pages in self._shards:
                scores, ids = merge_shard_topk(
                    q, pages, sids, n, self.embedder.mesh, k, scores, ids)
        return [
            {"page_id": int(i), "score": round(float(s), 4),
             "snippet": self.corpus.page_text(int(i))[: self.snippet_chars]}
            for s, i in zip(scores[0], ids[0]) if i >= 0]
