"""Vector store: memory-mapped fp16/int8 shards + id index (SURVEY.md §3 #20).

Layout under a directory:
  manifest.json               {"dim", "dtype", "shard_size", "shards": [...]}
  manifest.wNNNN.json         per-writer shard lists (multi-host embed)
  shard_00000.vec.npy         [n, dim] float16 L2-NORMALIZED page vectors,
                              or int8 codes when dtype == "int8"
  shard_00000.scl.npy         [n] float16 per-vector dequant scales (int8)
  shard_00000.ids.npy         [n] int64 page ids  (-1 = padding, never stored)

Vectors are stored normalized so retrieval is a pure dot product. Shards are
the resume unit: completed shards are recorded in a manifest and a restarted
job skips them (SURVEY.md §5.3 failure recovery).

Integrity (docs/ROBUSTNESS.md): each shard entry records the byte size and
CRC32 of its data files; verify() re-checks them on open and before embed
resume, quarantining (renaming aside + dropping from the shard table) any
shard whose bytes no longer match — so truncation or bit rot costs exactly
one re-embedded shard instead of silently corrupt retrieval. Torn (invalid
JSON) writer manifests are quarantined the same way. All manifest dumps and
shard writes run under the shared transient-I/O retry (utils/faults.py).

dtype "int8" (round 4): symmetric per-vector quantization — codes =
round(v / s) with s = max|v| / 127, dequantized to s * codes on read — for
~2x smaller shards and half the read bandwidth at 1B-page scale
(BASELINE.md:16). L2-normalized rows bound s to [1/(127*sqrt(D)), 1/127],
well inside fp16 range, and the per-element error <= s/2 ~= 0.004 shifts
cosine scores by far less than typical inter-page score gaps (recall
parity is test-pinned, tests/test_store_quant.py).

Multi-writer protocol (SURVEY.md §4.2 "each host reads its file shards";
VERDICT r3 Missing #1): concurrent processes must never read-modify-write
one manifest, so each writer appends to its OWN `manifest.wNNNN.json` —
atomic via tmp+rename, no cross-process locking anywhere. Readers see the
union of the main manifest and every writer manifest (`shards()`), which
makes an explicit merge unnecessary for correctness; `merge_writers()`
(process 0, after a barrier) folds writer files into the main manifest so a
finished store is a single self-describing file again.

Generations (docs/UPDATES.md): the base embed is generation 0; live corpus
updates land as append-only generations under `<store>/gen-NNNN/`, each a
directory of ordinary shard files plus its OWN `manifest.json` (same
bytes+CRC32+model-step machinery) recording the appended shard entries,
the id range they cover, and the page ids TOMBSTONED at that generation
(deleted pages, or pages re-embedded into this generation). The chain must
be contiguous 1..G and stamped at the base store's model step; a torn or
broken-chain generation manifest is quarantined and that generation plus
everything after it drops out of the merged view — readers keep serving
the longest intact prefix. Tombstones are applied at READ time:
`_load_entry` maps a page id to -1 when a LATER generation tombstoned it,
and every retrieval path (exact merge, HBM serving, IVF gather) already
treats id -1 as a dead slot — so stale vectors are masked without
rewriting a single committed byte. Writes go through `begin_generation()`
(the GenerationWriter protocol below); `missing_id_ranges()` exposes the
id-ranges lost to shard quarantines so appends never re-assign them.
"""
from __future__ import annotations

import glob
import json
import os
import queue as queue_mod
import threading
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from dnn_page_vectors_tpu.utils import faults


def read_ahead(it, depth: int = 1):
    """Depth-bounded background reader: drain `it` (a shard-loading
    iterator) on a reader thread so the NEXT shard's disk read overlaps the
    consumer's device work on the current one — the streaming top-k sweep
    (ops/topk.py:topk_over_store) and the degraded-tail serving loop
    (infer/serve.py) otherwise read each shard synchronously between device
    dispatches. Mirrors the bulk-embed writer contract (infer/bulk_embed.py
    _ShardWriter): bounded queue (a slow consumer backpressures the reader,
    host memory stays O(depth) pending shards) and join-and-reraise — the
    reader's first exception surfaces at the consumer AS ITSELF, so an
    `except IOError` around the sweep matches exactly as it did serially.
    """
    q: "queue_mod.Queue[object]" = queue_mod.Queue(maxsize=max(1, depth))
    done = object()
    stop = threading.Event()
    err: List[BaseException] = []

    def _put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue_mod.Full:
                continue
        return False

    def _read():
        try:
            for item in it:
                if not _put(item):
                    return
        except BaseException as e:  # noqa: BLE001 — re-raised consumer-side
            err.append(e)
        finally:
            _put(done)

    t = threading.Thread(target=_read, daemon=True, name="shard-reader")
    t.start()
    try:
        while True:
            item = q.get()
            if item is done:
                break
            yield item
    finally:
        # abandoning consumer (early break / error): unblock the reader
        stop.set()
        t.join()
        if err:
            raise err[0]


def crc_file(path: str) -> int:
    """Streaming CRC32 of a file's bytes (header included — a torn npy
    header is corruption too). Shared with the IVF ANN index
    (index/ivf.py), which persists its centroids + posting lists in an
    `ivf/` subdirectory of the store under the same bytes+CRC32+
    model-step-stamp manifest machinery: an `ensure_model_step` re-stamp
    or any shard-table change invalidates the index structurally (its
    recorded stamp/shard table no longer matches), and corrupt index
    files are quarantined the same way shards are."""
    crc = 0
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            crc = zlib.crc32(chunk, crc)
    return crc & 0xFFFFFFFF


_crc_file = crc_file        # internal alias (pre-index spelling)


def prepare_store(directory: str, dim: int, shard_size: Optional[int],
                  dtype: Optional[str], model_step: int) -> "VectorStore":
    """Open/create the store stamped for `model_step` with the given
    geometry. A stale store (older model_step) whose shard_size/dtype ALSO
    changed must not trip the populated-store geometry guard before its
    stale shards are dropped (ADVICE r4): open WITHOUT geometry first,
    reset if stale, then apply the overrides to the now-empty store.
    Shared by the CLI (init-store / single-writer embed) and the pipeline."""
    if os.path.exists(os.path.join(os.path.abspath(directory),
                                   "manifest.json")):
        try:
            plain = VectorStore(directory)
            if plain.manifest.get("model_step") != model_step:
                plain.reset()
        except ValueError:
            # torn main manifest: __init__ already quarantined it, and this
            # caller holds a creation intent — fall through to the fresh
            # open below (the unstamped store resets + re-embeds)
            pass
    store = VectorStore(directory, dim=dim, shard_size=shard_size,
                        dtype=dtype)
    store.ensure_model_step(model_step)
    return store


class VectorStore:
    def __init__(self, directory: str, dim: int | None = None,
                 shard_size: Optional[int] = None,
                 writer_id: Optional[int] = None,
                 dtype: Optional[str] = None, verify: bool = True):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._manifest_path = os.path.join(self.directory, "manifest.json")
        self.writer_id = writer_id
        self._writer_path = (
            None if writer_id is None else
            os.path.join(self.directory, f"manifest.w{int(writer_id):04d}.json"))
        if dtype not in (None, "float16", "int8"):
            raise ValueError(f"unsupported store dtype {dtype!r} "
                             "(want float16 or int8)")
        existed = os.path.exists(self._manifest_path)
        if existed:
            try:
                with open(self._manifest_path) as f:
                    self.manifest = json.load(f)
            except (json.JSONDecodeError, ValueError):
                # torn MAIN manifest (crash before this code fsynced renames,
                # or external damage): the shard files may be fine but their
                # record is gone. Quarantine the torn file; with a creation
                # intent (dim given) start a fresh manifest — the unstamped
                # store will be reset+re-embedded by ensure_model_step —
                # else surface a clear error instead of a JSON traceback.
                q = self._manifest_path + ".quarantined"
                os.replace(self._manifest_path, q)
                faults.count("quarantined_manifests")
                faults.warn(f"store manifest {self._manifest_path} is torn "
                            f"(invalid JSON); moved aside to {q}")
                if dim is None:
                    raise ValueError(
                        f"vector store manifest at {self.directory} is "
                        f"corrupt (quarantined to {q}); re-run 'init-store' "
                        "+ 'embed' to rebuild, or restore the manifest")
                existed = False
        if existed:
            if dim is not None and dim != self.manifest["dim"]:
                raise ValueError(
                    f"store at {self.directory} holds {self.manifest['dim']}-d "
                    f"vectors but dim={dim} was requested; use a fresh "
                    "directory (or reset()) when the model out_dim changes")
        else:
            if dim is None:
                raise FileNotFoundError(
                    f"no vector store at {self.directory} (missing "
                    "manifest.json) — run the 'embed' job first, or pass "
                    "dim= to create a new store")
            self.manifest = {"dim": dim, "dtype": dtype or "float16",
                             "shard_size": shard_size or 65_536,
                             "shards": []}
            self._flush_manifest()
        # resume: this writer's previously recorded shards
        self._writer_shards: List[Dict] = []
        if self._writer_path and os.path.exists(self._writer_path):
            data = self._read_writer(self._writer_path)
            self._writer_shards = [] if data is None else data.get("shards", [])
        # append-only generations (docs/UPDATES.md): the longest intact
        # gen-0001..gen-NNNN manifest chain, plus the tombstone map
        self._generations: List[Dict] = []
        self._tomb_gen: Dict[int, int] = {}   # page id -> gen that killed it
        self._dead_cache: Dict[int, np.ndarray] = {}
        self._load_generations()
        # integrity gate (docs/ROBUSTNESS.md): recorded checksums/sizes are
        # re-verified against the bytes on disk; corrupt or truncated shards
        # are quarantined so resume re-embeds exactly those id-ranges
        if existed and verify:
            self.verify()
        # an EMPTY store may adopt a new shard size / dtype (a populated one
        # cannot: shard files on disk already have the recorded geometry)
        for key, want in (("shard_size", shard_size), ("dtype", dtype)):
            if want is not None and want != self.manifest[key]:
                if self.shards():
                    raise ValueError(
                        f"store at {self.directory} was built with "
                        f"{key}={self.manifest[key]!r} and holds shards; "
                        f"cannot switch to {want!r} (reset() first)")
                self.manifest[key] = want
                self._flush_manifest()

    @property
    def dim(self) -> int:
        return self.manifest["dim"]

    @property
    def num_vectors(self) -> int:
        return sum(s["count"] for s in self.shards())

    @property
    def row_bytes(self) -> int:
        """Bytes one row costs to gather at STORED width (int8 codes +
        fp16 scale, or fp16 rows) — the per-shard HBM staging unit
        (infer/serve.py) and the payload-accounting unit behind the ANN
        gather metrics (`ann_gather_bytes`, docs/ANN.md)."""
        return (self.dim + 2 if self.manifest["dtype"] == "int8"
                else self.dim * 2)

    @property
    def model_step(self) -> Optional[int]:
        """The model step this store's vectors were embedded at (None for a
        pre-stamp store). Serving keys its query-embedding cache on this, so
        ensure_model_step / a store reload invalidates cached embeddings.
        During a rolling migration (docs/MAINTENANCE.md "Rolling model
        migration") this stays the FROM stamp until the completion flip —
        per-shard stamps are read through entry_step()."""
        return self.manifest.get("model_step")

    # -- rolling model migration (docs/MAINTENANCE.md) ---------------------
    @property
    def migration(self) -> Optional[Dict]:
        """The active rolling-migration record ({"from_step", "to_step"}),
        or None. While present, shards legitimately carry EITHER stamp —
        one stamp per shard, never mixed within one — and serving routes
        queries per shard by entry_step()."""
        return self.manifest.get("migration")

    @property
    def migration_epoch(self) -> int:
        """Monotonic count of migration manifest flips this store has ever
        committed. Folded into `generation`, so every migrate swap moves
        the SAME number the refresh broadcast, the worker eligibility
        gate, and the result-cache key already gate on — the
        no-mixed-generations machinery extends to stamp flips for free."""
        return int(self.manifest.get("migration_epoch", 0))

    def entry_step(self, entry: Dict) -> Optional[int]:
        """The model stamp one shard entry's vectors were embedded at: the
        entry's own recorded stamp (migrated base shards, annotated
        generation shards), falling back to the store stamp."""
        return entry.get("model_step", self.manifest.get("model_step"))

    def model_steps(self) -> List[int]:
        """Distinct model stamps across the live shard table, ascending.
        One element outside a migration window; two while a rolling
        migration is mid-sweep."""
        return sorted({s for s in (self.entry_step(e) for e in self.shards())
                       if s is not None})

    def _writer_files(self) -> List[str]:
        return sorted(p for p in glob.glob(
            os.path.join(self.directory, "manifest.w*.json"))
            if not p.endswith(".quarantined"))

    def _read_writer(self, path: str) -> Optional[Dict]:
        """Load one writer manifest; a TORN one (invalid JSON — crash while
        an old non-atomic writer held it, or external damage) is moved
        aside and reported as absent: its recorded shards fall out of the
        merged table and resume re-embeds them, instead of every reader
        dying on a JSON traceback."""
        try:
            with open(path) as f:
                return json.load(f)
        except FileNotFoundError:       # merged away between glob and open
            return None
        except (json.JSONDecodeError, ValueError):
            q = path + ".quarantined"
            try:
                os.replace(path, q)
            except FileNotFoundError:
                return None
            faults.count("quarantined_manifests")
            faults.warn(f"writer manifest {path} is torn (invalid JSON); "
                        f"moved aside to {q}; its shards will be re-embedded")
            return None

    def shards(self) -> List[Dict]:
        """Merged shard table: the main manifest plus every writer manifest
        currently on disk (so readers and resumed writers see other
        processes' completed work without any merge step) plus every intact
        generation's appended shards (docs/UPDATES.md)."""
        by_idx = {s["index"]: s for s in self.manifest["shards"]}
        for path in self._writer_files():
            data = self._read_writer(path)
            if data is None:
                continue
            for s in data.get("shards", []):
                by_idx[s["index"]] = s
        for gen in self._generations:
            for s in gen.get("shards", []):
                by_idx[s["index"]] = s
        return [by_idx[i] for i in sorted(by_idx)]

    def completed_shards(self) -> set:
        return {s["index"] for s in self.shards()}

    def reload(self) -> None:
        """Re-read the main manifest from disk (after another process merged
        or stamped it)."""
        with open(self._manifest_path) as f:
            self.manifest = json.load(f)

    def _atomic_dump(self, obj, path: str, op: str = "manifest") -> None:
        plan = faults.active()

        def _dump():
            plan.check(f"{op}_dump")
            tmp = path + f".tmp.{os.getpid()}"  # per-process: no shared tmp
            with open(tmp, "w") as f:
                json.dump(obj, f, indent=1, sort_keys=True)
                f.flush()
                os.fsync(f.fileno())   # durable before the atomic rename
            plan.corrupt(f"{op}_file", tmp)
            os.replace(tmp, path)  # atomic: crash-safe resume
            # the RENAME itself must survive a crash too: without a
            # directory fsync the dir entry can be lost and a recorded
            # manifest come back empty/old after power loss
            self._fsync_dir(os.path.dirname(path))

        faults.retry(_dump, op=f"{op}_dump")

    @staticmethod
    def _fsync_file(path: str) -> None:
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)

    @staticmethod
    def _fsync_dir(path: str) -> None:
        try:
            fd = os.open(path, os.O_RDONLY)
        except OSError:      # platforms without O_RDONLY dir opens: best effort
            return
        try:
            os.fsync(fd)
        except OSError:
            pass
        finally:
            os.close(fd)

    def _flush_manifest(self) -> None:
        self._atomic_dump(self.manifest, self._manifest_path)

    def ensure_model_step(self, step: int) -> None:
        """Stale-store invariant (one call site per topology, decided ONCE
        before any writer starts): vectors embedded at another model step
        are stale, not resumable work — reset, then stamp the new step.
        EXCEPT mid-migration: a rolling migration owns the stamp lifecycle
        (docs/MAINTENANCE.md), so asking for either endpoint of an active
        migration is a no-op instead of a store wipe."""
        mig = self.manifest.get("migration")
        if mig and step in (mig.get("from_step"), mig.get("to_step")):
            return
        if self.manifest.get("model_step") != step:
            self.reset()
        self.manifest["model_step"] = step
        self._flush_manifest()

    def merge_writers(self) -> None:
        """Fold every writer manifest into the main one and remove them.
        Call from ONE process after all writers finished (barrier first)."""
        files = self._writer_files()
        merged = {s["index"]: s for s in self.manifest["shards"]}
        for path in files:
            data = self._read_writer(path)
            for s in (data or {}).get("shards", []):
                merged[s["index"]] = s
        files = [p for p in files if os.path.exists(p)]  # minus quarantined
        self.manifest["shards"] = [merged[i] for i in sorted(merged)]
        self._flush_manifest()
        for path in files:
            os.remove(path)

    # -- generations (docs/UPDATES.md) -------------------------------------
    def _gen_path(self, gen: int) -> str:
        return os.path.join(self.directory, f"gen-{int(gen):04d}")

    def _load_generations(self) -> None:
        """Load the longest intact generation chain gen-NNNN starting one
        past the compaction epoch (docs/MAINTENANCE.md: a compacted base
        FOLDS generations 1..compacted_through, so the live chain resumes
        after them — generation numbers stay monotonic forever).
        The chain stops at the first missing/torn/stale manifest: a torn
        one is quarantined (renamed aside, counted), and everything AFTER
        the break is unreachable by construction — later generations were
        appended against a view that included the broken one, so readers
        keep serving the longest intact prefix instead of a gapped chain."""
        self._generations = []
        self._tomb_gen = {}
        self._dead_cache = {}
        step = self.manifest.get("model_step")
        # mid-migration, generations legitimately sit at EITHER endpoint
        # stamp — both are intact chain members, not stale strays; a gen
        # whose shards were re-embedded carries its migrated entries as a
        # main-manifest override instead (docs/MAINTENANCE.md)
        mig = self.manifest.get("migration") or {}
        ok_steps = {step, mig.get("from_step"), mig.get("to_step")} \
            if mig else {step}
        g = int(self.manifest.get("compacted_through", 0)) + 1
        while True:
            mpath = os.path.join(self._gen_path(g), "manifest.json")
            if not os.path.exists(mpath):
                break
            try:
                with open(mpath) as f:
                    man = json.load(f)
            except (json.JSONDecodeError, ValueError):
                q = mpath + ".quarantined"
                os.replace(mpath, q)
                faults.count("quarantined_generations")
                faults.warn(
                    f"generation manifest {mpath} is torn (invalid JSON); "
                    f"moved aside to {q}; serving the store without "
                    f"generation {g} and anything after it")
                break
            if man.get("gen") != g or (man.get("model_step") not in ok_steps
                                       and self._gen_override(g, man)
                                       is None):
                faults.count("stale_generations")
                faults.warn(
                    f"generation {g} at {mpath} is stale (gen="
                    f"{man.get('gen')}, model_step={man.get('model_step')} "
                    f"vs store {step}); serving without it")
                break
            self._register_generation(man)
            g += 1

    def _gen_override(self, g: int, man: Dict) -> Optional[List[Dict]]:
        """Migrated replacement entries for generation `g`, or None. They
        live in the MAIN manifest (docs/MAINTENANCE.md "Rolling model
        migration") so each per-generation migration commit is ONE atomic
        dump — no two-manifest crash window. Applied only while the
        recorded source CRCs still match the generation manifest on disk:
        a quarantined-and-reused generation number can never resurrect a
        stale override."""
        ov = (self.manifest.get("gen_overrides") or {}).get(str(int(g)))
        if not ov:
            return None
        src = [e.get("crc", {}).get("vec") for e in man.get("shards", [])]
        if src != ov.get("src_vec_crc"):
            return None
        return ov.get("shards")

    def _register_generation(self, man: Dict) -> None:
        ov = self._gen_override(int(man["gen"]), man)
        if ov is not None:
            # the effective view of a migrated generation: its re-embedded
            # entries (and their stamp) supersede the manifest's own
            man = dict(man)
            man["shards"] = [dict(e) for e in ov]
            steps = {e.get("model_step") for e in man["shards"]}
            if len(steps) == 1 and None not in steps:
                man["model_step"] = steps.pop()
        # annotate each entry with its owning manifest's stamp so the
        # merged shards() table is stamp-addressable without re-resolving
        # ownership (entry_step; docs/MAINTENANCE.md "Rolling model
        # migration")
        for s in man.get("shards", []):
            s.setdefault("model_step", man.get("model_step"))
        self._generations.append(man)
        g = int(man["gen"])
        for t in man.get("tombstones", []):
            self._tomb_gen[int(t)] = max(self._tomb_gen.get(int(t), 0), g)
        self._dead_cache = {}

    def reload_generations(self) -> None:
        """Pick up generations appended (or quarantined) by another process
        since this store was opened — the serving hot-swap entry point
        (infer/serve.py refresh)."""
        self._load_generations()

    @property
    def generation(self) -> int:
        """Current store generation (0 = base embed only). Monotonic across
        compactions: folded generations still count, so the next append
        always chains past every generation number ever committed.
        Migration manifest flips fold in through migration_epoch, so a
        stamp flip bumps the generation every reader/peer gates on even
        though no generation was appended."""
        return (int(self.manifest.get("compacted_through", 0))
                + len(self._generations)
                + int(self.manifest.get("migration_epoch", 0)))

    @property
    def chain_generation(self) -> int:
        """Top generation NUMBER in the append chain (compacted_through +
        intact generations) — the gen-NNNN numbering cursor. Unlike
        `generation` this excludes migration_epoch: migrate flips move what
        readers gate on, not where the next gen-NNNN directory lands."""
        return (int(self.manifest.get("compacted_through", 0))
                + len(self._generations))

    def generations(self) -> List[Dict]:
        """The intact generation manifests, in chain order."""
        return list(self._generations)

    def tombstoned_count(self) -> int:
        """Number of page ids with an active tombstone."""
        return len(self._tomb_gen)

    def appended_vectors(self) -> int:
        """Rows appended by generations > 0 (tombstoned rows included)."""
        return sum(s["count"] for g in self._generations
                   for s in g.get("shards", []))

    @property
    def compacted_through(self) -> int:
        """Highest generation folded into a compacted base (0 = never
        compacted; docs/MAINTENANCE.md)."""
        return int(self.manifest.get("compacted_through", 0))

    def maintenance_stats(self) -> Dict:
        """The compaction trigger's inputs (docs/MAINTENANCE.md): tombstone
        density across the live generation chain, dead rows still occupying
        store bytes, and the bytes a compaction would reclaim. Every
        tombstoned id masks exactly one stored row (append_corpus only
        accepts already-assigned ids, and an update's old row dies when the
        new one lands), so dead-row accounting is O(tombstone map) — no id
        files are re-read here."""
        dead = len(self._tomb_gen)
        total = self.num_vectors
        # one dead row costs its stored-width bytes plus the 8-byte id slot
        # (row_bytes already includes the int8 scale when applicable)
        return {
            "tombstone_density": round(dead / max(total, 1), 4),
            "dead_rows": dead,
            "reclaimable_bytes": dead * (self.row_bytes + 8),
            "generations": len(self._generations),
            "compacted_through": self.compacted_through,
        }

    # -- per-row attributes (index/attrs.py, docs/ANN.md) ------------------
    @property
    def attrs_enabled(self) -> bool:
        """Whether this store carries a per-row attribute table. Decided at
        init_attrs() time and recorded in the manifest (bit-field layout
        version included); shards written before the flag flipped simply
        have no `.atr.npy` file and read back as all-zero words."""
        return "attrs" in self.manifest

    def init_attrs(self) -> None:
        """Initialize (or validate) the store's attribute table: record the
        versioned bit-field layout in the manifest so every subsequent
        shard write — base embed, appends, compaction, migration — carries
        one packed uint32 attribute word per row through the same
        bytes+CRC32 integrity machinery as the vectors themselves."""
        from dnn_page_vectors_tpu.index import attrs as attrs_mod
        if self.attrs_enabled:
            attrs_mod.check_attrs_section(self.manifest["attrs"])
            return
        self.manifest["attrs"] = attrs_mod.attrs_manifest_section()
        self._flush_manifest()

    def load_attrs(self, entry: Dict) -> np.ndarray:
        """One shard's packed attribute words (uint32 [count]). Shards
        written before init_attrs() (no `.atr.npy`) read as all-zero words
        — a well-defined attribute value, so predicates stay total."""
        if "atr" not in entry:
            return np.zeros(int(entry["count"]), np.uint32)
        faults.active().check("shard_read")
        return np.ascontiguousarray(
            np.load(os.path.join(self.directory, entry["atr"])), np.uint32)

    # -- ANN index directory pointer (docs/MAINTENANCE.md) -----------------
    @property
    def index_dirname(self) -> str:
        """Directory (relative to the store root) holding the LIVE ANN
        index. "ivf" by default; a background rebuild builds the next index
        generation into a sibling dir (ivf-NNNN) and flips this pointer
        with one atomic manifest dump, so readers move between index
        generations without ever observing a half-written one."""
        return self.manifest.get("index_dir", "ivf")

    def set_index_dir(self, name: str) -> None:
        """Atomically repoint the live index directory (the background
        rebuild's hot-swap: build beside, flip last)."""
        self.manifest["index_dir"] = str(name)
        self._atomic_dump(self.manifest, self._manifest_path,
                          op="index_swap")

    def _dead_for_gen(self, gen: int) -> np.ndarray:
        """Sorted page ids tombstoned by a generation LATER than `gen` —
        the mask set for a shard written at `gen` (a tombstone never masks
        the generation that wrote it, or an updated page would kill its own
        replacement row)."""
        arr = self._dead_cache.get(gen)
        if arr is None:
            arr = np.array(sorted(i for i, tg in self._tomb_gen.items()
                                  if tg > gen), np.int64)
            self._dead_cache[gen] = arr
        return arr

    def _mask_dead(self, ids: np.ndarray, gen: int) -> np.ndarray:
        if not self._tomb_gen:
            return ids
        dead = self._dead_for_gen(int(gen))
        if not dead.size:
            return ids
        return np.where(np.isin(ids, dead), np.int64(-1), ids)

    def _next_shard_index(self) -> int:
        """One past the highest shard index EVER assigned — live entries,
        quarantined base ranges, and prior generations' high-water marks —
        so a new generation never collides with a quarantined shard's index
        (its id-range returns on the next embed resume)."""
        hi = max((s["index"] + 1 for s in self.shards()), default=0)
        ss = self.manifest["shard_size"]
        for lo, _ in self.manifest.get("missing_id_ranges", []):
            hi = max(hi, lo // ss + 1)
        for g in self._generations:
            hi = max(hi, int(g.get("max_index", -1)) + 1)
        return hi

    def next_page_id(self) -> int:
        """High-water mark: one past the highest page id ever assigned,
        counting live shards, quarantined (missing) id-ranges, and every
        generation's recorded id_end — the append cursor. A quarantined
        shard plus a later append must never double-assign ids
        (docs/UPDATES.md): the quarantined range is re-embedded by resume,
        not re-issued to new documents."""
        hi = int(self.manifest.get("append_cursor", 0))
        ss = self.manifest["shard_size"]
        for s in self.shards():
            if s.get("gen", 0):
                hi = max(hi, int(s.get("id_hi", 0)))
            else:
                hi = max(hi, s["index"] * ss + s["count"])
        for _, rhi in self.manifest.get("missing_id_ranges", []):
            hi = max(hi, int(rhi))
        for g in self._generations:
            hi = max(hi, int(g.get("id_end", 0)))
        return hi

    def missing_id_ranges(self) -> List[Tuple[int, int]]:
        """Id-ranges dropped by shard quarantines and not yet re-covered by
        a live shard: [lo, hi) pairs, recorded at quarantine time and
        cleared when a re-embed (write_shard) or a repair append re-covers
        them. Embed resume re-embeds exactly these; append_corpus treats
        them as assigned (next_page_id) so new docs never reuse them."""
        return [(int(lo), int(hi)) for lo, hi
                in self.manifest.get("missing_id_ranges", [])]

    def _record_missing_range(self, lo: int, hi: int) -> None:
        if hi <= lo:
            return
        ranges = {(int(a), int(b))
                  for a, b in self.manifest.get("missing_id_ranges", [])}
        ranges.add((int(lo), int(hi)))
        self.manifest["missing_id_ranges"] = sorted(ranges)
        self._flush_manifest()

    def _clear_missing_ranges(self, covered) -> None:
        """Drop recorded missing ranges fully inside `covered(lo, hi)`."""
        ranges = self.manifest.get("missing_id_ranges", [])
        kept = [r for r in ranges if not covered(int(r[0]), int(r[1]))]
        if len(kept) != len(ranges):
            self.manifest["missing_id_ranges"] = kept
            self._flush_manifest()

    def begin_generation(self, tombstones=()) -> "GenerationWriter":
        """Open the next generation for appending. Shards written through
        the returned writer land under gen-NNNN/ and become visible ONLY
        when commit() atomically writes the generation manifest — a crash
        or torn manifest costs exactly this generation, never the chain
        before it. `tombstones` are the page ids this generation kills in
        EARLIER generations (deleted pages, or pages about to be
        re-appended with fresh vectors)."""
        return GenerationWriter(self, self.chain_generation + 1,
                                tombstones=tombstones)

    def reset(self) -> None:
        """Drop all shards (e.g. the model changed and vectors are stale),
        including any written under writer manifests and every appended
        generation."""
        import shutil
        for s in self.shards():
            for key in ("vec", "ids", "scl", "atr"):
                try:
                    os.remove(os.path.join(self.directory, s[key]))
                except (FileNotFoundError, KeyError):
                    pass
        for path in self._writer_files():
            os.remove(path)
        for pat in ("gen-*", "compact-*", "migrate-*"):
            for path in glob.glob(os.path.join(self.directory, pat)):
                if os.path.isdir(path):
                    shutil.rmtree(path, ignore_errors=True)
        self._generations = []
        self._tomb_gen = {}
        self._dead_cache = {}
        self.manifest["shards"] = []
        self.manifest.pop("missing_id_ranges", None)
        self.manifest.pop("compacted_through", None)
        self.manifest.pop("append_cursor", None)
        # a reset abandons any mid-sweep migration wholesale; the epoch
        # counter stays (monotonic forever — generation-keyed consumers
        # must never see it move backward)
        self.manifest.pop("migration", None)
        self.manifest.pop("gen_overrides", None)
        self._writer_shards = []
        self._flush_manifest()

    # -- integrity (docs/ROBUSTNESS.md) ------------------------------------
    def entry_error(self, entry: Dict) -> Optional[str]:
        """Why this shard entry cannot be trusted, or None. Cheap checks
        first (existence, recorded byte size — catches truncation with one
        stat) then the CRC32 re-read. Entries from stores predating the
        integrity record (no "crc" key) pass, as they always did."""
        for key in ("vec", "ids", "scl", "atr"):
            if key not in entry:
                continue
            path = os.path.join(self.directory, entry[key])
            if not os.path.exists(path):
                return f"{key} file {entry[key]} missing"
            want_bytes = entry.get("bytes", {}).get(key)
            if want_bytes is not None:
                size = os.path.getsize(path)
                if size != want_bytes:
                    return (f"{key} file {entry[key]} is {size} bytes, "
                            f"manifest records {want_bytes} (truncated?)")
            want_crc = entry.get("crc", {}).get(key)
            if want_crc is not None:
                got = _crc_file(path)
                if got != want_crc:
                    return (f"{key} file {entry[key]} CRC {got:#010x} != "
                            f"recorded {want_crc:#010x} (corrupt)")
        return None

    def quarantine(self, entry: Dict, reason: str) -> None:
        """Move a corrupt/truncated shard's files aside (.quarantined — kept
        for forensics, invisible to readers) and drop its entry from
        whichever manifest holds it. The shard index disappears from
        completed_shards(), so the next embed_corpus resume re-embeds
        exactly this id-range."""
        idx = entry["index"]
        for key in ("vec", "ids", "scl", "atr"):
            if key in entry:
                src = os.path.join(self.directory, entry[key])
                try:
                    os.replace(src, src + ".quarantined")
                except FileNotFoundError:
                    pass
        # the dropped id-range stays DISCOVERABLE (missing_id_ranges): embed
        # resume re-embeds it, and the append cursor (next_page_id) treats
        # it as assigned so later appends never double-assign its ids
        gen = int(entry.get("gen", 0))
        if gen:
            lo, hi = int(entry.get("id_lo", 0)), int(entry.get("id_hi", 0))
            for g in self._generations:
                if g["gen"] != gen:
                    continue
                shards = g.get("shards", [])
                kept = [s for s in shards if s["index"] != idx]
                if len(kept) != len(shards):
                    g["shards"] = kept
                    self._atomic_dump(
                        g, os.path.join(self._gen_path(gen),
                                        "manifest.json"),
                        op="gen_manifest")
        else:
            ss = self.manifest["shard_size"]
            lo, hi = idx * ss, idx * ss + int(entry["count"])
        if any(s["index"] == idx for s in self.manifest["shards"]):
            self.manifest["shards"] = [
                s for s in self.manifest["shards"] if s["index"] != idx]
            self._flush_manifest()
        self._record_missing_range(lo, hi)
        for path in self._writer_files():
            data = self._read_writer(path)
            if data is None:
                continue
            shards = data.get("shards", [])
            kept = [s for s in shards if s["index"] != idx]
            if len(kept) != len(shards):
                self._atomic_dump({"shards": kept}, path)
        self._writer_shards = [
            s for s in self._writer_shards if s["index"] != idx]
        faults.count("quarantined_shards")
        faults.warn(f"quarantined store shard {idx} ({reason}); its id-range "
                    "will be re-embedded on the next embed resume")

    def verify(self) -> List[int]:
        """Re-check every recorded shard against its recorded sizes/CRCs,
        quarantining the ones that fail. Returns the quarantined indices.
        Runs on every open (VectorStore(..., verify=False) to skip) and
        before embed resume."""
        bad = []
        for entry in self.shards():
            err = self.entry_error(entry)
            if err is not None:
                self.quarantine(entry, err)
                bad.append(entry["index"])
        return bad

    # -- write ------------------------------------------------------------
    def write_shard(self, index: int, ids: np.ndarray,
                    vecs: Optional[np.ndarray] = None, *,
                    codes: Optional[np.ndarray] = None,
                    scales: Optional[np.ndarray] = None,
                    attrs: Optional[np.ndarray] = None) -> None:
        """Persist one shard. Either `vecs` (float rows; quantized here when
        the store is int8) or, for int8 stores, pre-quantized
        `codes`+`scales` straight off the device (bulk_embed's on-device
        quantize — same math as below, run before the D2H wire so the job
        moves 1 B/dim instead of 2).

        Durability order (the resume invariant bulk_embed's background
        writer leans on): data files are written AND fsynced first, the
        manifest entry lands last (itself fsync+atomic-rename) — so a crash
        at any point either leaves the shard unrecorded (re-embedded on
        resume) or recorded with all its bytes on disk; never recorded
        without them."""
        entry = self._write_shard_files("", index, ids, vecs, codes, scales,
                                        attrs=attrs)
        if self._writer_path is not None:
            self._writer_shards = (
                [s for s in self._writer_shards if s["index"] != index]
                + [entry])
            self._writer_shards.sort(key=lambda s: s["index"])
            self._atomic_dump({"shards": self._writer_shards},
                              self._writer_path)
            return
        self.manifest["shards"] = (
            [s for s in self.manifest["shards"] if s["index"] != index]
            + [entry])
        self.manifest["shards"].sort(key=lambda s: s["index"])
        # a re-embedded shard re-covers its quarantined id-range
        ss = self.manifest["shard_size"]
        lo, hi = index * ss, index * ss + entry["count"]
        ranges = self.manifest.get("missing_id_ranges", [])
        kept = [r for r in ranges
                if not (lo <= int(r[0]) and int(r[1]) <= max(hi, lo + ss))]
        if len(kept) != len(ranges):
            self.manifest["missing_id_ranges"] = kept
        self._flush_manifest()

    def _write_shard_files(self, subdir: str, index: int, ids: np.ndarray,
                           vecs, codes, scales, attrs=None) -> Dict:
        """Durably write one shard's data files (under `subdir` relative to
        the store root; "" = the base layout) and return its manifest entry
        with byte sizes + CRC32s recorded — the shared core of base
        write_shard and GenerationWriter appends. On an attrs-enabled store
        (init_attrs) every shard also lands a `.atr.npy` of packed uint32
        attribute words — `attrs` aligned with `ids` pre-padding, zeros
        when the producer has none — under the same CRC record."""
        data = vecs if codes is None else codes
        if data.shape[-1] != self.dim:
            raise ValueError(f"vectors are {data.shape[-1]}-d, store is "
                             f"{self.dim}-d")
        if codes is not None and self.manifest["dtype"] != "int8":
            raise ValueError("pre-quantized codes require an int8 store")
        keep = ids >= 0  # drop batch padding rows
        ids = ids[keep]
        if attrs is not None and not self.attrs_enabled:
            raise ValueError("attrs given but the store has no attribute "
                             "table; call init_attrs() first")
        if self.attrs_enabled:
            attr_words = (np.zeros(keep.shape[0], np.uint32) if attrs is None
                          else np.asarray(attrs, np.uint32))
            if attr_words.shape[0] != keep.shape[0]:
                raise ValueError(
                    f"attrs has {attr_words.shape[0]} rows, ids has "
                    f"{keep.shape[0]}")
            attr_words = attr_words[keep]
        else:
            attr_words = None
        d = os.path.join(self.directory, subdir) if subdir else self.directory
        vpath = os.path.join(d, f"shard_{index:05d}.vec.npy")
        ipath = os.path.join(d, f"shard_{index:05d}.ids.npy")
        spath = os.path.join(d, f"shard_{index:05d}.scl.npy")
        apath = os.path.join(d, f"shard_{index:05d}.atr.npy")
        rel = (lambda p: os.path.join(subdir, os.path.basename(p))
               if subdir else os.path.basename(p))
        entry = {"index": index, "count": int(ids.shape[0]),
                 "vec": rel(vpath), "ids": rel(ipath)}
        plan = faults.active()

        def _write_files():
            plan.check("shard_write")
            if codes is not None:
                np.save(vpath, np.asarray(codes[keep], np.int8))
                np.save(spath, np.asarray(scales[keep], np.float16))
                entry["scl"] = rel(spath)
            elif self.manifest["dtype"] == "int8":
                v = np.asarray(vecs[keep], np.float32)
                scale = np.abs(v).max(axis=-1) / 127.0 if v.size else \
                    np.zeros((0,), np.float32)
                # quantize with the SAME fp16-rounded scale the reader will
                # dequantize with, so |err| <= scale/2 holds exactly; the
                # floor must survive the fp16 round-trip (>= smallest fp16
                # normal), or an all-zero row would divide by
                # fp16-underflowed 0
                floor = np.float32(np.float16(6.2e-5))  # exact fp16 value
                safe = np.maximum(
                    scale.astype(np.float16).astype(np.float32), floor)
                q = np.clip(np.rint(v / safe[:, None]), -127, 127)
                np.save(vpath, q.astype(np.int8))
                np.save(spath, safe.astype(np.float16))
                entry["scl"] = rel(spath)
            else:
                np.save(vpath, vecs[keep].astype(np.float16))
            np.save(ipath, ids.astype(np.int64))
            if attr_words is not None:
                np.save(apath, attr_words.astype("<u4"))
                entry["atr"] = rel(apath)
            # integrity record: byte size + CRC32 of each data file, taken
            # from the bytes just written — the manifest carries what the
            # files MUST look like, so verify()/staging can tell truncation
            # and bit rot from legitimate data forever after
            pairs = [("vec", vpath), ("ids", ipath)]
            if "scl" in entry:
                pairs.append(("scl", spath))
            if "atr" in entry:
                pairs.append(("atr", apath))
            entry["bytes"] = {}
            entry["crc"] = {}
            for key, path in pairs:
                entry["bytes"][key] = os.path.getsize(path)
                entry["crc"][key] = _crc_file(path)
                self._fsync_file(path)
            # injected post-fsync corruption (media rot / torn write the
            # kernel lied about): lands AFTER the checksum record, so the
            # verify gate — not this writer — must catch it
            plan.corrupt("shard_file", vpath)

        faults.retry(_write_files, op="shard_write")
        return entry

    # -- read -------------------------------------------------------------
    def _load_entry(self, entry: Dict, raw: bool = False):
        """(ids, vecs) dequantized to fp32 rows — or, with raw=True,
        (ids, stored-dtype vecs, scales-or-None) so the device top-k path
        can ship int8 codes / fp16 rows over PCIe and dequantize on-chip
        (VERDICT r4 Weak #3: host dequant made int8 cost fp32 bandwidth).

        Tombstone masking (docs/UPDATES.md) happens HERE, the one choke
        point every reader goes through: a page id tombstoned by a LATER
        generation comes back as -1, which the exact merge, the HBM serving
        merge, and the IVF posting gather all already treat as a dead slot
        — so stale vectors can score but never surface."""
        faults.active().check("shard_read")
        vecs = np.load(os.path.join(self.directory, entry["vec"]),
                       mmap_mode="r")
        ids = self._mask_dead(
            np.load(os.path.join(self.directory, entry["ids"])),
            entry.get("gen", 0))
        scale = (np.load(os.path.join(self.directory, entry["scl"]))
                 if "scl" in entry else None)
        if raw:
            return ids, vecs, scale
        if scale is not None:   # int8: dequantize on read (fp32 rows)
            vecs = np.asarray(vecs, np.float32) * \
                scale.astype(np.float32)[:, None]
        return ids, vecs

    def load_shard(self, index: int) -> Tuple[np.ndarray, np.ndarray]:
        return self._load_entry(
            {s["index"]: s for s in self.shards()}[index])

    def load_ids(self, entry: Dict) -> np.ndarray:
        """Just one shard's (tombstone-masked) page ids — the cheap reload
        the serving hot-swap uses when it reuses already-staged device
        vectors but must re-apply tombstones from newer generations."""
        return self._mask_dead(
            np.load(os.path.join(self.directory, entry["ids"])),
            entry.get("gen", 0))

    def load_all(self) -> Tuple[np.ndarray, np.ndarray]:
        """Concatenated (ids [N], vectors [N, D] fp16). Shard files are
        memory-mapped; the concat materialises — callers at 1B-page scale
        should iterate shards instead (see iter_shards)."""
        ids_list: List[np.ndarray] = []
        vec_list: List[np.ndarray] = []
        for s in self.shards():
            ids, vecs = self._load_entry(s)
            ids_list.append(ids)
            vec_list.append(np.asarray(vecs))
        if not ids_list:
            return (np.zeros(0, np.int64),
                    np.zeros((0, self.dim), np.float16))
        return np.concatenate(ids_list), np.concatenate(vec_list)

    def iter_shards(self, raw: bool = False, prefetch: int = 0,
                    entries: Optional[List[Dict]] = None):
        """Yield every shard's arrays. `prefetch` > 0 double-buffers the
        sweep: shard loads run `prefetch` ahead on a background reader
        thread (read_ahead above), with the mmap'd vector file materialized
        READER-SIDE — np.load(mmap_mode='r') defers the actual disk read to
        first touch, which without the copy would land back on the consumer
        and overlap nothing. `entries` sweeps an explicit shard-table
        snapshot instead of the live table (the serving hot-swap's
        old-view isolation, docs/UPDATES.md)."""
        # one merged-table build for the whole sweep (not one per shard)
        if entries is None:
            entries = self.shards()
        if not prefetch:
            return (self._load_entry(s, raw=raw) for s in entries)

        def _load():
            for s in entries:
                out = self._load_entry(s, raw=raw)
                yield (out[0], np.asarray(out[1]), *out[2:])

        return read_ahead(_load(), depth=prefetch)


class GenerationWriter:
    """Append one generation to a VectorStore (docs/UPDATES.md).

    Protocol: shards written through write_shard land under
    `<store>/gen-NNNN/` with GLOBALLY unique shard indices (continuing the
    store's index sequence, past quarantined indices too), invisible to
    every reader until commit() atomically writes the generation manifest
    — the same data-files-then-manifest durability order as the base
    embed, so a crash or injected fault mid-append costs exactly this
    generation and the chain before it keeps serving. commit() also clears
    any recorded missing id-range this generation fully re-covers (a
    repair append)."""

    def __init__(self, store: VectorStore, gen: int, tombstones=()):
        import shutil
        if gen != store.chain_generation + 1:
            raise ValueError(f"generation {gen} cannot be opened: the chain "
                             f"is at {store.chain_generation}")
        self.store = store
        self.gen = int(gen)
        self.tombstones = sorted({int(t) for t in tombstones})
        self._dir = store._gen_path(gen)
        # a quarantined predecessor may have left files under this gen
        # number: the torn generation is unreachable (its manifest is
        # gone), so its number and directory are REUSED — clear leftovers
        # first so stale bytes can never satisfy a fresh CRC record
        if os.path.isdir(self._dir):
            shutil.rmtree(self._dir, ignore_errors=True)
        os.makedirs(self._dir, exist_ok=True)
        self._entries: List[Dict] = []
        self._next_index = store._next_shard_index()
        self._id_cursor = store.next_page_id()
        self._committed = False

    def write_shard(self, ids: np.ndarray,
                    vecs: Optional[np.ndarray] = None, *,
                    codes: Optional[np.ndarray] = None,
                    scales: Optional[np.ndarray] = None,
                    attrs: Optional[np.ndarray] = None) -> Dict:
        """Persist one appended shard (same vecs/codes contract as
        VectorStore.write_shard); the shard index is assigned here."""
        index = self._next_index
        entry = self.store._write_shard_files(
            os.path.basename(self._dir), index, ids, vecs, codes, scales,
            attrs=attrs)
        entry["gen"] = self.gen
        kept = np.asarray(ids)[np.asarray(ids) >= 0]
        entry["id_lo"] = int(kept.min()) if kept.size else self._id_cursor
        entry["id_hi"] = int(kept.max()) + 1 if kept.size else self._id_cursor
        self._entries.append(entry)
        self._next_index += 1
        return entry

    def commit(self) -> Dict:
        """Atomically publish the generation: manifest last, fault-aware
        (`gen_manifest_dump` / `gen_manifest_file` ops) — a torn manifest
        here is exactly what readers quarantine."""
        if self._committed:
            raise RuntimeError(f"generation {self.gen} already committed")
        man = {
            "gen": self.gen,
            "model_step": self.store.manifest.get("model_step"),
            "tombstones": self.tombstones,
            "id_start": self._id_cursor,
            "id_end": max([self._id_cursor]
                          + [e["id_hi"] for e in self._entries]),
            "max_index": max([self._next_index - 1]
                             + [e["index"] for e in self._entries]),
            "shards": sorted(self._entries, key=lambda s: s["index"]),
        }
        self.store._atomic_dump(
            man, os.path.join(self._dir, "manifest.json"), op="gen_manifest")
        self.store._register_generation(man)
        if self._entries:
            lo = min(e["id_lo"] for e in self._entries)
            hi = max(e["id_hi"] for e in self._entries)
            self.store._clear_missing_ranges(
                lambda a, b: lo <= a and b <= hi)
        self._committed = True
        return man

    def abort(self) -> None:
        import shutil
        if not self._committed:
            shutil.rmtree(self._dir, ignore_errors=True)
