"""Vector store: memory-mapped fp16 shards + id index (SURVEY.md §3 #20).

Layout under a directory:
  manifest.json               {"dim", "dtype", "shard_size", "shards": [...]}
  shard_00000.vec.npy         [n, dim] float16 L2-NORMALIZED page vectors
  shard_00000.ids.npy         [n] int64 page ids  (-1 = padding, never stored)

Vectors are stored normalized so retrieval is a pure dot product. Shards are
the resume unit: the manifest records completed shards and a restarted job
skips them (SURVEY.md §5.3 failure recovery).
"""
from __future__ import annotations

import json
import os
from typing import Dict, List, Tuple

import numpy as np


class VectorStore:
    def __init__(self, directory: str, dim: int | None = None,
                 shard_size: int = 65_536):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._manifest_path = os.path.join(self.directory, "manifest.json")
        if os.path.exists(self._manifest_path):
            with open(self._manifest_path) as f:
                self.manifest = json.load(f)
            if dim is not None and dim != self.manifest["dim"]:
                raise ValueError(
                    f"store at {self.directory} holds {self.manifest['dim']}-d "
                    f"vectors but dim={dim} was requested; use a fresh "
                    "directory (or reset()) when the model out_dim changes")
        else:
            if dim is None:
                raise FileNotFoundError(
                    f"no vector store at {self.directory} (missing "
                    "manifest.json) — run the 'embed' job first, or pass "
                    "dim= to create a new store")
            self.manifest = {"dim": dim, "dtype": "float16",
                             "shard_size": shard_size, "shards": []}
            self._flush_manifest()

    @property
    def dim(self) -> int:
        return self.manifest["dim"]

    @property
    def num_vectors(self) -> int:
        return sum(s["count"] for s in self.manifest["shards"])

    def completed_shards(self) -> set:
        return {s["index"] for s in self.manifest["shards"]}

    def _flush_manifest(self) -> None:
        tmp = self._manifest_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.manifest, f, indent=1, sort_keys=True)
        os.replace(tmp, self._manifest_path)  # atomic: crash-safe resume

    def reset(self) -> None:
        """Drop all shards (e.g. the model changed and vectors are stale)."""
        for s in self.manifest["shards"]:
            for key in ("vec", "ids"):
                try:
                    os.remove(os.path.join(self.directory, s[key]))
                except FileNotFoundError:
                    pass
        self.manifest["shards"] = []
        self._flush_manifest()

    # -- write ------------------------------------------------------------
    def write_shard(self, index: int, ids: np.ndarray,
                    vecs: np.ndarray) -> None:
        if vecs.shape[-1] != self.dim:
            raise ValueError(f"vectors are {vecs.shape[-1]}-d, store is "
                             f"{self.dim}-d")
        keep = ids >= 0  # drop batch padding rows
        ids, vecs = ids[keep], vecs[keep]
        vpath = os.path.join(self.directory, f"shard_{index:05d}.vec.npy")
        ipath = os.path.join(self.directory, f"shard_{index:05d}.ids.npy")
        np.save(vpath, vecs.astype(np.float16))
        np.save(ipath, ids.astype(np.int64))
        entry = {"index": index, "count": int(ids.shape[0]),
                 "vec": os.path.basename(vpath), "ids": os.path.basename(ipath)}
        self.manifest["shards"] = (
            [s for s in self.manifest["shards"] if s["index"] != index]
            + [entry])
        self.manifest["shards"].sort(key=lambda s: s["index"])
        self._flush_manifest()

    # -- read -------------------------------------------------------------
    def load_shard(self, index: int) -> Tuple[np.ndarray, np.ndarray]:
        entry = {s["index"]: s for s in self.manifest["shards"]}[index]
        vecs = np.load(os.path.join(self.directory, entry["vec"]),
                       mmap_mode="r")
        ids = np.load(os.path.join(self.directory, entry["ids"]))
        return ids, vecs

    def load_all(self) -> Tuple[np.ndarray, np.ndarray]:
        """Concatenated (ids [N], vectors [N, D] fp16). Shard files are
        memory-mapped; the concat materialises — callers at 1B-page scale
        should iterate shards instead (see iter_shards)."""
        ids_list: List[np.ndarray] = []
        vec_list: List[np.ndarray] = []
        for s in self.manifest["shards"]:
            ids, vecs = self.load_shard(s["index"])
            ids_list.append(ids)
            vec_list.append(np.asarray(vecs))
        if not ids_list:
            return (np.zeros(0, np.int64),
                    np.zeros((0, self.dim), np.float16))
        return np.concatenate(ids_list), np.concatenate(vec_list)

    def iter_shards(self):
        for s in self.manifest["shards"]:
            yield self.load_shard(s["index"])
