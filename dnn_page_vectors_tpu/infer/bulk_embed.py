"""Corpus->vector bulk-embed job (SURVEY.md §3 #19; call stack §4.2).

The reference's batch-inference job ran data-parallel on GPUs
(BASELINE.json:5); here the forward pass is one jitted `encode_page` with
the batch sharded over the mesh 'data' axis and params HBM-resident, so every
chip embeds its batch shard and results stream back to the host (overlapped
with the next batch via the prefetch queue) into the resumable vector store.
Throughput metric: pages/sec/chip (BASELINE.json:2).
"""
from __future__ import annotations

import queue as queue_mod
import threading
import time
from typing import Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np

from dnn_page_vectors_tpu.config import Config
from dnn_page_vectors_tpu.data.loader import iter_corpus_batches, prefetch_to_device
from dnn_page_vectors_tpu.data.toy import ToyCorpus
from dnn_page_vectors_tpu.infer.vector_store import VectorStore
from dnn_page_vectors_tpu.models.losses import l2_normalize
from dnn_page_vectors_tpu.parallel.sharding import (
    batch_sharding, replicated, shard_params, stacked_batch_sharding)
from dnn_page_vectors_tpu.utils import faults
from dnn_page_vectors_tpu.utils.logging import MetricsLogger
from dnn_page_vectors_tpu.utils.profiling import PipelineProfiler


class _ShardWriter:
    """Background store writeback: the shard-level np.concatenate +
    write_shard runs on this thread, so disk writeback of shard i overlaps
    device compute of shard i+1 instead of stalling the device loop between
    shards.

    Contract:
      * bounded pending budget (`max_pending` queued shards) — host memory
        for not-yet-written shards stays O(budget), and a dead disk
        backpressures the device loop instead of buffering forever;
      * the resume manifest records a shard only AFTER write_shard returns
        (data files synced, then the manifest flush — vector_store.py), so
        killing the job mid-shard never marks an unwritten shard complete;
      * the first writer exception is re-raised consumer-side AS ITSELF
        (the caller's `except SomeError` still matches — writeback moving
        off-thread must not change the exception surface): submit() raises
        it promptly (the device loop stops instead of racing ahead), and
        close() joins the thread and re-raises so embed_corpus can never
        return with a swallowed write failure.
    """

    _SENTINEL = object()

    def __init__(self, store: VectorStore, q8: bool, max_pending: int = 2,
                 profiler: Optional[PipelineProfiler] = None,
                 log: Optional[MetricsLogger] = None,
                 n_dev: int = 1, t0: Optional[float] = None):
        self._store = store
        self._q8 = q8
        self._prof = profiler
        self._log = log
        self._n_dev = n_dev
        self._t0 = time.perf_counter() if t0 is None else t0
        self._q: "queue_mod.Queue[object]" = queue_mod.Queue(
            maxsize=max(1, max_pending))
        self._err: Optional[BaseException] = None
        self._t = threading.Thread(target=self._run, daemon=True,
                                   name="shard-writer")
        self._t.start()

    def submit(self, index: int, ids_acc, vec_acc, scl_acc,
               pages_so_far: int) -> None:
        """Queue one finished shard (accumulator lists, concatenated on the
        writer thread). Blocks while the pending budget is full; raises the
        writer's error as soon as one exists."""
        item = (index, ids_acc, vec_acc, scl_acc, pages_so_far)
        t0 = time.perf_counter()
        try:
            while True:
                if self._err is not None:
                    raise self._err
                try:
                    self._q.put(item, timeout=0.1)
                    return
                except queue_mod.Full:
                    continue
        finally:
            if self._prof is not None:
                self._prof.add("write_wait", time.perf_counter() - t0)

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is self._SENTINEL:
                return
            if self._err is not None:
                continue   # drain after failure so submit/close never hang
            try:
                index, ids_acc, vec_acc, scl_acc, pages = item
                t0 = time.perf_counter()
                ids = np.concatenate(ids_acc)
                if self._q8:
                    self._store.write_shard(index, ids,
                                            codes=np.concatenate(vec_acc),
                                            scales=np.concatenate(scl_acc))
                else:
                    self._store.write_shard(index, ids,
                                            np.concatenate(vec_acc))
                now = time.perf_counter()
                if self._prof is not None:
                    self._prof.add("write", now - t0)
                if self._log is not None:
                    self._log.write({
                        "bulk_embed_shard": index,
                        "pages_per_sec_per_chip":
                            pages / (now - self._t0) / self._n_dev})
            except BaseException as e:
                self._err = e

    def close(self, raise_error: bool = True) -> None:
        """Join the writer (flushing queued shards) and re-raise its first
        error. raise_error=False is the unwind path when the device loop
        already holds the primary exception."""
        if self._t.is_alive():
            self._q.put(self._SENTINEL)
            self._t.join()
        if raise_error and self._err is not None:
            raise self._err


def _stack_batches(it, k: int):
    """Group k consecutive {page, page_id} batches into one [k, B, ...]
    stacked batch for the fused lax.map sweep; the tail group is padded
    with page_id=-1 zero batches (dropped by the store like any padding)."""
    group = []

    def _emit(g):
        return {key: np.stack([b[key] for b in g]) for key in g[0]}

    for b in it:
        group.append(b)
        if len(group) == k:
            yield _emit(group)
            group = []
    if group:
        pad = {key: np.zeros_like(group[0][key]) for key in group[0]}
        pad["page_id"] = np.full_like(group[0]["page_id"], -1)
        yield _emit(group + [pad] * (k - len(group)))


class BulkEmbedder:
    def __init__(self, cfg: Config, model, params, page_tok, mesh,
                 query_tok=None):
        self.cfg = cfg
        self.model = model
        # (re-)place params for THIS mesh — training may have run on a
        # different mesh shape than the embed job (call stack §4.2 restores
        # from checkpoint anyway). Under multi-host, `mesh` is process-LOCAL
        # (parallel/multihost.py) and params trained on the global mesh are
        # pulled to host first (replicated DP params: every host has a copy).
        if any(isinstance(x, jax.Array) and not x.is_fully_addressable
               for x in jax.tree_util.tree_leaves(params)):
            from dnn_page_vectors_tpu.parallel.multihost import (
                host_replicated_copy)
            params = host_replicated_copy(params)
        self.params = shard_params(params, mesh)
        self.page_tok = page_tok
        self.query_tok = query_tok
        self.mesh = mesh
        out_sh = batch_sharding(mesh)

        def _encode(params, ids, method):
            vecs = model.apply(params, ids, deterministic=True, method=method)
            return l2_normalize(vecs)

        # Page vectors leave the device as fp16: the store persists fp16 (or
        # int8 quantized FROM the fp16-rounded values) either way, so casting
        # on device halves the device->host bytes of the bulk-embed job — the
        # job's whole output is D2H traffic (~0.5 GB/M pages at D=256).
        # Normalization still runs in fp32; the cast is the store's own
        # rounding, just applied before the wire instead of after. Query
        # vectors stay fp32 (they feed the fp32 top-k scorer directly).
        self._encode_page = jax.jit(
            lambda p, x: _encode(p, x, "encode_page").astype(jnp.float16),
            in_shardings=(None, batch_sharding(mesh)), out_shardings=out_sh)
        self._encode_query = jax.jit(
            lambda p, x: _encode(p, x, "encode_query"),
            in_shardings=(None, batch_sharding(mesh)), out_shardings=out_sh)
        # Fused sweep: E batches per dispatch ([E, B, ...] -> [E, B, D] via
        # lax.map). Same per-batch compute, so vectors are identical to the
        # per-batch path. embed_corpus dispatches eval.embed_stack batches
        # at a time through this (+8% measured on v5e at E=8, round 4 —
        # dispatch amortization on the forward-only sweep).
        stk = stacked_batch_sharding(mesh)

        def _encode_stack(params, stacked):
            return jax.lax.map(
                lambda x: _encode(params, x, "encode_page").astype(
                    jnp.float16), stacked)

        self._encode_page_stack = jax.jit(
            _encode_stack, in_shardings=(None, stk), out_shardings=stk)

        # int8-store wire (round 5): quantize ON DEVICE with exactly the
        # math VectorStore.write_shard applies on host — per-row scale from
        # the fp16-rounded vector, fp16-rounded scale with the underflow
        # floor, rint codes — so the job ships 1 B/dim codes + 2 B scales
        # instead of 2 B/dim fp16 rows (another 2x off the bulk-embed D2H
        # wire on top of the fp16 cast; the store bytes are unchanged).
        def _quantize(v16):
            v = v16.astype(jnp.float32)
            scale = jnp.max(jnp.abs(v), axis=-1) / 127.0
            floor = jnp.float32(jnp.float16(6.2e-5))  # exact fp16 value
            safe = jnp.maximum(
                scale.astype(jnp.float16).astype(jnp.float32), floor)
            codes = jnp.clip(jnp.rint(v / safe[..., None]),
                             -127, 127).astype(jnp.int8)
            return codes, safe.astype(jnp.float16)

        self._encode_page_q8 = jax.jit(
            lambda p, x: _quantize(_encode(p, x, "encode_page").astype(
                jnp.float16)),
            in_shardings=(None, batch_sharding(mesh)),
            out_shardings=(out_sh, out_sh))

        def _encode_stack_q8(params, stacked):
            return jax.lax.map(
                lambda x: _quantize(_encode(params, x, "encode_page").astype(
                    jnp.float16)), stacked)

        self._encode_page_stack_q8 = jax.jit(
            _encode_stack_q8, in_shardings=(None, stk),
            out_shardings=(stk, stk))

    # -- single batches ---------------------------------------------------
    def _put(self, ids: np.ndarray) -> jax.Array:
        # jit under process_count>1 refuses numpy args with non-replicated
        # in_shardings (it can't tell global from process-local values), so
        # place the batch explicitly; the mesh here is fully addressable
        # (local under multi-host, global single-process).
        return jax.device_put(ids, batch_sharding(self.mesh))

    def embed_pages(self, ids: np.ndarray) -> np.ndarray:
        """[B, L(, K)] token ids -> [B, D] L2-normalized page vectors.

        Returns FLOAT16 rows (ADVICE r5): the page tower casts to fp16 on
        device — the store's own rounding applied before the D2H wire, so
        the bulk job ships half the bytes; normalization still runs fp32.
        The query tower (embed_queries) stays fp32: it feeds the fp32 top-k
        scorer directly and is never bulk traffic."""
        return np.asarray(self._encode_page(self.params, self._put(ids)))

    def embed_queries(self, ids: np.ndarray) -> np.ndarray:
        return np.asarray(self._encode_query(self.params, self._put(ids)))

    def embed_texts(self, texts, tower: str = "query",
                    batch_size: Optional[int] = None) -> np.ndarray:
        """Tokenize + embed a list of texts, padding each batch to the
        compiled batch shape (one XLA program regardless of len(texts)).
        Shared by the recall eval and the ANN miner.

        Return dtype is per-tower (ADVICE r5): tower="page" yields FLOAT16
        rows (the on-device store-rounding cast, see embed_pages) while
        tower="query" yields fp32 — callers mixing towers must not assume
        a common dtype."""
        tok = self.query_tok if tower == "query" else self.page_tok
        run = self.embed_queries if tower == "query" else self.embed_pages
        bs = batch_size or self.cfg.eval.embed_batch_size
        chunks = []
        for s in range(0, len(texts), bs):
            part = texts[s: s + bs]
            enc = tok.encode_batch(part)
            if enc.shape[0] < bs:
                pad = bs - enc.shape[0]
                enc = np.concatenate(
                    [enc, np.zeros((pad,) + enc.shape[1:], enc.dtype)])
            chunks.append(run(enc)[: len(part)])
        return (np.concatenate(chunks) if chunks
                else np.zeros((0, self.cfg.model.out_dim), np.float32))

    # -- the bulk job -----------------------------------------------------
    # graftcheck: hot
    def embed_corpus(self, corpus: ToyCorpus, store: VectorStore,
                     batch_size: Optional[int] = None, resume: bool = True,
                     log: Optional[MetricsLogger] = None,
                     start: int = 0, stop: Optional[int] = None,
                     workers: Optional[int] = None,
                     write_pending: Optional[int] = None,
                     profiler: Optional[PipelineProfiler] = None
                     ) -> VectorStore:
        """Sweep the corpus into the store, one store-shard at a time.

        Host pipeline: `workers` tokenizer workers (default
        cfg.data.tokenize_workers) read+tokenize batch id-ranges
        concurrently, reassembled in order — vectors are byte-identical to
        the serial path; store writeback runs on a background writer thread
        with a bounded pending budget (`write_pending`, default
        cfg.eval.writeback_depth), so the disk write of shard i overlaps
        device compute of shard i+1. The writer joins — and re-raises —
        before this method returns; the manifest records a shard only after
        its files are durably written, so a killed job never resumes past
        an unwritten shard.

        `profiler` (one is created when omitted) collects the per-stage
        wall-time breakdown (produce_wait / read / tokenize / h2d / compute
        / d2h / write / write_wait); the summary lands in the metrics log
        when `log` is given.

        Resume: completed shards are recorded in the store manifest and
        skipped on restart (SURVEY.md §5.3 fault recovery).

        Multi-host (SURVEY.md §4.2 "each host reads its file shards"): when
        jax.process_count() > 1, each process embeds only the store shards
        with ``si % process_count == process_index`` on its process-LOCAL
        mesh — the forward pass has no collectives, so hosts run fully
        independently and a straggler never stalls the others — and records
        them under its own writer manifest; after a barrier, process 0 folds
        the writer manifests into the main one.

        `start`/`stop` restrict the sweep to a page range (both must be
        store-shard-aligned so resume bookkeeping stays per-shard exact);
        this is the manual variant of the same sharding for fleets launched
        WITHOUT jax.distributed — one process per corpus slice, each with
        ``writer_id=start // shard_size`` (docs/SCALING.md recipe).
        """
        bs = batch_size or self.cfg.eval.embed_batch_size
        if store.manifest.get("compacted_through"):
            # a compacted base re-shards rows by id order under new shard
            # indices (docs/MAINTENANCE.md): the index-based resume
            # bookkeeping below would re-embed — and double-assign — the
            # whole base range. Compaction only ever runs on a completed
            # store, so a base sweep here is a caller error.
            raise ValueError(
                f"store at {store.directory} has been compacted (through "
                f"generation {store.manifest['compacted_through']}); the "
                "base embed is complete — append new pages with "
                "append_corpus / `cli append` instead")
        shard_size = store.manifest["shard_size"]
        assert shard_size % bs == 0 or shard_size >= corpus.num_pages, (
            "shard_size must be a batch multiple for resumable sweeps")
        stop = corpus.num_pages if stop is None else min(stop, corpus.num_pages)
        if start % shard_size:
            raise ValueError(f"start={start} must be a multiple of the store "
                             f"shard_size {shard_size}")
        if stop % shard_size and stop != corpus.num_pages:
            raise ValueError(f"stop={stop} must be shard-aligned (multiple of "
                             f"{shard_size}) or the corpus end "
                             f"{corpus.num_pages}")
        if resume:
            # integrity gate before trusting the manifest (docs/
            # ROBUSTNESS.md): a shard whose bytes no longer match their
            # recorded checksum/size is quarantined HERE, so `done` below
            # excludes it and exactly its id-range is re-embedded — resume
            # never skips over silently corrupt vectors
            bad = store.verify()
            if bad and log:
                log.write({"bulk_embed_quarantined_shards": bad})
        pi, pc = jax.process_index(), jax.process_count()
        if pc > 1:
            from dnn_page_vectors_tpu.parallel.multihost import is_local_mesh
            if not is_local_mesh(self.mesh):
                raise ValueError(
                    "multi-process embed_corpus requires a process-local "
                    "mesh (parallel.multihost.local_mesh): a global mesh "
                    "would deadlock on per-process shard loops")
            if store.writer_id != pi:
                raise ValueError(
                    f"multi-process embed_corpus needs "
                    f"writer_id=process_index ({pi}), got {store.writer_id}")
        done = store.completed_shards() if resume else set()
        n_dev = self.mesh.devices.size
        # int8 stores quantize ON DEVICE (codes + fp16 scales over the wire,
        # 1 B/dim instead of 2 — see the q8 encode paths above); fp16 stores
        # ship fp16 rows. Either way the wire carries the stored width.
        q8 = store.manifest["dtype"] == "int8"
        workers = (self.cfg.data.tokenize_workers if workers is None
                   else workers)
        write_pending = (self.cfg.eval.writeback_depth if write_pending is None
                         else write_pending)
        prof = PipelineProfiler() if profiler is None else profiler
        # embed-sweep throughput as registry instruments (docs/
        # OBSERVABILITY.md): the windowed pages counter answers "what is
        # the rate RIGHT NOW" mid-sweep, the end-of-job gauge mirrors the
        # metrics line
        from dnn_page_vectors_tpu.utils import telemetry
        _reg = telemetry.default_registry()
        _m_pages = _reg.counter("embed.pages",
                                window_s=telemetry.DEFAULT_WINDOW_S)
        t0 = time.perf_counter()
        pages = 0
        writer = _ShardWriter(store, q8, max_pending=write_pending,
                              profiler=prof, log=log, n_dev=n_dev, t0=t0)
        try:
            for si in range(start // shard_size, -(-stop // shard_size)):
                if si in done or si % pc != pi:
                    continue
                lo = si * shard_size
                hi = min(lo + shard_size, corpus.num_pages)
                ids_acc, vec_acc, scl_acc = [], [], []
                batches = iter_corpus_batches(corpus, self.page_tok, bs,
                                              start=lo, stop=hi,
                                              workers=workers, profiler=prof)
                # clamp to the shard's batch count: a 2-batch shard must not
                # pad an 8-slot dispatch with 6 all-zero batches
                E = min(max(1, self.cfg.eval.embed_stack),
                        -(-(hi - lo) // bs))
                if E > 1:
                    # fuse E batches per dispatch (lax.map; +8% measured at
                    # E=8): the tail group is padded with page_id=-1 batches,
                    # which write_shard drops like any batch padding
                    batches = _stack_batches(batches, E)
                    sharding = stacked_batch_sharding(self.mesh)
                    encode = (self._encode_page_stack_q8 if q8
                              else self._encode_page_stack)
                else:
                    sharding = batch_sharding(self.mesh)
                    encode = self._encode_page_q8 if q8 else self._encode_page
                # Output is double-buffered (VERDICT r1 #8): dispatch batch
                # i's encode (async under JAX's deferred execution), THEN
                # materialize batch i-1's vectors — the device->host copy of
                # the previous batch overlaps the current batch's compute
                # instead of serializing after it.
                pending = None

                def _collect(p):
                    nonlocal pages
                    with prof.stage("d2h"):
                        # ONE packed drain per dispatch: ids + vectors
                        # (+ scales) materialize together instead of a
                        # sequence of per-array np.asarray syncs — on a
                        # tunneled/remote backend each sync is a full
                        # round trip, and the drain rate (stage_d2h_bytes
                        # over stage_d2h_s, reported as
                        # embed_d2h_mbytes_per_sec) is what bounds the
                        # from-text sweep (docs/MFU.md "host pipeline").
                        host = jax.device_get(p)  # graftcheck: off=host-sync -- the one packed d2h drain per dispatch
                    ids = host[0].reshape(-1)
                    if q8:
                        codes, scl = host[1]
                        vec_acc.append(codes.reshape(-1, codes.shape[-1]))
                        scl_acc.append(scl.reshape(-1))
                        prof.add_bytes("d2h", ids.nbytes + codes.nbytes
                                       + scl.nbytes)
                    else:
                        vecs = host[1]
                        vec_acc.append(vecs.reshape(-1, vecs.shape[-1]))
                        prof.add_bytes("d2h", ids.nbytes + vecs.nbytes)
                    ids_acc.append(ids)
                    real = (ids >= 0).sum()
                    pages += int(real)
                    _m_pages.inc(int(real))

                for batch in prefetch_to_device(batches, sharding=sharding,
                                                profiler=prof):
                    with prof.stage("compute"):
                        vecs = encode(self.params, batch["page"])
                    if pending is not None:
                        _collect(pending)
                    pending = (batch["page_id"], vecs)
                if pending is not None:
                    _collect(pending)
                # hand the shard to the writer thread: its concat + disk
                # write overlaps the next shard's device compute; resume
                # bookkeeping happens inside write_shard after the data is
                # durably on disk
                writer.submit(si, ids_acc, vec_acc,
                              scl_acc if q8 else None, pages)
        except BaseException:
            writer.close(raise_error=False)  # primary exception wins
            raise
        writer.close()   # join + re-raise any write failure
        _reg.gauge("embed.pages_per_sec_per_chip").set(
            pages / max(time.perf_counter() - t0, 1e-9) / n_dev)
        # measured drain rate of the packed d2h transfers — the transport
        # number the from-text sweep is bounded by (docs/MFU.md)
        d2h_s = prof.stages().get("d2h", 0.0)
        d2h_rate = (prof.stage_bytes().get("d2h", 0) / d2h_s / 1e6
                    if d2h_s > 0 else 0.0)
        _reg.gauge("embed.d2h_mbytes_per_sec").set(d2h_rate)
        if log:
            rec = {"bulk_embed_pages": pages,
                   "embed_d2h_mbytes_per_sec": round(d2h_rate, 2),
                   **prof.summary()}
            fc = faults.counters()
            if fc:     # recovery-path activity belongs next to the rate
                rec["fault_counters"] = fc
            log.write(rec)
        if pc > 1:
            from dnn_page_vectors_tpu.parallel.multihost import barrier
            barrier("embed_corpus_written")
            if pi == 0:
                store.merge_writers()
            barrier("embed_corpus_merged")
            store.reload()
        return store
