"""Corpus->vector bulk-embed job (SURVEY.md §3 #19; call stack §4.2).

The reference's batch-inference job ran data-parallel on GPUs
(BASELINE.json:5); here the forward pass is one jitted `encode_page` with
the batch sharded over the mesh 'data' axis and params HBM-resident, so every
chip embeds its batch shard and results stream back to the host (overlapped
with the next batch via the prefetch queue) into the resumable vector store.
Throughput metric: pages/sec/chip (BASELINE.json:2).
"""
from __future__ import annotations

import time
from typing import Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np

from dnn_page_vectors_tpu.config import Config
from dnn_page_vectors_tpu.data.loader import iter_corpus_batches, prefetch_to_device
from dnn_page_vectors_tpu.data.toy import ToyCorpus
from dnn_page_vectors_tpu.infer.vector_store import VectorStore
from dnn_page_vectors_tpu.models.losses import l2_normalize
from dnn_page_vectors_tpu.parallel.sharding import (
    batch_sharding, replicated, shard_params)
from dnn_page_vectors_tpu.utils.logging import MetricsLogger


class BulkEmbedder:
    def __init__(self, cfg: Config, model, params, page_tok, mesh,
                 query_tok=None):
        self.cfg = cfg
        self.model = model
        # (re-)place params for THIS mesh — training may have run on a
        # different mesh shape than the embed job (call stack §4.2 restores
        # from checkpoint anyway).
        self.params = shard_params(params, mesh)
        self.page_tok = page_tok
        self.query_tok = query_tok
        self.mesh = mesh
        out_sh = batch_sharding(mesh)

        def _encode(params, ids, method):
            vecs = model.apply(params, ids, deterministic=True, method=method)
            return l2_normalize(vecs)

        self._encode_page = jax.jit(
            lambda p, x: _encode(p, x, "encode_page"),
            in_shardings=(None, batch_sharding(mesh)), out_shardings=out_sh)
        self._encode_query = jax.jit(
            lambda p, x: _encode(p, x, "encode_query"),
            in_shardings=(None, batch_sharding(mesh)), out_shardings=out_sh)
        # Fused sweep: E batches per dispatch ([E, B, ...] -> [E, B, D] via
        # lax.map). Same per-batch compute, so vectors are identical to the
        # per-batch path. Used by bench.py's throughput sweep; embed_corpus
        # still dispatches per batch (its prefetch overlap measured on par
        # on the tunneled v5e — fusing its shard loop is a possible future
        # step if multi-host profiling says dispatch dominates).
        from dnn_page_vectors_tpu.parallel.sharding import stacked_batch_sharding
        stk = stacked_batch_sharding(mesh)

        def _encode_stack(params, stacked):
            return jax.lax.map(
                lambda x: _encode(params, x, "encode_page"), stacked)

        self._encode_page_stack = jax.jit(
            _encode_stack, in_shardings=(None, stk), out_shardings=stk)

    # -- single batches ---------------------------------------------------
    def embed_pages(self, ids: np.ndarray) -> np.ndarray:
        return np.asarray(self._encode_page(self.params, ids))

    def embed_queries(self, ids: np.ndarray) -> np.ndarray:
        return np.asarray(self._encode_query(self.params, ids))

    def embed_texts(self, texts, tower: str = "query",
                    batch_size: Optional[int] = None) -> np.ndarray:
        """Tokenize + embed a list of texts, padding each batch to the
        compiled batch shape (one XLA program regardless of len(texts)).
        Shared by the recall eval and the ANN miner."""
        tok = self.query_tok if tower == "query" else self.page_tok
        run = self.embed_queries if tower == "query" else self.embed_pages
        bs = batch_size or self.cfg.eval.embed_batch_size
        chunks = []
        for s in range(0, len(texts), bs):
            part = texts[s: s + bs]
            enc = tok.encode_batch(part)
            if enc.shape[0] < bs:
                pad = bs - enc.shape[0]
                enc = np.concatenate(
                    [enc, np.zeros((pad,) + enc.shape[1:], enc.dtype)])
            chunks.append(run(enc)[: len(part)])
        return (np.concatenate(chunks) if chunks
                else np.zeros((0, self.cfg.model.out_dim), np.float32))

    # -- the bulk job -----------------------------------------------------
    def embed_corpus(self, corpus: ToyCorpus, store: VectorStore,
                     batch_size: Optional[int] = None, resume: bool = True,
                     log: Optional[MetricsLogger] = None) -> VectorStore:
        """Sweep the corpus into the store, one store-shard at a time.

        Resume: completed shards are recorded in the store manifest and
        skipped on restart (SURVEY.md §5.3 fault recovery).
        """
        bs = batch_size or self.cfg.eval.embed_batch_size
        shard_size = store.manifest["shard_size"]
        assert shard_size % bs == 0 or shard_size >= corpus.num_pages, (
            "shard_size must be a batch multiple for resumable sweeps")
        n_shards = -(-corpus.num_pages // shard_size)
        done = store.completed_shards() if resume else set()
        n_dev = self.mesh.devices.size
        t0 = time.perf_counter()
        pages = 0
        for si in range(n_shards):
            if si in done:
                continue
            start = si * shard_size
            stop = min(start + shard_size, corpus.num_pages)
            ids_acc, vec_acc = [], []
            batches = iter_corpus_batches(corpus, self.page_tok, bs,
                                          start=start, stop=stop)
            # Output is double-buffered (VERDICT r1 #8): dispatch batch i's
            # encode (async under JAX's deferred execution), THEN materialize
            # batch i-1's vectors — the device->host copy of the previous
            # batch overlaps the current batch's compute instead of
            # serializing after it.
            pending = None
            for batch in prefetch_to_device(batches,
                                            sharding=batch_sharding(self.mesh)):
                vecs = self._encode_page(self.params, batch["page"])
                if pending is not None:
                    ids_acc.append(np.asarray(pending[0]))
                    vec_acc.append(np.asarray(pending[1]))
                    pages += int((ids_acc[-1] >= 0).sum())
                pending = (batch["page_id"], vecs)
            if pending is not None:
                ids_acc.append(np.asarray(pending[0]))
                vec_acc.append(np.asarray(pending[1]))
                pages += int((ids_acc[-1] >= 0).sum())
            store.write_shard(si, np.concatenate(ids_acc),
                              np.concatenate(vec_acc))
            if log:
                dt = time.perf_counter() - t0
                log.write({"bulk_embed_shard": si,
                           "pages_per_sec_per_chip": pages / dt / n_dev})
        return store
