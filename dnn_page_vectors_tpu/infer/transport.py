"""Over-the-wire serving protocol (docs/SERVING.md "Network front end").

One compact length-prefixed binary framing for every socket in the
serving fleet — the client edge (`infer/server.py`, text queries in,
scores/ids out) and the partition RPC hop (`infer/partition_host.py`,
query vectors fanned out to partition workers). Binary because the hot
payloads ARE arrays (a [n, D] fp32 query block out, [n, k] fp32 scores +
[n, k] int64 ids back): raw little-endian array bytes round-trip exactly,
so over-the-wire results can be pinned BYTE-identical to the in-process
scatter-gather, and a query costs tens of bytes of framing instead of a
JSON re-encode of its vectors.

Frame layout (9-byte header, network byte order):

    +--------+--------+----------------+=================+
    | magic  | type   | payload length |  payload bytes  |
    | u32    | u8     | u32            |  (type-specific)|
    +--------+--------+----------------+=================+

`magic` (0x44505631, "DPV1") carries the protocol version; a reader that
sees anything else is talking to the wrong peer (or a corrupted stream)
and must REJECT — close the connection — rather than resynchronize.
`payload length` is bounded by MAX_FRAME (64 MiB): an oversize length is
rejected BEFORE any payload read, so a garbage header can never park a
connection in a multi-gigabyte recv. Truncation (EOF mid-frame) raises
`FrameError` — torn responses are indistinguishable from a crashed peer
and are treated exactly like one (docs/ROBUSTNESS.md).

Message types:

    T_QUERY      client -> front end: text queries + k/nprobe/deadline
    T_VQUERY     front end -> partition worker (and vector-mode clients):
                 an fp32 query block + k/nprobe/deadline
    T_RESULT     scores [n, k] f32 + page ids [n, k] i64 + scan bytes
    T_SHED       admission rejection (deadline/SLO budget) — NOT an error
    T_ERROR      server-side failure, message attached
    T_REGISTER   partition worker hello: (partition, replica, pid
                 [, flags, store generation])
    T_HEARTBEAT  worker liveness tick (empty payload)
    T_BYE        clean worker deregistration (empty payload)

Compressed extensions (negotiated — see below — so mixed fleets of
compressing and raw peers interoperate on one gateway):

    T_RESULT_C   a RESULT whose id block is zigzag-delta+varint encoded
                 per row; scores stay raw f32 (lossless — byte-identity
                 pins hold unchanged; ids shrink ~8 -> ~3 bytes each)
    T_VQUERY_PUT a VQUERY that also interns its query block into a
                 sender-chosen per-connection cache slot
    T_VQUERY_REF a VQUERY referencing a previously PUT slot instead of
                 re-shipping the block — the scatter's dominant wire
                 cost (the same fp32 block re-sent to every worker on
                 every request) collapses to a 2-byte slot id
    T_HELLO      capability exchange on the client edge (the RPC hop
                 negotiates via REGISTER flags + a HELLO ack)
    T_REFRESH    control: ask a worker to re-open the store and rebuild
                 its view (payload = the target store generation, plus —
                 extended form — the fleet's partition-split width for
                 elastic re-splits); the worker acks with its own
                 T_REFRESH carrying the generation and split it now
                 serves. Like REGISTER, the decoder accepts both the
                 legacy 8-byte and the extended 12-byte form, so a
                 pre-elastic peer interoperates unchanged
    T_DRAIN      control: a worker announces it is draining — the
                 gateway stops routing to it (its slice falls back to
                 the local view) and the worker BYEs once told traffic
                 has stopped coming

Fleet result-cache extensions (negotiated via FLAG_RESULT_CACHE — see
docs/SERVING.md "Result cache"):

    T_CACHE_LOOKUP  a pure result-cache probe: the generation-qualified
                    cache key (normalized query text, k, nprobe, store
                    generation, index generation). A hit answers with a
                    standard T_RESULT/T_RESULT_C; a miss answers T_SHED
                    with code SHED_CACHE_MISS — the probe never admits,
                    queues, or computes anything.
    T_CACHE_PUT     share one computed result into the peer's cache:
                    the same key plus the [k] scores/ids row. Fire-and-
                    forget (NO response frame — the receiver validates
                    the generations against its own live view and
                    silently drops a stale or unwanted entry), so a PUT
                    can ride ahead of the next request on one ordered
                    connection without desynchronizing request/response.

Negotiation: capability flags (FLAG_WIRE_COMPRESS, FLAG_RESULT_CACHE)
are advertised by
the connecting peer — a worker in its REGISTER frame, a client in a
leading T_HELLO — and confirmed by the accepting side with a T_HELLO
carrying the agreed intersection. Nobody sends a compressed or interned
frame a peer did not advertise, so a raw worker and a compressing
worker can serve side by side behind one gateway.

Deadlines travel as RELATIVE remaining milliseconds (not absolute
timestamps): the two ends of a socket do not share a clock, and a
relative budget re-anchors on the receiver's own monotonic clock at
receipt — clock skew costs at most the in-flight network time.

Filtered retrieval (negotiated via FLAG_FILTERS, docs/ANN.md "Filtered
retrieval"): T_QUERY and every T_VQUERY variant accept one OPTIONAL
trailing field — a u16 length + the CANONICAL predicate text
(index/attrs.py) in utf-8. Absent field = unfiltered, and an unfiltered
frame is byte-identical to the pre-filters protocol; decoders accept
the field unconditionally (negotiation governs what a peer SENDS, like
compression), so a filtered gateway never ships the field to a worker
that did not advertise FLAG_FILTERS — it serves that slice locally
instead, never wrong results.
"""
from __future__ import annotations

import asyncio
import itertools
import socket
import struct
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from dnn_page_vectors_tpu.utils import faults
from dnn_page_vectors_tpu.utils.faults import InjectedFault

MAGIC = 0x44505631            # "DPV1": protocol id + version in one word
MAX_FRAME = 64 * 2 ** 20      # reject oversize lengths before any recv

HEADER = struct.Struct("!IBI")            # magic, type, payload length

T_QUERY = 1
T_VQUERY = 2
T_RESULT = 3
T_SHED = 4
T_ERROR = 5
T_REGISTER = 6
T_HEARTBEAT = 7
T_BYE = 8
T_RESULT_C = 9                # compressed RESULT (varint id block)
T_VQUERY_PUT = 10             # VQUERY + intern block into a cache slot
T_VQUERY_REF = 11             # VQUERY referencing an interned slot
T_HELLO = 12                  # capability exchange (flags byte)
T_REFRESH = 13                # view-refresh control / generation ack
T_CACHE_LOOKUP = 14           # result-cache probe (key -> RESULT / miss)
T_CACHE_PUT = 15              # result-cache share (key + one result row)
T_DRAIN = 16                  # worker drain announcement (empty payload)

_TYPES = {T_QUERY, T_VQUERY, T_RESULT, T_SHED, T_ERROR, T_REGISTER,
          T_HEARTBEAT, T_BYE, T_RESULT_C, T_VQUERY_PUT, T_VQUERY_REF,
          T_HELLO, T_REFRESH, T_CACHE_LOOKUP, T_CACHE_PUT, T_DRAIN}

# capability flags (REGISTER / HELLO negotiation)
FLAG_WIRE_COMPRESS = 0x01     # peer speaks T_RESULT_C + T_VQUERY_PUT/REF
FLAG_RESULT_CACHE = 0x02      # peer speaks T_CACHE_LOOKUP / T_CACHE_PUT
FLAG_FILTERS = 0x04           # peer accepts the QUERY/VQUERY filter field

# per-connection intern table size: a protocol constant, so the sender's
# slot assignment (a ring over these slots) and the receiver's passive
# slot store can never disagree about capacity
WIRE_SLOTS = 64

# shed reason codes (T_SHED payload)
SHED_DEADLINE = 1             # deadline expired / cannot be met
SHED_QUEUE = 2                # admission queue budget exceeded
SHED_DRAINING = 3             # front end shutting down (graceful drain)
SHED_CACHE_MISS = 4           # T_CACHE_LOOKUP probe missed (not an error)

_QUERY_HEAD = struct.Struct("!QdiiH")     # req id, deadline ms, k, nprobe, nq
_VQUERY_HEAD = struct.Struct("!QdiiHH")   # ... + n, dim
_RESULT_HEAD = struct.Struct("!QQHH")     # req id, scan bytes, n, k
_SHED_HEAD = struct.Struct("!QB")         # req id, reason code
_ERROR_HEAD = struct.Struct("!Q")         # req id
_REGISTER_HEAD = struct.Struct("!IIQ")    # partition, replica, pid (legacy)
_REGISTER_HEAD2 = struct.Struct("!IIQBQ")  # ... + flags, store generation
_SLOT = struct.Struct("!H")               # intern slot id
_HELLO_HEAD = struct.Struct("!B")         # capability flags
_REFRESH_HEAD = struct.Struct("!Q")       # store generation (legacy)
_REFRESH_HEAD2 = struct.Struct("!QI")     # ... + partition-split width
# result-cache key head: req id, k, nprobe, store generation, index
# generation (signed; -1 = the view serves without an index), text len.
# The store-generation word is COMPOSED, not raw: the low 32 bits carry
# the store's folded generation and the high 32 the serving model stamp
# (docs/MAINTENANCE.md "Rolling model migration"), so a cached result
# stamped by one tower can never answer for the other — the wire codec
# treats the u64 opaquely and needs no migration awareness.
_CACHE_HEAD = struct.Struct("!QiiQqH")

_REQ_IDS = itertools.count(1)


def next_request_id() -> int:
    return next(_REQ_IDS)


class FrameError(ValueError):
    """The stream is not speaking this protocol (bad magic/type), the
    frame is oversize, or it was truncated mid-read. The only safe
    response is to reject: answer nothing further and close."""


class DeadlineExceeded(RuntimeError):
    """A request was shed at admission (or at the micro-batch door)
    because its deadline had expired or could not be met. A shed is a
    deliberate availability decision, not a server error — it counts in
    `serve.deadline_shed`, never in `serve.errors`."""


class RemoteError(RuntimeError):
    """The remote end answered T_ERROR: the failure happened there."""


# ---------------------------------------------------------------------------
# payload codecs (pure functions of bytes — the fuzz-test surface)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class QueryRequest:
    req_id: int
    deadline_ms: float            # remaining budget; <= 0 means none
    k: int                        # 0 means the server default
    nprobe: int                   # 0 means the server default
    queries: Tuple[str, ...]
    filters: Optional[str] = None  # canonical predicate text; None = all


@dataclass(frozen=True)
class VectorRequest:
    req_id: int
    deadline_ms: float
    k: int
    nprobe: int
    qv: np.ndarray                # [n, dim] float32
    filters: Optional[str] = None  # canonical predicate text; None = all


def _filters_field(filters: Optional[str]) -> bytes:
    """The optional trailing predicate field: u16 length + canonical
    text. None encodes as NO bytes at all — an unfiltered frame is
    byte-identical to the pre-filters protocol."""
    if filters is None:
        return b""
    raw = filters.encode("utf-8")
    if len(raw) > 0xFFFF:
        raise ValueError("filter text exceeds 65535 utf-8 bytes")
    return struct.pack("!H", len(raw)) + raw


def _take_filters(payload: bytes, off: int,
                  what: str) -> Tuple[Optional[str], int]:
    """Parse the optional trailing predicate field at `off`: absent
    (frame ends exactly there) -> (None, off); present -> (text, end).
    Truncation inside the field REJECTS — a frame either carries the
    whole field or none of it."""
    if off == len(payload):
        return None, off
    if off + 2 > len(payload):
        raise FrameError(f"{what} truncated inside the filter length")
    (ln,) = struct.unpack_from("!H", payload, off)
    off += 2
    if off + ln > len(payload):
        raise FrameError(f"{what} truncated inside the filter text")
    try:
        text = payload[off: off + ln].decode("utf-8")
    except UnicodeDecodeError as e:
        raise FrameError(f"{what} filter text is not utf-8: {e}") from None
    return text, off + ln


def encode_query(req_id: int, queries: Sequence[str], k: int = 0,
                 nprobe: int = 0, deadline_ms: float = 0.0,
                 filters: Optional[str] = None) -> bytes:
    if not 0 < len(queries) <= 0xFFFF:
        raise ValueError(f"1..65535 queries per frame, got {len(queries)}")
    parts = [_QUERY_HEAD.pack(req_id, float(deadline_ms), int(k),
                              int(nprobe), len(queries))]
    for q in queries:
        raw = q.encode("utf-8")
        if len(raw) > 0xFFFF:
            raise ValueError("query text exceeds 65535 utf-8 bytes")
        parts.append(struct.pack("!H", len(raw)))
        parts.append(raw)
    parts.append(_filters_field(filters))
    return b"".join(parts)


def decode_query(payload: bytes) -> QueryRequest:
    if len(payload) < _QUERY_HEAD.size:
        raise FrameError("query frame shorter than its fixed header")
    req_id, deadline_ms, k, nprobe, nq = _QUERY_HEAD.unpack_from(payload)
    off = _QUERY_HEAD.size
    queries: List[str] = []
    for _ in range(nq):
        if off + 2 > len(payload):
            raise FrameError("query frame truncated inside a length prefix")
        (ln,) = struct.unpack_from("!H", payload, off)
        off += 2
        if off + ln > len(payload):
            raise FrameError("query frame truncated inside a query string")
        try:
            queries.append(payload[off: off + ln].decode("utf-8"))
        except UnicodeDecodeError as e:
            raise FrameError(f"query text is not utf-8: {e}") from None
        off += ln
    filters, off = _take_filters(payload, off, "query frame")
    if off != len(payload):
        raise FrameError(f"{len(payload) - off} trailing bytes after the "
                         "last query")
    return QueryRequest(req_id, deadline_ms, k, nprobe, tuple(queries),
                        filters)


def encode_vquery(req_id: int, qv: np.ndarray, k: int = 0, nprobe: int = 0,
                  deadline_ms: float = 0.0,
                  filters: Optional[str] = None) -> bytes:
    qv = np.ascontiguousarray(qv, dtype="<f4")
    if qv.ndim != 2 or not 0 < qv.shape[0] <= 0xFFFF \
            or not 0 < qv.shape[1] <= 0xFFFF:
        raise ValueError(f"query block must be [1..65535, 1..65535], "
                         f"got {qv.shape}")
    return (_VQUERY_HEAD.pack(req_id, float(deadline_ms), int(k),
                              int(nprobe), qv.shape[0], qv.shape[1])
            + qv.tobytes() + _filters_field(filters))


def _block_to_qv(block, n: int, dim: int, what: str) -> np.ndarray:
    """A raw little-endian f32 block -> [n, dim] array WITHOUT copying:
    np.frombuffer aliases the (immutable) payload bytes, so the hot RPC
    decode path stops duplicating a block it immediately re-slices. The
    result is read-only; every consumer copies at its own boundary
    (device staging, np.concatenate padding)."""
    want = n * dim * 4
    if len(block) != want:
        raise FrameError(f"{what} carries {len(block)} bytes for a "
                         f"[{n}, {dim}] f32 matrix ({want} expected)")
    if n == 0 or dim == 0:
        raise FrameError(f"{what} is empty")
    return np.frombuffer(block, dtype="<f4").reshape(n, dim)


def decode_vquery(payload: bytes) -> VectorRequest:
    if len(payload) < _VQUERY_HEAD.size:
        raise FrameError("vquery frame shorter than its fixed header")
    req_id, deadline_ms, k, nprobe, n, dim = _VQUERY_HEAD.unpack_from(payload)
    cut = _VQUERY_HEAD.size + n * dim * 4
    if len(payload) < cut:
        raise FrameError(f"vquery block carries "
                         f"{len(payload) - _VQUERY_HEAD.size} bytes for a "
                         f"[{n}, {dim}] f32 matrix ({n * dim * 4} expected)")
    qv = _block_to_qv(memoryview(payload)[_VQUERY_HEAD.size: cut], n, dim,
                      "vquery block")
    filters, off = _take_filters(payload, cut, "vquery frame")
    if off != len(payload):
        raise FrameError(f"{len(payload) - off} trailing bytes after a "
                         "vquery filter field")
    return VectorRequest(req_id, deadline_ms, k, nprobe, qv, filters)


def encode_vquery_put(req_id: int, slot: int, block: bytes, n: int,
                      dim: int, k: int = 0, nprobe: int = 0,
                      deadline_ms: float = 0.0,
                      filters: Optional[str] = None) -> bytes:
    """A VQUERY that also interns its (already encoded) query block into
    the receiver's per-connection cache slot `slot`. The filter field
    (present only when `filters` is not None) is PER REQUEST — it rides
    after the block and is never interned with it."""
    return (_VQUERY_HEAD.pack(req_id, float(deadline_ms), int(k),
                              int(nprobe), n, dim)
            + _SLOT.pack(slot) + block + _filters_field(filters))


def encode_vquery_ref(req_id: int, slot: int, n: int, dim: int,
                      k: int = 0, nprobe: int = 0,
                      deadline_ms: float = 0.0,
                      filters: Optional[str] = None) -> bytes:
    """A VQUERY whose block was interned earlier on this connection: the
    per-request head plus a 2-byte slot id instead of n*dim*4 raw f32."""
    return (_VQUERY_HEAD.pack(req_id, float(deadline_ms), int(k),
                              int(nprobe), n, dim) + _SLOT.pack(slot)
            + _filters_field(filters))


def decode_vquery_any(ftype: int, payload: bytes,
                      slots: Optional[Dict[int, bytes]] = None
                      ) -> VectorRequest:
    """Decode T_VQUERY / T_VQUERY_PUT / T_VQUERY_REF. `slots` is the
    receiver's per-connection intern table: PUT stores its block there
    (a stable bytes copy — the slot outlives this frame), REF resolves
    against it. A REF to a slot never PUT on this connection is a
    protocol violation -> FrameError (the sender controls slot reuse, so
    the two tables can only disagree if the peer is broken)."""
    if ftype == T_VQUERY:
        return decode_vquery(payload)
    if len(payload) < _VQUERY_HEAD.size + _SLOT.size:
        raise FrameError("interned vquery frame shorter than its header")
    req_id, deadline_ms, k, nprobe, n, dim = _VQUERY_HEAD.unpack_from(payload)
    (slot,) = _SLOT.unpack_from(payload, _VQUERY_HEAD.size)
    if slot >= WIRE_SLOTS:
        raise FrameError(f"intern slot {slot} out of range "
                         f"(WIRE_SLOTS {WIRE_SLOTS})")
    if slots is None:
        raise FrameError("interned vquery on a connection that never "
                         "negotiated compression")
    off = _VQUERY_HEAD.size + _SLOT.size
    if ftype == T_VQUERY_PUT:
        cut = off + n * dim * 4
        if len(payload) < cut:
            raise FrameError(f"interned vquery block carries "
                             f"{len(payload) - off} bytes for a "
                             f"[{n}, {dim}] f32 matrix "
                             f"({n * dim * 4} expected)")
        block = bytes(memoryview(payload)[off: cut])
        qv = _block_to_qv(block, n, dim, "interned vquery block")
        filters, end = _take_filters(payload, cut, "interned vquery frame")
        if end != len(payload):
            raise FrameError(f"{len(payload) - end} trailing bytes after "
                             "an interned vquery filter field")
        slots[slot] = block
        return VectorRequest(req_id, deadline_ms, k, nprobe, qv, filters)
    if ftype != T_VQUERY_REF:
        # the explicit REF branch (not a fall-through): a future vquery
        # variant routed here by mistake must REJECT, not silently parse
        # as a slot reference
        raise FrameError(f"frame type {ftype} is not a vquery")
    filters, end = _take_filters(payload, off, "vquery slot reference")
    if end != len(payload):
        raise FrameError(f"{len(payload) - end} trailing bytes after a "
                         "vquery slot reference")
    block = slots.get(slot)
    if block is None:
        raise FrameError(f"vquery references empty intern slot {slot}")
    qv = _block_to_qv(block, n, dim, "interned vquery block")
    return VectorRequest(req_id, deadline_ms, k, nprobe, qv, filters)


def encode_result(req_id: int, scores: np.ndarray, ids: np.ndarray,
                  scan_bytes: int = 0) -> bytes:
    scores = np.ascontiguousarray(scores, dtype="<f4")
    ids = np.ascontiguousarray(ids, dtype="<i8")
    if scores.shape != ids.shape or scores.ndim != 2:
        raise ValueError(f"scores {scores.shape} / ids {ids.shape} must be "
                         "matching [n, k]")
    n, k = scores.shape
    return (_RESULT_HEAD.pack(req_id, int(scan_bytes), n, k)
            + scores.tobytes() + ids.tobytes())


def decode_result(payload: bytes
                  ) -> Tuple[int, np.ndarray, np.ndarray, int]:
    """-> (req_id, scores [n, k] f32, ids [n, k] i64, scan_bytes).
    Zero-copy: both arrays alias the (immutable) payload bytes via
    np.frombuffer at an offset — no slice copy, no astype copy."""
    if len(payload) < _RESULT_HEAD.size:
        raise FrameError("result frame shorter than its fixed header")
    req_id, scan_bytes, n, k = _RESULT_HEAD.unpack_from(payload)
    body_len = len(payload) - _RESULT_HEAD.size
    want = n * k * (4 + 8)
    if body_len != want:
        raise FrameError(f"result block carries {body_len} bytes for "
                         f"[{n}, {k}] scores+ids ({want} expected)")
    scores = np.frombuffer(payload, dtype="<f4", count=n * k,
                           offset=_RESULT_HEAD.size).reshape(n, k)
    ids = np.frombuffer(payload, dtype="<i8", count=n * k,
                        offset=_RESULT_HEAD.size + n * k * 4).reshape(n, k)
    return req_id, scores, ids, int(scan_bytes)


# -- varints (the compressed RESULT id block) -------------------------------
#
# LEB128 with a 10-byte cap (enough for any 64-bit zigzag delta — even
# the worst case, -1 next to 2^63-1, fits 65 bits = 10 septets). The cap
# is what makes adversarial continuation bytes REJECT instead of parsing
# unboundedly.

_VARINT_MAX_BYTES = 10


def _append_uvarint(out: bytearray, v: int) -> None:
    while v >= 0x80:
        out.append((v & 0x7F) | 0x80)
        v >>= 7
    out.append(v)


def _read_uvarint(payload, off: int) -> Tuple[int, int]:
    """-> (value, next offset); FrameError on truncation mid-varint or a
    continuation run past the 10-byte cap."""
    v = 0
    shift = 0
    end = len(payload)
    for i in range(_VARINT_MAX_BYTES):
        if off >= end:
            raise FrameError("stream truncated inside a varint")
        b = payload[off]
        off += 1
        v |= (b & 0x7F) << shift
        if not b & 0x80:
            return v, off
        shift += 7
    raise FrameError(f"varint longer than {_VARINT_MAX_BYTES} bytes "
                     "(unterminated continuation run)")


_I64_MIN, _I64_MAX = -(2 ** 63), 2 ** 63 - 1


def _encode_ids_compressed(ids: np.ndarray) -> bytearray:
    """[n, k] int64 page ids -> per-row zigzag-delta varint block. Rows
    restart their delta chain (prev = 0), so one row's ids stay
    independently decodable and a result row full of -1 padding costs
    one byte per slot. Top-k ids are draws from a bounded id space, so
    deltas carry ~log2(store rows) bits instead of 64 — the ~3x cut."""
    out = bytearray()
    for row in ids:
        prev = 0
        for v in row:
            d = int(v) - prev
            prev = int(v)
            # zigzag over plain python ints: 2d for d >= 0, -2d-1 below
            _append_uvarint(out, d << 1 if d >= 0 else (d << 1) ^ -1)
    return out


def _decode_ids_compressed(payload, off: int, n: int, k: int) -> np.ndarray:
    ids = np.empty((n, k), np.int64)
    for r in range(n):
        prev = 0
        row = ids[r]
        for c in range(k):
            zz, off = _read_uvarint(payload, off)
            d = (zz >> 1) ^ -(zz & 1)
            prev += d
            if not _I64_MIN <= prev <= _I64_MAX:
                raise FrameError(f"compressed id delta overflows int64 "
                                 f"(row {r}, col {c})")
            row[c] = prev
    if off != len(payload):
        raise FrameError(f"{len(payload) - off} trailing bytes after the "
                         "compressed id block")
    return ids


def encode_result_c(req_id: int, scores: np.ndarray, ids: np.ndarray,
                    scan_bytes: int = 0) -> bytes:
    """The compressed RESULT payload: same fixed head, raw little-endian
    f32 scores (lossless — the byte-identity pins hold unchanged), then
    the zigzag-delta varint id block."""
    scores = np.ascontiguousarray(scores, dtype="<f4")
    ids = np.ascontiguousarray(ids, dtype="<i8")
    if scores.shape != ids.shape or scores.ndim != 2:
        raise ValueError(f"scores {scores.shape} / ids {ids.shape} must be "
                         "matching [n, k]")
    n, k = scores.shape
    return (_RESULT_HEAD.pack(req_id, int(scan_bytes), n, k)
            + scores.tobytes() + bytes(_encode_ids_compressed(ids)))


def decode_result_c(payload: bytes
                    ) -> Tuple[int, np.ndarray, np.ndarray, int]:
    """-> (req_id, scores [n, k] f32, ids [n, k] i64, scan_bytes); the
    scores alias the payload (zero-copy), the ids materialize out of the
    varint block. Truncation anywhere — inside the score block, mid-
    varint, or short of n*k ids — and trailing bytes all REJECT."""
    if len(payload) < _RESULT_HEAD.size:
        raise FrameError("result frame shorter than its fixed header")
    req_id, scan_bytes, n, k = _RESULT_HEAD.unpack_from(payload)
    cut = _RESULT_HEAD.size + n * k * 4
    if len(payload) < cut:
        raise FrameError(f"compressed result truncated inside the score "
                         f"block ({len(payload) - _RESULT_HEAD.size}/"
                         f"{n * k * 4} bytes)")
    scores = np.frombuffer(payload, dtype="<f4", count=n * k,
                           offset=_RESULT_HEAD.size).reshape(n, k)
    ids = _decode_ids_compressed(payload, cut, n, k)
    return req_id, scores, ids, int(scan_bytes)


def decode_result_any(ftype: int, payload: bytes
                      ) -> Tuple[int, np.ndarray, np.ndarray, int]:
    """Raw or compressed RESULT, by frame type — receivers accept both
    unconditionally (negotiation only governs what a peer SENDS)."""
    if ftype == T_RESULT_C:
        return decode_result_c(payload)
    return decode_result(payload)


def result_raw_bytes(n: int, k: int) -> int:
    """What a [n, k] RESULT costs as a raw frame (header included) — the
    raw-equivalent side of the wire-compression accounting."""
    return HEADER.size + _RESULT_HEAD.size + n * k * (4 + 8)


def encode_shed(req_id: int, code: int, reason: str) -> bytes:
    return _SHED_HEAD.pack(req_id, code) + reason.encode("utf-8")[:512]


def decode_shed(payload: bytes) -> Tuple[int, int, str]:
    if len(payload) < _SHED_HEAD.size:
        raise FrameError("shed frame shorter than its fixed header")
    req_id, code = _SHED_HEAD.unpack_from(payload)
    return req_id, code, payload[_SHED_HEAD.size:].decode(
        "utf-8", errors="replace")


def encode_error(req_id: int, message: str) -> bytes:
    return _ERROR_HEAD.pack(req_id) + message.encode("utf-8")[:2048]


def decode_error(payload: bytes) -> Tuple[int, str]:
    if len(payload) < _ERROR_HEAD.size:
        raise FrameError("error frame shorter than its fixed header")
    (req_id,) = _ERROR_HEAD.unpack_from(payload)
    return req_id, payload[_ERROR_HEAD.size:].decode(
        "utf-8", errors="replace")


def encode_register(partition: int, replica: int, pid: int,
                    flags: int = 0, generation: int = 0) -> bytes:
    """Worker hello. `flags` advertises capabilities (FLAG_WIRE_COMPRESS
    = this worker answers T_RESULT_C and accepts interned VQUERYs once
    the gateway confirms with a T_HELLO); `generation` is the store
    generation the worker's view serves — the gateway routes around a
    worker whose generation lags the front end's (it serves that slice
    locally) until a T_REFRESH ack catches it up."""
    return _REGISTER_HEAD2.pack(partition, replica, pid, flags, generation)


def decode_register(payload: bytes) -> Tuple[int, int, int, int, int]:
    """-> (partition, replica, pid, flags, generation). Accepts the
    legacy 16-byte form (a raw pre-compression worker: flags 0,
    generation 0) next to the extended one — mixed fleets register on
    one gateway."""
    if len(payload) == _REGISTER_HEAD.size:
        partition, replica, pid = _REGISTER_HEAD.unpack(payload)
        return partition, replica, pid, 0, 0
    if len(payload) != _REGISTER_HEAD2.size:
        raise FrameError("register frame has the wrong size")
    return _REGISTER_HEAD2.unpack(payload)


def encode_hello(flags: int) -> bytes:
    return _HELLO_HEAD.pack(flags & 0xFF)


def decode_hello(payload: bytes) -> int:
    if len(payload) != _HELLO_HEAD.size:
        raise FrameError("hello frame has the wrong size")
    return _HELLO_HEAD.unpack(payload)[0]


def encode_refresh(generation: int, partitions: int = 0) -> bytes:
    """Refresh control / ack. `partitions` > 0 ships the extended form
    carrying the fleet's partition-split width (elastic re-splits,
    docs/SCALING.md "Scale-out tier"); 0 keeps the legacy 8-byte frame a
    pre-elastic peer understands — the same mixed-fleet dual-size
    pattern REGISTER uses."""
    if partitions > 0:
        return _REFRESH_HEAD2.pack(int(generation), int(partitions))
    return _REFRESH_HEAD.pack(int(generation))


def decode_refresh(payload: bytes) -> Tuple[int, int]:
    """-> (generation, partitions). Accepts the legacy 8-byte form
    (partitions reported as 0 = unspecified, keep the current split) and
    the extended 12-byte form."""
    if len(payload) == _REFRESH_HEAD.size:
        return _REFRESH_HEAD.unpack(payload)[0], 0
    if len(payload) == _REFRESH_HEAD2.size:
        gen, parts = _REFRESH_HEAD2.unpack(payload)
        return gen, parts
    raise FrameError("refresh frame has the wrong size")


# -- fleet result cache (docs/SERVING.md "Result cache") --------------------

@dataclass(frozen=True)
class CacheKey:
    """The generation-qualified result-cache key as it travels the wire:
    identical to the service-side key tuple, so a LOOKUP on front end B
    addresses exactly the entry a PUT from front end A created."""
    req_id: int
    k: int
    nprobe: int               # 0 = the server default
    store_gen: int
    index_gen: int            # -1 = the view serves without an index
    query: str                # whitespace-normalized text


def _encode_cache_key(req_id: int, query: str, k: int, nprobe: int,
                      store_gen: int, index_gen: int) -> bytes:
    raw = query.encode("utf-8")
    if len(raw) > 0xFFFF:
        raise ValueError("cache-key query text exceeds 65535 utf-8 bytes")
    return _CACHE_HEAD.pack(req_id, int(k), int(nprobe), int(store_gen),
                            int(index_gen), len(raw)) + raw


def _decode_cache_key(payload: bytes, what: str) -> Tuple[CacheKey, int]:
    """-> (key, offset past the key); FrameError on truncation."""
    if len(payload) < _CACHE_HEAD.size:
        raise FrameError(f"{what} frame shorter than its fixed header")
    req_id, k, nprobe, sgen, igen, ln = _CACHE_HEAD.unpack_from(payload)
    off = _CACHE_HEAD.size
    if off + ln > len(payload):
        raise FrameError(f"{what} frame truncated inside the query text")
    try:
        query = payload[off: off + ln].decode("utf-8")
    except UnicodeDecodeError as e:
        raise FrameError(f"{what} query text is not utf-8: {e}") from None
    return CacheKey(req_id, k, nprobe, sgen, igen, query), off + ln


def encode_cache_lookup(req_id: int, query: str, k: int, nprobe: int,
                        store_gen: int, index_gen: int) -> bytes:
    return _encode_cache_key(req_id, query, k, nprobe, store_gen, index_gen)


def decode_cache_lookup(payload: bytes) -> CacheKey:
    key, off = _decode_cache_key(payload, "cache lookup")
    if off != len(payload):
        raise FrameError(f"{len(payload) - off} trailing bytes after a "
                         "cache-lookup key")
    return key


def encode_cache_put(req_id: int, query: str, k: int, nprobe: int,
                     store_gen: int, index_gen: int, scores: np.ndarray,
                     ids: np.ndarray) -> bytes:
    """One computed result row for the key: scores [k] f32 + ids [k] i64,
    -1-id padded past the real hit count (the same wire convention as a
    RESULT frame)."""
    scores = np.ascontiguousarray(scores, dtype="<f4").reshape(-1)
    ids = np.ascontiguousarray(ids, dtype="<i8").reshape(-1)
    if scores.shape[0] != k or ids.shape[0] != k:
        raise ValueError(f"cache-put row must be [{k}] scores + [{k}] ids, "
                         f"got {scores.shape[0]}/{ids.shape[0]}")
    return (_encode_cache_key(req_id, query, k, nprobe, store_gen,
                              index_gen) + scores.tobytes() + ids.tobytes())


def decode_cache_put(payload: bytes
                     ) -> Tuple[CacheKey, np.ndarray, np.ndarray]:
    """-> (key, scores [k] f32, ids [k] i64); the arrays alias the
    payload (zero-copy). Truncated or trailing bytes REJECT."""
    key, off = _decode_cache_key(payload, "cache put")
    if key.k <= 0:
        raise FrameError(f"cache-put k {key.k} must be positive")
    want = key.k * (4 + 8)
    if len(payload) - off != want:
        raise FrameError(f"cache-put row carries {len(payload) - off} "
                         f"bytes for k={key.k} ({want} expected)")
    scores = np.frombuffer(payload, dtype="<f4", count=key.k, offset=off)
    ids = np.frombuffer(payload, dtype="<i8", count=key.k,
                        offset=off + key.k * 4)
    return key, scores, ids


# ---------------------------------------------------------------------------
# framing over sync sockets (partition RPC hop, client library)
# ---------------------------------------------------------------------------

def _check_header(hdr: bytes) -> Tuple[int, int]:
    magic, ftype, length = HEADER.unpack(hdr)
    if magic != MAGIC:
        raise FrameError(f"bad magic 0x{magic:08x} (not a DPV1 peer)")
    if ftype not in _TYPES:
        raise FrameError(f"unknown frame type {ftype}")
    if length > MAX_FRAME:
        raise FrameError(f"frame length {length} exceeds MAX_FRAME "
                         f"{MAX_FRAME}")
    return ftype, length


def pack_frame(ftype: int, payload: bytes = b"") -> bytes:
    return HEADER.pack(MAGIC, ftype, len(payload)) + payload


def read_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    """Read exactly n bytes; None on clean EOF BEFORE the first byte,
    FrameError on EOF mid-read (a torn frame)."""
    if n == 0:
        return b""
    chunks: List[bytes] = []
    got = 0
    while got < n:
        chunk = sock.recv(n - got)
        if not chunk:
            if got == 0:
                return None
            raise FrameError(f"stream truncated: EOF after {got}/{n} bytes")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def _close_quietly(sock: socket.socket) -> None:
    # shutdown BEFORE close: close() alone does not release the kernel
    # socket while a peer thread is blocked in recv() on the same fd, so
    # no FIN reaches either side and the "dropped" connection lingers as
    # a zombie until the next send; shutdown() wakes blocked readers and
    # tears the stream immediately — which is what a dropped connection
    # means
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


def _wire_fault_send(sock: socket.socket, view, op: str) -> bool:
    """Consult the active fault plan before a framed send (docs/
    ROBUSTNESS.md "Network failure model"); ~free with no plan installed.
    Returns True when the fault itself performed the send (frame_dup);
    the stream-tearing kinds close the socket and raise InjectedFault so
    callers' existing OSError recovery paths fire unmodified."""
    plan = faults.active()
    spec = plan.wire(op)
    if spec is None:
        return False
    kind = spec.kind
    if kind in ("delay", "frame_delay"):
        time.sleep(plan.wire_delay_s())
        return False                      # stalled; frame still ships
    if kind == "frame_dup":
        sock.sendall(view)
        sock.sendall(view)                # the receiver sees a retransmit
        return True
    if kind == "frame_trunc":
        try:
            sock.sendall(view[:max(1, len(view) // 2)])
        except OSError:
            pass
        _close_quietly(sock)
        raise InjectedFault(f"injected fault: {op} frame_trunc")
    # conn_drop / io_error: the stream dies before any byte of this frame
    _close_quietly(sock)
    raise InjectedFault(f"injected fault: {op} {kind}")


def _wire_fault_recv(sock: socket.socket, op: str) -> None:
    """Recv twin of _wire_fault_send, fired as a framed read starts.
    Delay kinds stall the reader; every other wire kind tears the stream
    under it (the receiver cannot truncate or duplicate what the peer
    sends, so frame_trunc/frame_dup degenerate to conn_drop here)."""
    plan = faults.active()
    spec = plan.wire(op)
    if spec is None:
        return
    if spec.kind in ("delay", "frame_delay"):
        time.sleep(plan.wire_delay_s())
        return
    _close_quietly(sock)
    raise InjectedFault(f"injected fault: {op} {spec.kind}")


def read_frame(sock: socket.socket,
               op: str = "wire_recv") -> Optional[Tuple[int, bytes]]:
    """-> (type, payload), or None on clean EOF at a frame boundary.
    Garbage/oversize headers and truncation raise FrameError."""
    _wire_fault_recv(sock, op)
    hdr = read_exact(sock, HEADER.size)
    if hdr is None:
        return None
    ftype, length = _check_header(hdr)
    payload = read_exact(sock, length)
    if payload is None:
        raise FrameError("stream truncated between header and payload")
    return ftype, payload


def write_frame(sock: socket.socket, ftype: int, payload: bytes = b"",
                counter=None, op: str = "wire_send") -> int:
    """Send one frame; returns the wire bytes written (header included).
    `counter` (a telemetry Counter) accumulates wire-byte accounting."""
    frame = pack_frame(ftype, payload)
    if not _wire_fault_send(sock, frame, op):
        sock.sendall(frame)
    if counter is not None:
        counter.inc(len(frame))
    return len(frame)


def _byte_view(part) -> memoryview:
    """Any bytes-like (incl. a contiguous np array) -> a flat byte view
    with a correct len() — no tobytes() copy on the encode path."""
    if isinstance(part, np.ndarray):
        return memoryview(np.ascontiguousarray(part)).cast("B")
    return memoryview(part)


class FrameSender:
    """Per-connection reused encode buffer: the frame — header plus
    payload parts — is assembled in ONE resident bytearray and shipped
    with ONE coalesced sendall, so the hot send path stops allocating
    and concatenating per frame (the old pack_frame built the payload
    from joined parts, then concatenated the header on top: two fresh
    allocations and two copies per RESULT). NOT thread-safe — every
    caller already serializes its connection writes (the worker/gateway
    wlock, the client's thread-local connection)."""

    def __init__(self, sock: socket.socket):
        self.sock = sock
        # owned by the connection's single writer (see class docstring)
        self._buf = bytearray(8192)

    def send(self, ftype: int, *parts, counter=None, raw_counter=None,
             raw_len: Optional[int] = None, op: str = "wire_send") -> int:
        """Assemble + send one frame; returns wire bytes written.
        `raw_len` is the raw-equivalent frame size for compression
        accounting (defaults to the actual size — uncompressed frames
        count 1:1). `op` names the fault-injection point this send fires
        (docs/ROBUSTNESS.md "Network failure model")."""
        views = [_byte_view(p) for p in parts]
        total = HEADER.size + sum(len(v) for v in views)
        buf = self._buf
        if len(buf) < total:
            buf = self._buf = bytearray(total)
        HEADER.pack_into(buf, 0, MAGIC, ftype, total - HEADER.size)
        off = HEADER.size
        for v in views:
            buf[off: off + len(v)] = v
            off += len(v)
        frame = memoryview(buf)[:total]
        if not _wire_fault_send(self.sock, frame, op):
            self.sock.sendall(frame)
        if counter is not None:
            counter.inc(total)
        if raw_counter is not None:
            raw_counter.inc(total if raw_len is None else raw_len)
        return total


class InternTable:
    """SENDER side of the per-connection query-block interning: block
    bytes -> slot id, with a deterministic ring over WIRE_SLOTS slots.
    The sender alone decides slot reuse (the receiver's table is a
    passive slot -> bytes store that PUT overwrites), so eviction can
    never desynchronize the two ends. NOT thread-safe — owned by the
    connection's writer."""

    def __init__(self, cap: int = WIRE_SLOTS):
        self._cap = int(cap)
        self._by_key: Dict[bytes, int] = {}
        self._keys: List[Optional[bytes]] = [None] * self._cap
        self._next = 0

    def slot_for(self, key: bytes) -> Tuple[int, bool]:
        """-> (slot, fresh): fresh means the block must ride this frame
        (a PUT); a stale slot's previous occupant is forgotten here the
        same instant the receiver's PUT overwrites it there."""
        slot = self._by_key.get(key)
        if slot is not None:
            return slot, False
        slot = self._next
        self._next = (self._next + 1) % self._cap
        old = self._keys[slot]
        if old is not None:
            del self._by_key[old]
        self._keys[slot] = key
        self._by_key[key] = slot
        return slot, True


# ---------------------------------------------------------------------------
# framing over asyncio streams (the front-end server)
# ---------------------------------------------------------------------------

async def read_frame_async(reader: asyncio.StreamReader,
                           op: str = "wire_recv"
                           ) -> Optional[Tuple[int, bytes]]:
    """Asyncio twin of read_frame: (type, payload), None on clean EOF,
    FrameError on garbage/oversize/truncation. Injected wire faults
    surface as FrameError here (no socket handle to drop; the server's
    torn-frame path closes the connection for us)."""
    spec = faults.active().wire(op)
    if spec is not None:
        if spec.kind in ("delay", "frame_delay"):
            await asyncio.sleep(faults.active().wire_delay_s())
        else:
            raise FrameError(f"injected fault: {op} {spec.kind}")
    try:
        hdr = await reader.readexactly(HEADER.size)
    except asyncio.IncompleteReadError as e:
        if not e.partial:
            return None
        raise FrameError(
            f"stream truncated inside a header ({len(e.partial)}/"
            f"{HEADER.size} bytes)") from None
    ftype, length = _check_header(hdr)
    try:
        payload = await reader.readexactly(length)
    except asyncio.IncompleteReadError as e:
        raise FrameError(f"stream truncated: EOF after {len(e.partial)}/"
                         f"{length} payload bytes") from None
    return ftype, payload


# ---------------------------------------------------------------------------
# the client library (loadgen socket mode, cli loadtest --transport socket)
# ---------------------------------------------------------------------------

class SocketSearchClient:
    """Blocking client for the front-end protocol. Thread-safe the same
    way the loadgen driver is threaded: each calling thread gets its own
    connection (thread-local), so concurrent trial workers never
    interleave frames on one socket. `search()` mirrors
    `SearchService.search`'s signature, so `loadgen/driver.py:run_trial`
    can point its issue loop at a client unchanged.

    With `compress` (the default) each fresh connection leads with a
    T_HELLO advertising FLAG_WIRE_COMPRESS; the server answers with the
    agreed intersection. On a compressing connection, repeated query
    blocks intern into per-connection slots (PUT once, 2-byte REF after)
    and results arrive as T_RESULT_C — both lossless. A server that does
    not answer the HELLO (a pre-compression peer closes on the unknown
    frame) is remembered and the client reconnects raw.

    With `result_cache` the HELLO also advertises FLAG_RESULT_CACHE;
    once the server confirms, `cache_lookup()` probes its result cache
    and `cache_put()` shares a computed row into it — the peering calls
    the fleet cache rides on (docs/SERVING.md "Result cache"). Against a
    server that does not confirm the flag, both degrade to no-ops.

    With `filters` (the default) the HELLO also advertises FLAG_FILTERS:
    once the server confirms, `search`/`search_raw`/`topk_vectors`
    accept a `filters` predicate that rides the frame's optional
    trailing field. Passing a predicate to a server that never
    confirmed the flag raises RemoteError — the client refuses to
    silently serve unfiltered results for a filtered request."""

    def __init__(self, host: str, port: int, deadline_ms: float = 0.0,
                 timeout_s: float = 30.0, compress: bool = True,
                 result_cache: bool = False, filters: bool = True):
        self.host = host
        self.port = int(port)
        self.deadline_ms = float(deadline_ms)
        self.timeout_s = float(timeout_s)
        self.compress = bool(compress)
        self.result_cache = bool(result_cache)
        self.filters = bool(filters)
        self._local = threading.local()
        self._lock = threading.Lock()
        self._conns: List[socket.socket] = []   # guarded-by: _lock
        self._legacy_server = False             # guarded-by: _lock

    def _conn(self):
        """-> (sock, sender, flags, intern): this thread's connection
        state, dialing + negotiating on first use."""
        sock = getattr(self._local, "sock", None)
        if sock is not None:
            return (sock, self._local.sender, self._local.flags,
                    self._local.intern)
        sock = socket.create_connection((self.host, self.port),
                                        timeout=self.timeout_s)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sender = FrameSender(sock)
        flags = 0
        want = ((FLAG_WIRE_COMPRESS if self.compress else 0)
                | (FLAG_RESULT_CACHE if self.result_cache else 0)
                | (FLAG_FILTERS if self.filters else 0))
        with self._lock:
            attempt_hello = bool(want) and not self._legacy_server
        if attempt_hello:
            try:
                sender.send(T_HELLO, encode_hello(want))
                frame = read_frame(sock)
            except (OSError, FrameError):
                frame = None
            if frame is not None and frame[0] == T_HELLO:
                flags = decode_hello(frame[1])
            else:
                # a pre-compression server errors/closes on T_HELLO:
                # remember and redial raw so every later connection
                # skips the doomed handshake
                with self._lock:
                    self._legacy_server = True
                try:
                    sock.close()
                except OSError:
                    pass
                sock = socket.create_connection((self.host, self.port),
                                                timeout=self.timeout_s)
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                sender = FrameSender(sock)
        self._local.sock = sock
        self._local.sender = sender
        self._local.flags = flags
        self._local.intern = InternTable()
        with self._lock:
            self._conns.append(sock)
        return sock, sender, flags, self._local.intern

    def _roundtrip(self, ftype: int, parts: Tuple, req_id: int,
                   op: str = "wire_send"
                   ) -> Tuple[np.ndarray, np.ndarray, int]:
        sock, sender, _, _ = self._conn()
        try:
            sender.send(ftype, *parts, op=op)
            frame = read_frame(sock)
        except (OSError, FrameError):
            # a broken connection must not poison the thread's next call
            self._drop_local()
            raise
        if frame is None:
            self._drop_local()
            raise RemoteError("server closed the connection mid-request")
        rtype, body = frame
        if rtype in (T_RESULT, T_RESULT_C):
            rid, scores, ids, scan = decode_result_any(rtype, body)
            if rid != req_id:
                self._drop_local()
                raise RemoteError(f"response for request {rid} arrived on "
                                  f"request {req_id}'s connection")
            return scores, ids, scan
        if rtype == T_SHED:
            _, code, reason = decode_shed(body)
            raise DeadlineExceeded(reason or f"shed (code {code})")
        if rtype == T_ERROR:
            _, msg = decode_error(body)
            raise RemoteError(msg)
        self._drop_local()
        raise FrameError(f"unexpected frame type {rtype} in response")

    def _drop_local(self) -> None:
        sock = getattr(self._local, "sock", None)
        if sock is not None:
            self._local.sock = None
            self._local.sender = None
            self._local.flags = 0
            self._local.intern = None
            try:
                sock.close()
            except OSError:
                pass

    def _filters_text(self, filters) -> Optional[str]:
        """Normalize a filters argument (None / canonical text / a
        compiled Predicate) and enforce negotiation: a predicate for a
        server that never confirmed FLAG_FILTERS REJECTS here — shipped
        unfiltered frames would serve WRONG results silently."""
        text = getattr(filters, "text", filters)
        if text is None or text == "":
            return None
        _, _, flags, _ = self._conn()
        if not flags & FLAG_FILTERS:
            raise RemoteError("server did not negotiate filtered queries "
                              "(FLAG_FILTERS)")
        return str(text)

    def search(self, query: str, k: Optional[int] = None,
               nprobe: Optional[int] = None,
               deadline_ms: Optional[float] = None,
               filters=None) -> List[Dict]:
        """One text query over the wire -> the same [{page_id, score}]
        shape a local `SearchService.search` returns (snippets stay
        server-side; the wire carries scores/ids)."""
        scores, ids, _ = self.search_raw([query], k=k, nprobe=nprobe,
                                         deadline_ms=deadline_ms,
                                         filters=filters)
        return [{"page_id": int(i), "score": float(s)}
                for s, i in zip(scores[0], ids[0]) if i >= 0]

    def search_raw(self, queries: Sequence[str], k: Optional[int] = None,
                   nprobe: Optional[int] = None,
                   deadline_ms: Optional[float] = None,
                   filters=None) -> Tuple[np.ndarray, np.ndarray, int]:
        req_id = next_request_id()
        dl = self.deadline_ms if deadline_ms is None else float(deadline_ms)
        payload = encode_query(req_id, list(queries), k=k or 0,
                               nprobe=nprobe or 0, deadline_ms=dl,
                               filters=self._filters_text(filters))
        return self._roundtrip(T_QUERY, (payload,), req_id)

    def topk_vectors(self, qv: np.ndarray, k: Optional[int] = None,
                     nprobe: Optional[int] = None,
                     deadline_ms: Optional[float] = None,
                     filters=None) -> Tuple[np.ndarray, np.ndarray, int]:
        """Raw vector retrieval over the wire (the model-free twin of
        `SearchService.topk_vectors`): (scores, ids, scan_bytes). On a
        compressing connection the query block interns — a repeated
        block ships once and costs a 2-byte slot reference after; the
        filter field rides per request, never with the interned block."""
        req_id = next_request_id()
        dl = self.deadline_ms if deadline_ms is None else float(deadline_ms)
        ftext = self._filters_text(filters)
        block = np.ascontiguousarray(qv, dtype="<f4")
        if block.ndim != 2 or not 0 < block.shape[0] <= 0xFFFF \
                or not 0 < block.shape[1] <= 0xFFFF:
            raise ValueError(f"query block must be [1..65535, 1..65535], "
                             f"got {block.shape}")
        n, dim = block.shape
        _, _, flags, intern = self._conn()
        if flags & FLAG_WIRE_COMPRESS:
            key = block.tobytes()
            slot, fresh = intern.slot_for(key)
            head = _VQUERY_HEAD.pack(req_id, dl, int(k or 0),
                                     int(nprobe or 0), n, dim)
            tail = _filters_field(ftext)
            if fresh:
                parts = (head, _SLOT.pack(slot), key, tail)
                return self._roundtrip(T_VQUERY_PUT, parts, req_id)
            return self._roundtrip(T_VQUERY_REF,
                                   (head, _SLOT.pack(slot), tail), req_id)
        payload = encode_vquery(req_id, block, k=k or 0, nprobe=nprobe or 0,
                                deadline_ms=dl, filters=ftext)
        return self._roundtrip(T_VQUERY, (payload,), req_id)

    # -- fleet result-cache peering (docs/SERVING.md "Result cache") -------
    def cache_lookup(self, query: str, k: int, nprobe: int,
                     store_gen: int, index_gen: int
                     ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """Probe the server's result cache for the generation-qualified
        key: (scores [1, k], ids [1, k]) on a hit, None on a miss or on
        a connection that never negotiated FLAG_RESULT_CACHE. A probe
        never admits, queues, or computes anything server-side."""
        _, _, flags, _ = self._conn()
        if not flags & FLAG_RESULT_CACHE:
            return None
        req_id = next_request_id()
        payload = encode_cache_lookup(req_id, query, k=k, nprobe=nprobe,
                                      store_gen=store_gen,
                                      index_gen=index_gen)
        try:
            scores, ids, _ = self._roundtrip(T_CACHE_LOOKUP, (payload,),
                                             req_id, op="cache_peer_send")
        except DeadlineExceeded:
            return None           # SHED_CACHE_MISS: a miss, not a shed
        return scores, ids

    def cache_put(self, query: str, k: int, nprobe: int, store_gen: int,
                  index_gen: int, scores: np.ndarray,
                  ids: np.ndarray) -> bool:
        """Share one computed [k] result row into the server's cache.
        Fire-and-forget: no response frame rides back (the server
        validates the generations and silently drops a stale entry), so
        the call costs one send on the ordered connection. True when the
        frame left; False when the flag was never negotiated or the
        connection broke (the entry just doesn't share — never an
        error)."""
        sock, sender, flags, _ = self._conn()
        if not flags & FLAG_RESULT_CACHE:
            return False
        payload = encode_cache_put(next_request_id(), query, k=k,
                                   nprobe=nprobe, store_gen=store_gen,
                                   index_gen=index_gen, scores=scores,
                                   ids=ids)
        try:
            sender.send(T_CACHE_PUT, payload, op="cache_peer_send")
        except OSError:
            self._drop_local()
            return False
        return True

    def close(self) -> None:
        with self._lock:
            conns, self._conns = self._conns, []
        for sock in conns:
            try:
                sock.close()
            except OSError:
                pass
