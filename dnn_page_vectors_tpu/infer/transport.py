"""Over-the-wire serving protocol (docs/SERVING.md "Network front end").

One compact length-prefixed binary framing for every socket in the
serving fleet — the client edge (`infer/server.py`, text queries in,
scores/ids out) and the partition RPC hop (`infer/partition_host.py`,
query vectors fanned out to partition workers). Binary because the hot
payloads ARE arrays (a [n, D] fp32 query block out, [n, k] fp32 scores +
[n, k] int64 ids back): raw little-endian array bytes round-trip exactly,
so over-the-wire results can be pinned BYTE-identical to the in-process
scatter-gather, and a query costs tens of bytes of framing instead of a
JSON re-encode of its vectors.

Frame layout (9-byte header, network byte order):

    +--------+--------+----------------+=================+
    | magic  | type   | payload length |  payload bytes  |
    | u32    | u8     | u32            |  (type-specific)|
    +--------+--------+----------------+=================+

`magic` (0x44505631, "DPV1") carries the protocol version; a reader that
sees anything else is talking to the wrong peer (or a corrupted stream)
and must REJECT — close the connection — rather than resynchronize.
`payload length` is bounded by MAX_FRAME (64 MiB): an oversize length is
rejected BEFORE any payload read, so a garbage header can never park a
connection in a multi-gigabyte recv. Truncation (EOF mid-frame) raises
`FrameError` — torn responses are indistinguishable from a crashed peer
and are treated exactly like one (docs/ROBUSTNESS.md).

Message types:

    T_QUERY      client -> front end: text queries + k/nprobe/deadline
    T_VQUERY     front end -> partition worker (and vector-mode clients):
                 an fp32 query block + k/nprobe/deadline
    T_RESULT     scores [n, k] f32 + page ids [n, k] i64 + scan bytes
    T_SHED       admission rejection (deadline/SLO budget) — NOT an error
    T_ERROR      server-side failure, message attached
    T_REGISTER   partition worker hello: (partition, replica, pid)
    T_HEARTBEAT  worker liveness tick (empty payload)
    T_BYE        clean worker deregistration (empty payload)

Deadlines travel as RELATIVE remaining milliseconds (not absolute
timestamps): the two ends of a socket do not share a clock, and a
relative budget re-anchors on the receiver's own monotonic clock at
receipt — clock skew costs at most the in-flight network time.
"""
from __future__ import annotations

import asyncio
import itertools
import socket
import struct
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

MAGIC = 0x44505631            # "DPV1": protocol id + version in one word
MAX_FRAME = 64 * 2 ** 20      # reject oversize lengths before any recv

HEADER = struct.Struct("!IBI")            # magic, type, payload length

T_QUERY = 1
T_VQUERY = 2
T_RESULT = 3
T_SHED = 4
T_ERROR = 5
T_REGISTER = 6
T_HEARTBEAT = 7
T_BYE = 8

_TYPES = {T_QUERY, T_VQUERY, T_RESULT, T_SHED, T_ERROR, T_REGISTER,
          T_HEARTBEAT, T_BYE}

# shed reason codes (T_SHED payload)
SHED_DEADLINE = 1             # deadline expired / cannot be met
SHED_QUEUE = 2                # admission queue budget exceeded

_QUERY_HEAD = struct.Struct("!QdiiH")     # req id, deadline ms, k, nprobe, nq
_VQUERY_HEAD = struct.Struct("!QdiiHH")   # ... + n, dim
_RESULT_HEAD = struct.Struct("!QQHH")     # req id, scan bytes, n, k
_SHED_HEAD = struct.Struct("!QB")         # req id, reason code
_ERROR_HEAD = struct.Struct("!Q")         # req id
_REGISTER_HEAD = struct.Struct("!IIQ")    # partition, replica, pid

_REQ_IDS = itertools.count(1)


def next_request_id() -> int:
    return next(_REQ_IDS)


class FrameError(ValueError):
    """The stream is not speaking this protocol (bad magic/type), the
    frame is oversize, or it was truncated mid-read. The only safe
    response is to reject: answer nothing further and close."""


class DeadlineExceeded(RuntimeError):
    """A request was shed at admission (or at the micro-batch door)
    because its deadline had expired or could not be met. A shed is a
    deliberate availability decision, not a server error — it counts in
    `serve.deadline_shed`, never in `serve.errors`."""


class RemoteError(RuntimeError):
    """The remote end answered T_ERROR: the failure happened there."""


# ---------------------------------------------------------------------------
# payload codecs (pure functions of bytes — the fuzz-test surface)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class QueryRequest:
    req_id: int
    deadline_ms: float            # remaining budget; <= 0 means none
    k: int                        # 0 means the server default
    nprobe: int                   # 0 means the server default
    queries: Tuple[str, ...]


@dataclass(frozen=True)
class VectorRequest:
    req_id: int
    deadline_ms: float
    k: int
    nprobe: int
    qv: np.ndarray                # [n, dim] float32


def encode_query(req_id: int, queries: Sequence[str], k: int = 0,
                 nprobe: int = 0, deadline_ms: float = 0.0) -> bytes:
    if not 0 < len(queries) <= 0xFFFF:
        raise ValueError(f"1..65535 queries per frame, got {len(queries)}")
    parts = [_QUERY_HEAD.pack(req_id, float(deadline_ms), int(k),
                              int(nprobe), len(queries))]
    for q in queries:
        raw = q.encode("utf-8")
        if len(raw) > 0xFFFF:
            raise ValueError("query text exceeds 65535 utf-8 bytes")
        parts.append(struct.pack("!H", len(raw)))
        parts.append(raw)
    return b"".join(parts)


def decode_query(payload: bytes) -> QueryRequest:
    if len(payload) < _QUERY_HEAD.size:
        raise FrameError("query frame shorter than its fixed header")
    req_id, deadline_ms, k, nprobe, nq = _QUERY_HEAD.unpack_from(payload)
    off = _QUERY_HEAD.size
    queries: List[str] = []
    for _ in range(nq):
        if off + 2 > len(payload):
            raise FrameError("query frame truncated inside a length prefix")
        (ln,) = struct.unpack_from("!H", payload, off)
        off += 2
        if off + ln > len(payload):
            raise FrameError("query frame truncated inside a query string")
        try:
            queries.append(payload[off: off + ln].decode("utf-8"))
        except UnicodeDecodeError as e:
            raise FrameError(f"query text is not utf-8: {e}") from None
        off += ln
    if off != len(payload):
        raise FrameError(f"{len(payload) - off} trailing bytes after the "
                         "last query")
    return QueryRequest(req_id, deadline_ms, k, nprobe, tuple(queries))


def encode_vquery(req_id: int, qv: np.ndarray, k: int = 0, nprobe: int = 0,
                  deadline_ms: float = 0.0) -> bytes:
    qv = np.ascontiguousarray(qv, dtype="<f4")
    if qv.ndim != 2 or not 0 < qv.shape[0] <= 0xFFFF \
            or not 0 < qv.shape[1] <= 0xFFFF:
        raise ValueError(f"query block must be [1..65535, 1..65535], "
                         f"got {qv.shape}")
    return (_VQUERY_HEAD.pack(req_id, float(deadline_ms), int(k),
                              int(nprobe), qv.shape[0], qv.shape[1])
            + qv.tobytes())


def decode_vquery(payload: bytes) -> VectorRequest:
    if len(payload) < _VQUERY_HEAD.size:
        raise FrameError("vquery frame shorter than its fixed header")
    req_id, deadline_ms, k, nprobe, n, dim = _VQUERY_HEAD.unpack_from(payload)
    body = payload[_VQUERY_HEAD.size:]
    want = n * dim * 4
    if len(body) != want:
        raise FrameError(f"vquery block carries {len(body)} bytes for a "
                         f"[{n}, {dim}] f32 matrix ({want} expected)")
    if n == 0 or dim == 0:
        raise FrameError("vquery block is empty")
    qv = np.frombuffer(body, dtype="<f4").reshape(n, dim).astype(
        np.float32, copy=True)
    return VectorRequest(req_id, deadline_ms, k, nprobe, qv)


def encode_result(req_id: int, scores: np.ndarray, ids: np.ndarray,
                  scan_bytes: int = 0) -> bytes:
    scores = np.ascontiguousarray(scores, dtype="<f4")
    ids = np.ascontiguousarray(ids, dtype="<i8")
    if scores.shape != ids.shape or scores.ndim != 2:
        raise ValueError(f"scores {scores.shape} / ids {ids.shape} must be "
                         "matching [n, k]")
    n, k = scores.shape
    return (_RESULT_HEAD.pack(req_id, int(scan_bytes), n, k)
            + scores.tobytes() + ids.tobytes())


def decode_result(payload: bytes
                  ) -> Tuple[int, np.ndarray, np.ndarray, int]:
    """-> (req_id, scores [n, k] f32, ids [n, k] i64, scan_bytes)."""
    if len(payload) < _RESULT_HEAD.size:
        raise FrameError("result frame shorter than its fixed header")
    req_id, scan_bytes, n, k = _RESULT_HEAD.unpack_from(payload)
    body = payload[_RESULT_HEAD.size:]
    want = n * k * (4 + 8)
    if len(body) != want:
        raise FrameError(f"result block carries {len(body)} bytes for "
                         f"[{n}, {k}] scores+ids ({want} expected)")
    cut = n * k * 4
    scores = np.frombuffer(body[:cut], dtype="<f4").reshape(n, k).astype(
        np.float32, copy=True)
    ids = np.frombuffer(body[cut:], dtype="<i8").reshape(n, k).astype(
        np.int64, copy=True)
    return req_id, scores, ids, int(scan_bytes)


def encode_shed(req_id: int, code: int, reason: str) -> bytes:
    return _SHED_HEAD.pack(req_id, code) + reason.encode("utf-8")[:512]


def decode_shed(payload: bytes) -> Tuple[int, int, str]:
    if len(payload) < _SHED_HEAD.size:
        raise FrameError("shed frame shorter than its fixed header")
    req_id, code = _SHED_HEAD.unpack_from(payload)
    return req_id, code, payload[_SHED_HEAD.size:].decode(
        "utf-8", errors="replace")


def encode_error(req_id: int, message: str) -> bytes:
    return _ERROR_HEAD.pack(req_id) + message.encode("utf-8")[:2048]


def decode_error(payload: bytes) -> Tuple[int, str]:
    if len(payload) < _ERROR_HEAD.size:
        raise FrameError("error frame shorter than its fixed header")
    (req_id,) = _ERROR_HEAD.unpack_from(payload)
    return req_id, payload[_ERROR_HEAD.size:].decode(
        "utf-8", errors="replace")


def encode_register(partition: int, replica: int, pid: int) -> bytes:
    return _REGISTER_HEAD.pack(partition, replica, pid)


def decode_register(payload: bytes) -> Tuple[int, int, int]:
    if len(payload) != _REGISTER_HEAD.size:
        raise FrameError("register frame has the wrong size")
    return _REGISTER_HEAD.unpack(payload)


# ---------------------------------------------------------------------------
# framing over sync sockets (partition RPC hop, client library)
# ---------------------------------------------------------------------------

def _check_header(hdr: bytes) -> Tuple[int, int]:
    magic, ftype, length = HEADER.unpack(hdr)
    if magic != MAGIC:
        raise FrameError(f"bad magic 0x{magic:08x} (not a DPV1 peer)")
    if ftype not in _TYPES:
        raise FrameError(f"unknown frame type {ftype}")
    if length > MAX_FRAME:
        raise FrameError(f"frame length {length} exceeds MAX_FRAME "
                         f"{MAX_FRAME}")
    return ftype, length


def pack_frame(ftype: int, payload: bytes = b"") -> bytes:
    return HEADER.pack(MAGIC, ftype, len(payload)) + payload


def read_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    """Read exactly n bytes; None on clean EOF BEFORE the first byte,
    FrameError on EOF mid-read (a torn frame)."""
    if n == 0:
        return b""
    chunks: List[bytes] = []
    got = 0
    while got < n:
        chunk = sock.recv(n - got)
        if not chunk:
            if got == 0:
                return None
            raise FrameError(f"stream truncated: EOF after {got}/{n} bytes")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def read_frame(sock: socket.socket) -> Optional[Tuple[int, bytes]]:
    """-> (type, payload), or None on clean EOF at a frame boundary.
    Garbage/oversize headers and truncation raise FrameError."""
    hdr = read_exact(sock, HEADER.size)
    if hdr is None:
        return None
    ftype, length = _check_header(hdr)
    payload = read_exact(sock, length)
    if payload is None:
        raise FrameError("stream truncated between header and payload")
    return ftype, payload


def write_frame(sock: socket.socket, ftype: int, payload: bytes = b"",
                counter=None) -> int:
    """Send one frame; returns the wire bytes written (header included).
    `counter` (a telemetry Counter) accumulates wire-byte accounting."""
    frame = pack_frame(ftype, payload)
    sock.sendall(frame)
    if counter is not None:
        counter.inc(len(frame))
    return len(frame)


# ---------------------------------------------------------------------------
# framing over asyncio streams (the front-end server)
# ---------------------------------------------------------------------------

async def read_frame_async(reader: asyncio.StreamReader
                           ) -> Optional[Tuple[int, bytes]]:
    """Asyncio twin of read_frame: (type, payload), None on clean EOF,
    FrameError on garbage/oversize/truncation."""
    try:
        hdr = await reader.readexactly(HEADER.size)
    except asyncio.IncompleteReadError as e:
        if not e.partial:
            return None
        raise FrameError(
            f"stream truncated inside a header ({len(e.partial)}/"
            f"{HEADER.size} bytes)") from None
    ftype, length = _check_header(hdr)
    try:
        payload = await reader.readexactly(length)
    except asyncio.IncompleteReadError as e:
        raise FrameError(f"stream truncated: EOF after {len(e.partial)}/"
                         f"{length} payload bytes") from None
    return ftype, payload


# ---------------------------------------------------------------------------
# the client library (loadgen socket mode, cli loadtest --transport socket)
# ---------------------------------------------------------------------------

class SocketSearchClient:
    """Blocking client for the front-end protocol. Thread-safe the same
    way the loadgen driver is threaded: each calling thread gets its own
    connection (thread-local), so concurrent trial workers never
    interleave frames on one socket. `search()` mirrors
    `SearchService.search`'s signature, so `loadgen/driver.py:run_trial`
    can point its issue loop at a client unchanged."""

    def __init__(self, host: str, port: int, deadline_ms: float = 0.0,
                 timeout_s: float = 30.0):
        self.host = host
        self.port = int(port)
        self.deadline_ms = float(deadline_ms)
        self.timeout_s = float(timeout_s)
        self._local = threading.local()
        self._lock = threading.Lock()
        self._conns: List[socket.socket] = []   # guarded-by: _lock

    def _conn(self) -> socket.socket:
        sock = getattr(self._local, "sock", None)
        if sock is None:
            sock = socket.create_connection((self.host, self.port),
                                            timeout=self.timeout_s)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._local.sock = sock
            with self._lock:
                self._conns.append(sock)
        return sock

    def _roundtrip(self, ftype: int, payload: bytes,
                   req_id: int) -> Tuple[np.ndarray, np.ndarray, int]:
        sock = self._conn()
        try:
            write_frame(sock, ftype, payload)
            frame = read_frame(sock)
        except (OSError, FrameError):
            # a broken connection must not poison the thread's next call
            self._drop_local()
            raise
        if frame is None:
            self._drop_local()
            raise RemoteError("server closed the connection mid-request")
        rtype, body = frame
        if rtype == T_RESULT:
            rid, scores, ids, scan = decode_result(body)
            if rid != req_id:
                self._drop_local()
                raise RemoteError(f"response for request {rid} arrived on "
                                  f"request {req_id}'s connection")
            return scores, ids, scan
        if rtype == T_SHED:
            _, code, reason = decode_shed(body)
            raise DeadlineExceeded(reason or f"shed (code {code})")
        if rtype == T_ERROR:
            _, msg = decode_error(body)
            raise RemoteError(msg)
        self._drop_local()
        raise FrameError(f"unexpected frame type {rtype} in response")

    def _drop_local(self) -> None:
        sock = getattr(self._local, "sock", None)
        if sock is not None:
            self._local.sock = None
            try:
                sock.close()
            except OSError:
                pass

    def search(self, query: str, k: Optional[int] = None,
               nprobe: Optional[int] = None,
               deadline_ms: Optional[float] = None) -> List[Dict]:
        """One text query over the wire -> the same [{page_id, score}]
        shape a local `SearchService.search` returns (snippets stay
        server-side; the wire carries scores/ids)."""
        scores, ids, _ = self.search_raw([query], k=k, nprobe=nprobe,
                                         deadline_ms=deadline_ms)
        return [{"page_id": int(i), "score": float(s)}
                for s, i in zip(scores[0], ids[0]) if i >= 0]

    def search_raw(self, queries: Sequence[str], k: Optional[int] = None,
                   nprobe: Optional[int] = None,
                   deadline_ms: Optional[float] = None
                   ) -> Tuple[np.ndarray, np.ndarray, int]:
        req_id = next_request_id()
        dl = self.deadline_ms if deadline_ms is None else float(deadline_ms)
        payload = encode_query(req_id, list(queries), k=k or 0,
                               nprobe=nprobe or 0, deadline_ms=dl)
        return self._roundtrip(T_QUERY, payload, req_id)

    def topk_vectors(self, qv: np.ndarray, k: Optional[int] = None,
                     nprobe: Optional[int] = None,
                     deadline_ms: Optional[float] = None
                     ) -> Tuple[np.ndarray, np.ndarray, int]:
        """Raw vector retrieval over the wire (the model-free twin of
        `SearchService.topk_vectors`): (scores, ids, scan_bytes)."""
        req_id = next_request_id()
        dl = self.deadline_ms if deadline_ms is None else float(deadline_ms)
        payload = encode_vquery(req_id, qv, k=k or 0, nprobe=nprobe or 0,
                                deadline_ms=dl)
        return self._roundtrip(T_VQUERY, payload, req_id)

    def close(self) -> None:
        with self._lock:
            conns, self._conns = self._conns, []
        for sock in conns:
            try:
                sock.close()
            except OSError:
                pass
