"""dnn_page_vectors_tpu — a TPU-native web-page embedding framework.

Capability-parity rebuild of `collawolley/dnn_page_vectors` (reference mount
was empty at survey time; spec reconstructed in SURVEY.md from BASELINE.json):
two-tower page encoders (CDSSM char-trigram CNN, Kim-CNN, BERT-mini, mT5-base)
trained with a cosine-contrastive loss over global in-batch and ANN-mined hard
negatives, a sharded corpus->vector bulk-embed job, and Recall@10 retrieval
eval.

TPU-first design notes (vs. the reference's torch-DDP/NCCL path,
BASELINE.json:5):
  * the trainer writes *global* batch math once; GSPMD (jit + NamedSharding
    over a `jax.sharding.Mesh`) partitions it and inserts ICI collectives —
    there is no user-level all-reduce hook.
  * all hot paths are jit-compiled, static-shape, bfloat16-on-MXU.
  * host-side work (tokenization, IO) stays off the compiled path behind a
    double-buffered prefetch queue.
"""

__version__ = "0.1.0"
