"""Hard-negative mining layer (SURVEY.md §2 layer 6, §3 #21)."""
from dnn_page_vectors_tpu.mine.ann import HardNegatives, mine_hard_negatives

__all__ = ["HardNegatives", "mine_hard_negatives"]
