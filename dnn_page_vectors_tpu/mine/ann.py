"""ANN hard-negative miner (SURVEY.md §3 #21; BASELINE.json:10; call stack §4.4).

The reference mined hard negatives with an ANN index over the embedded
corpus. The TPU-native path is exact brute-force retrieval on the MXU: embed
queries with the current params, stream the vector store — one disk shard at
a time, row-sharded over the mesh 'data' axis — through the cross-shard
top-k merge (ops/topk.py:topk_over_store), drop the gold page, keep the top
H as negatives. One pass over the store total, O(one shard) memory, so
mining scales to the 100M-page corpus (BASELINE.md; VERDICT r1 #2). Mined
lists feed back into training via TrainBatcher.hard_negative_lookup (the
mine -> train loop of config 4).
"""
from __future__ import annotations

import os
from typing import Optional

import numpy as np

from dnn_page_vectors_tpu.data.toy import ToyCorpus
from dnn_page_vectors_tpu.infer.bulk_embed import BulkEmbedder
from dnn_page_vectors_tpu.infer.vector_store import VectorStore
from dnn_page_vectors_tpu.ops.topk import topk_over_store


class HardNegatives:
    """[num_queries, H] page-id table; callable for TrainBatcher."""

    def __init__(self, table: np.ndarray):
        assert table.ndim == 2
        self.table = table.astype(np.int32)

    @property
    def num_negatives(self) -> int:
        return self.table.shape[1]

    def __call__(self, gold_ids: np.ndarray) -> np.ndarray:
        if int(np.max(gold_ids)) >= self.table.shape[0]:
            raise ValueError(
                f"hard-negative table covers page ids < {self.table.shape[0]} "
                f"but batch contains id {int(np.max(gold_ids))}; mine over the "
                "full training corpus (num_queries=None) before training")
        return self.table[gold_ids]

    def save(self, path: str) -> None:
        np.save(path, self.table)

    @classmethod
    def load(cls, path: str) -> "HardNegatives":
        return cls(np.load(path))


def mine_hard_negatives(embedder: BulkEmbedder, corpus: ToyCorpus,
                        store: VectorStore, num_negatives: int = 7,
                        search_k: int = 100,
                        num_queries: Optional[int] = None) -> HardNegatives:
    """Top-`search_k` retrieval per training query minus the gold page,
    truncated to `num_negatives`. Queries are embedded with CURRENT params
    (periodic re-mining keeps negatives hard as the model improves)."""
    nq = min(num_queries or corpus.num_pages, corpus.num_pages)
    if corpus.num_pages < 2:
        raise ValueError("cannot mine negatives from a <2-page corpus")
    qvecs = embedder.embed_texts(
        [corpus.query_text(i) for i in range(nq)], tower="query")
    k = min(search_k, store.num_vectors)
    # single streaming pass over the store; queries batched inside
    _, retrieved = topk_over_store(
        np.asarray(qvecs, np.float32), store, embedder.mesh, k=k,
        query_batch=embedder.cfg.eval.embed_batch_size)
    out = np.zeros((nq, num_negatives), dtype=np.int32)
    for qi in range(nq):
        negs = [int(p) for p in retrieved[qi]
                if p != qi and p >= 0][: num_negatives]
        # tiny corpora: deterministic fillers — never the gold page,
        # unique until the corpus is exhausted, then cycled
        off = 1
        while len(negs) < num_negatives:
            cand = (qi + off) % corpus.num_pages
            if cand != qi and (cand not in negs
                               or off > corpus.num_pages):
                negs.append(cand)
            off += 1
        out[qi] = negs
    return HardNegatives(out)
