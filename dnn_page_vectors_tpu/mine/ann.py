"""ANN hard-negative miner (SURVEY.md §3 #21; BASELINE.json:10; call stack §4.4).

The reference mined hard negatives with an ANN index over the embedded
corpus. Two TPU-native retrieval paths serve that role here: exact
brute-force on the MXU — embed queries with the current params, stream the
vector store (one disk shard at a time, row-sharded over the mesh 'data'
axis) through the cross-shard top-k merge (ops/topk.py:topk_over_store) —
or, with `index=` (an IVF index, index/ivf.py, docs/ANN.md), a sublinear
top-`nprobe` posting scan with exact re-rank, so mining stops paying a
full store sweep per query block. Either way: drop the gold page, keep the
top H as negatives, O(one shard) memory, so mining scales to the 100M-page
corpus (BASELINE.md; VERDICT r1 #2). Mined lists feed back into training
via TrainBatcher.hard_negative_lookup (the mine -> train loop of config 4).
"""
from __future__ import annotations

import os
from typing import Optional

import numpy as np

from dnn_page_vectors_tpu.data.toy import ToyCorpus
from dnn_page_vectors_tpu.infer.bulk_embed import BulkEmbedder
from dnn_page_vectors_tpu.infer.vector_store import VectorStore
from dnn_page_vectors_tpu.ops.topk import topk_over_store


class HardNegatives:
    """[num_queries, H] page-id table; callable for TrainBatcher."""

    def __init__(self, table: np.ndarray):
        assert table.ndim == 2
        # keep memmap-backed tables as-is (astype would pull them into RAM)
        self.table = (table if table.dtype == np.int32
                      else table.astype(np.int32))

    @property
    def num_negatives(self) -> int:
        return self.table.shape[1]

    def __call__(self, gold_ids: np.ndarray) -> np.ndarray:
        if int(np.max(gold_ids)) >= self.table.shape[0]:
            raise ValueError(
                f"hard-negative table covers page ids < {self.table.shape[0]} "
                f"but batch contains id {int(np.max(gold_ids))}; mine over the "
                "full training corpus (num_queries=None) before training")
        return self.table[gold_ids]

    def save(self, path: str) -> None:
        """Single-process export of an in-memory table. The production
        persistence path is mine_hard_negatives(out_path=...) — it fills a
        memmap in query blocks (multi-process slice/merge, O(block) RAM);
        this helper streams the whole table through np.save and exists for
        ad-hoc copies of small tables only."""
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:        # file handle: no .npy suffixing
            np.save(f, self.table)
        os.replace(tmp, path)             # atomic: no torn tables on crash

    @classmethod
    def load(cls, path: str) -> "HardNegatives":
        # memmap: the config-4 table is ~2.8 GB (100M x 7 int32) and the
        # batcher only ever gathers [B, H] rows per step — loading it
        # resident would cost every training process the full table
        return cls(np.load(path, mmap_mode="r"))


def _pick_negatives(retrieved: np.ndarray, gold: np.ndarray,
                    num_negatives: int, num_pages: int) -> np.ndarray:
    """[B, H] negatives from [B, k] retrieval results: drop the gold page
    and -1 padding, keep score order, truncate to H. Vectorized (VERDICT r3
    Weak #3): valid-first stable argsort preserves the retrieval ranking
    without a per-query Python loop. Rows left short (store < H+1 vectors —
    toy corpora only) fall back to the deterministic filler loop: never the
    gold page, unique until the corpus is exhausted, then cycled."""
    B, k = retrieved.shape
    H = num_negatives
    out = np.full((B, H), -1, np.int64)
    m = min(k, H)
    valid = (retrieved >= 0) & (retrieved != gold[:, None])
    order = np.argsort(~valid, axis=1, kind="stable")[:, :m]
    out[:, :m] = np.where(np.take_along_axis(valid, order, axis=1),
                          np.take_along_axis(retrieved, order, axis=1), -1)
    for r in np.nonzero((out < 0).any(axis=1))[0]:
        negs = [int(p) for p in out[r] if p >= 0]
        qi, off = int(gold[r]), 1
        while len(negs) < H:
            cand = (qi + off) % num_pages
            if cand != qi and (cand not in negs or off > num_pages):
                negs.append(cand)
            off += 1
        out[r] = negs
    return out.astype(np.int32)


def mine_hard_negatives(embedder: BulkEmbedder, corpus: ToyCorpus,
                        store: VectorStore, num_negatives: int = 7,
                        search_k: int = 100,
                        num_queries: Optional[int] = None,
                        query_block: Optional[int] = None,
                        out_path: Optional[str] = None,
                        index=None,
                        nprobe: Optional[int] = None,
                        start: int = 0) -> HardNegatives:
    """Top-`search_k` retrieval per training query minus the gold page,
    truncated to `num_negatives`. Queries are embedded with CURRENT params
    (periodic re-mining keeps negatives hard as the model improves).

    The query side streams in blocks of `query_block` (VERDICT r3 Missing
    #2): embed a block, stream the store through the sharded top-k once,
    write that block's rows of the negative table — so peak host memory is
    O(query_block * search_k), independent of corpus size. The trade is one
    full store sweep per block; pick query_block as large as host RAM
    allows (default 8192 ~= 3 MB of running top-k state per 100-wide
    search). With `out_path` the table is an np.memmap filled in place, so
    even the [nq, H] result never has to fit in RAM at config-4 scale
    (100M queries, BASELINE.json:10).

    Multi-host (VERDICT r4 Weak #4): the full [nq, H] table is NEVER
    materialized in RAM or allgathered. Each process fills its OWN
    `out_path.wNNNN` memmap slice (mirroring the vector store's writer
    manifests: no shared file is ever read-modify-written), process 0
    streams the slices into the final table in query_block-sized copies
    after a barrier, and every host returns a read-only memmap over the
    merged file — peak host memory is O(query_block * max(H, search_k))
    at ANY process count. This requires a shared filesystem and `out_path`,
    the same contract the store's multi-writer embed already has.

    With `index` (an index.ivf.IVFIndex over this store), each query block
    scans only its top-`nprobe` posting lists plus an exact re-rank
    (docs/ANN.md) instead of sweeping the full store — the sublinear path
    for config-4 scale mining. Retrieval is approximate; mined negatives
    are "hard" by construction either way, and any lists the ANN misses
    are by definition the least-similar candidates.

    Incremental re-mine (`start` > 0; docs/UPDATES.md): after a corpus
    append, only the NEW queries [start, nq) are mined — against the
    GROWN store, so their negatives come from every generation — and
    spliced onto the existing table at `out_path` (required, single
    process), which keeps the mine cost proportional to the appended
    pages instead of the corpus. Re-mining the old rows against the new
    pages stays a periodic full mine, exactly like before.
    """
    from dnn_page_vectors_tpu.parallel.multihost import barrier, process_info
    nq = min(num_queries or corpus.num_pages, corpus.num_pages)
    if corpus.num_pages < 2:
        raise ValueError("cannot mine negatives from a <2-page corpus")
    H = num_negatives
    k = min(search_k, store.num_vectors)
    pi, pc = process_info()
    if pc > 1 and out_path is None:
        raise ValueError(
            "multi-process mine_hard_negatives requires out_path (the table "
            "is merged through per-writer files on the shared filesystem, "
            "like the store's multi-writer embed)")
    prev = None
    if start:
        if pc > 1:
            raise ValueError("incremental mining (start > 0) is a "
                             "single-process job")
        if out_path is None or not os.path.exists(out_path):
            raise ValueError(
                "start > 0 extends an existing mined table: pass out_path "
                "pointing at the previous mine's output")
        prev = np.load(out_path, mmap_mode="r")
        if prev.shape[0] < start or prev.shape[1] != H:
            raise ValueError(
                f"existing table {tuple(prev.shape)} at {out_path} cannot "
                f"seed start={start}, num_negatives={H}; run a full mine")
    per = -(-nq // pc)                     # contiguous equal slices
    lo, hi = (start, nq) if start else (pi * per, min(nq, (pi + 1) * per))
    qb = query_block or 8192
    if out_path is not None:
        # fill a side file, os.replace on completion: an interrupted mine
        # must never leave a complete-looking partial table at out_path (the
        # pipeline's resume check is existence-based)
        my_path = out_path + (f".w{pi:04d}" if pc > 1
                              else ".part" if start else ".tmp")
        table = np.lib.format.open_memmap(
            my_path, mode="w+", dtype=np.int32, shape=(max(hi - lo, 0), H))
    else:
        table = np.zeros((max(hi - lo, 0), H), np.int32)
    for s in range(lo, hi, qb):
        e = min(s + qb, hi)
        qvecs = embedder.embed_texts(
            [corpus.query_text(i) for i in range(s, e)], tower="query")
        if index is not None:
            _, retrieved, _ = index.search(
                np.asarray(qvecs, np.float32), k=k, nprobe=nprobe)
        else:
            _, retrieved = topk_over_store(
                np.asarray(qvecs, np.float32), store, embedder.mesh, k=k,
                query_batch=embedder.cfg.eval.embed_batch_size)
        table[s - lo: e - lo] = _pick_negatives(
            retrieved, np.arange(s, e, dtype=np.int64), H, corpus.num_pages)
    if out_path is not None:
        table.flush()
        del table
        if start:
            # splice: old rows [0, start) from the previous table, the
            # freshly mined [start, nq) from the side file — O(block)
            # copies, atomic replace, so an interrupted splice leaves the
            # previous table intact
            tmp = out_path + ".tmp"
            out = np.lib.format.open_memmap(
                tmp, mode="w+", dtype=np.int32, shape=(nq, H))
            for b in range(0, start, qb):
                out[b: min(b + qb, start)] = prev[b: min(b + qb, start)]
            part = np.load(my_path, mmap_mode="r")
            for b in range(0, nq - start, qb):
                out[start + b: start + min(b + qb, nq - start)] = \
                    part[b: min(b + qb, nq - start)]
            out.flush()
            del out, prev, part
            os.replace(tmp, out_path)
            os.remove(my_path)
        elif pc > 1:
            barrier("mine_slices_written")
            if pi == 0:
                tmp = out_path + ".tmp"
                out = np.lib.format.open_memmap(
                    tmp, mode="w+", dtype=np.int32, shape=(nq, H))
                row = 0
                for p in range(pc):
                    part = np.load(out_path + f".w{p:04d}", mmap_mode="r")
                    n = part.shape[0]
                    for b in range(0, n, qb):              # O(block) copies
                        out[row + b: row + min(b + qb, n)] = \
                            part[b: min(b + qb, n)]
                    row += n
                assert row == nq, (row, nq)
                out.flush()
                del out
                os.replace(tmp, out_path)
                for p in range(pc):
                    os.remove(out_path + f".w{p:04d}")
            barrier("mine_slices_merged")
        else:
            os.replace(out_path + ".tmp", out_path)
        table = np.load(out_path, mmap_mode="r")
    return HardNegatives(table)
